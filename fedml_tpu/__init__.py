"""fedml_tpu — a TPU-native federated-learning framework.

A ground-up JAX/XLA re-design of the capabilities of FedML (reference:
arj119/FedML). Instead of the reference's actor/observer thread machinery
(fedml_core/distributed/*), the single-host simulation path is a *compiled
program*: client states are stacked pytrees, local training is a ``vmap`` /
``shard_map`` of a jitted local update, and aggregation is a weighted
pytree reduction (``psum`` across a device mesh).

Layer map (mirrors SURVEY.md §1 of the reference):

- ``fedml_tpu.core``       — message/transport runtime, topology, robustness
  (reference: ``fedml_core/distributed``)
- ``fedml_tpu.data``       — partitioners + federated dataset loaders
  (reference: ``fedml_api/data_preprocessing``)
- ``fedml_tpu.models``     — flax model zoo
  (reference: ``fedml_api/model``)
- ``fedml_tpu.algorithms`` — FL algorithms, compiled-sim and actor-based
  (reference: ``fedml_api/{standalone,distributed}``)
- ``fedml_tpu.parallel``   — mesh construction, client/data sharding
- ``fedml_tpu.ops``        — pallas kernels for hot ops
- ``fedml_tpu.metrics``    — metric sinks, FID, KD losses
- ``fedml_tpu.experiments``— CLI entry points
"""

__version__ = "0.1.0"

from fedml_tpu import config as config
from fedml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    TrainConfig,
)

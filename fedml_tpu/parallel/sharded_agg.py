"""Mesh-sharded server aggregation: the weight update partitioned over
the client axis.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336, PAPERS.md) shards the reduce + optimizer
update across replicas and all-gathers only the final params. This
module applies that scheme to the FL *server*: the deploy actor's
aggregation pass — decompress (when the wire codec is on,
:mod:`fedml_tpu.core.compress`) -> clip -> defense-reduce -> server
optimizer step — used to run replicated on ONE device while the stacked
``[C, ...]`` client deltas are embarrassingly parallel over C. Here the
stack is laid out row-wise over a 1-D ``clients`` mesh
(:func:`fedml_tpu.parallel.mesh.make_client_mesh`) and the update runs
under ``shard_map``:

- **per-client stages shard**: decompress (scatter/dequant per row),
  the delta subtraction, and norm clipping touch only local rows;
- **the reduce crosses shards once**: ``mean``/FedNova partial sums
  meet in a ``psum``; the Krum family's ``O(C^2 D)`` pairwise gram —
  the dominant term at C=1000 — is computed in ROW BLOCKS
  (:func:`fedml_tpu.core.robust.pairwise_sq_dists_rows`), each shard
  scoring its own rows against the gathered stack, with only the
  ``[C]`` score vector all-gathered;
- **only the final params replicate**: the round's output is one
  updated :class:`~fedml_tpu.algorithms.fedavg.ServerState`.

The update body is :func:`fedml_tpu.algorithms.fedavg.server_update`
with a ``psum`` reducer — the SAME function the replicated actor path
and both sims run, so the parity contract is inherited, not re-proven:

- selection/gather rules (``median``, ``trimmed_mean``, ``krum``,
  ``multikrum``'s mask, ``fltrust``) see the identical gathered stack
  and apply identical per-row ops — **bitwise** equal to the
  replicated path;
- sum-based terms (the ``mean`` rule, FedNova, batch_stats averaging)
  reassociate across the shard boundary — parity within the same
  ~1-ulp band as PR 5's bucket padding (pinned with a tight tolerance
  in ``tests/test_compress.py``).

Cohorts that don't fill the mesh are padded to a per-mesh bucket with
PR 5's zero-weight healed rows (:func:`fedml_tpu.core.elastic
.pad_stacked`) — every rule is already mask-aware, so padding is
content-blind; with elastic buckets on, the bucket is additionally the
power-of-two one, so membership churn stays a compile-cache hit.
Executables live in a :class:`~fedml_tpu.core.elastic
.CompiledRoundCache` keyed by the mesh bucket (the cache accepts any
hashable key for executables that vary on more than shape); nothing
is donated on this path (see the constructor note —
the stacked operands alias nothing model-sized, and the threaded
actor's host-side round snapshot can zero-copy alias the state). The
buffer-donation satellite lives in the sim round, whose state and
residual have exactly one owner. Round fusion (docs/PERFORMANCE.md
"Round fusion") likewise lives in the sims — ``ShardedFedAvg`` scans
its shard_map'd round; THIS path closes rounds on the transport
barrier, so there is no multi-round program to fuse.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.compat import shard_map

from fedml_tpu.core import compress as C
from fedml_tpu.core import elastic as E
from fedml_tpu.parallel.mesh import make_client_mesh

Pytree = object


def mesh_bucket(c: int, n_shards: int, elastic: bool) -> int:
    """Rows the stacked operand is padded to: a multiple of the mesh
    (every shard gets equal rows), and with ``elastic`` the
    power-of-two bucket on top so churn stays a cache hit."""
    b = E.bucket_for(c) if elastic else c
    return ((b + n_shards - 1) // n_shards) * n_shards


class ShardedAggregator:
    """Client-axis-sharded server update for the deploy actor path
    (``FedConfig.shard_aggregation`` / ``--shard_aggregation``)."""

    def __init__(
        self,
        cfg,
        steps_per_epoch: int,
        batch_size: int,
        mesh: Mesh | None = None,
        spec: C.CompressionSpec | None = None,
        max_entries: int = 8,
    ):
        from fedml_tpu.algorithms.fedavg import psum_reducer

        self.cfg = cfg
        self.steps_per_epoch = steps_per_epoch
        self.batch_size = batch_size
        self.mesh = mesh if mesh is not None else make_client_mesh()
        self.axis = self.mesh.axis_names[0]
        self.n_shards = int(self.mesh.devices.size)
        self._elastic = bool(cfg.fed.elastic_buckets)
        self._spec = spec if spec is not None and spec.enabled() else None
        self._red = psum_reducer(self.axis)
        self._rows = NamedSharding(self.mesh, P(self.axis))
        self._rep = NamedSharding(self.mesh, P())
        # (state, stacked, w, valid, rkey): stacked rows ride sharded,
        # everything else replicated, and the new ServerState comes
        # back replicated — the "all-gather only the final params"
        # edge of the scheme. Nothing is donated here: the stacked
        # [C, ...] operands alias nothing in the model-sized output
        # (donating them would only emit unusable-donation warnings),
        # and donating the old state is unsafe in the threaded actor —
        # on the CPU backend the server's host-side round snapshot can
        # zero-copy ALIAS the state buffers a donation would let the
        # executable overwrite (the aliasing class PR 1's checkpoint
        # fix documents). The sim round, whose state has exactly one
        # owner, is where the donation satellite lives.
        self._update_cache = E.CompiledRoundCache(
            self._sharded_update,
            max_entries=max_entries,
            jit_kwargs=dict(
                in_shardings=(self._rep, self._rows, self._rows,
                              self._rows, self._rep),
                out_shardings=self._rep,
            ),
            family="sharded_agg_update",
        )
        self._decomp_cache = (
            E.CompiledRoundCache(
                self._sharded_decompress,
                max_entries=max_entries,
                jit_kwargs=dict(
                    in_shardings=(self._rows, self._rep),
                    out_shardings=self._rows,
                ),
                family="sharded_agg_decompress",
            )
            if self._spec is not None else None
        )

    # -- compiled bodies ---------------------------------------------------

    def _sharded_update(self, state, stacked_vars, n_k, valid, rkey):
        from fedml_tpu.algorithms.fedavg import server_update

        def body(state, stacked, w, v, key):
            # stacked/w/v arrive as this shard's row block; state/key
            # replicated — server_update with the psum reducer is the
            # sharded sim's exact aggregation body
            return server_update(
                self.cfg.fed,
                self.cfg.train,
                self.steps_per_epoch,
                self.batch_size,
                state,
                stacked,
                w,
                key,
                self._red,
                valid=v,
            )

        return shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis), P(self.axis),
                      P()),
            out_specs=P(),
            check_vma=False,
        )(state, stacked_vars, n_k, valid, rkey)

    def _sharded_decompress(self, stacked_payload, global_vars):
        """Stacked compressed payloads (rows sharded) -> stacked dense
        VARIABLES (rows sharded): each shard scatters/dequantizes only
        its own clients' payloads. Padded zero payload rows decompress
        to a delta of exactly zero — i.e. the healed global row."""
        spec = self._spec

        def body(payload, gvars):
            delta = C.decompress_stacked(spec, payload, gvars)
            return jax.tree.map(
                lambda g, d: (g[None] + d).astype(g.dtype), gvars, delta
            )

        return shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(self.axis), P()),
            out_specs=P(self.axis),
            check_vma=False,
        )(stacked_payload, global_vars)

    # -- host-facing API ---------------------------------------------------

    def _place_rows(self, tree):
        return jax.device_put(tree, self._rows)

    def decompress(self, stacked_payload: Pytree, global_vars: Pytree,
                   n_rows: int) -> Pytree:
        """Decompress ``n_rows`` stacked payloads into dense stacked
        variables (rows stay sharded over the mesh; callers slice off
        the padding rows)."""
        bucket = mesh_bucket(n_rows, self.n_shards, self._elastic)
        padded = C.pad_stacked_payload(stacked_payload, bucket)
        dense = self._decomp_cache(
            bucket, self._place_rows(padded),
            jax.device_put(global_vars, self._rep),
        )
        return jax.tree.map(lambda x: x[:n_rows], dense)

    def update(self, state, stacked_vars: Pytree, weights, rkey):
        """One server step over ``stacked_vars`` (``[C, ...]`` dense
        client variables), sharded over the mesh. Pads the cohort to
        the mesh bucket with zero-weight healed rows (mask-aware rules
        make the padding content-blind) and returns the new replicated
        :class:`ServerState`. The old state stays valid (nothing is
        donated — see the constructor note)."""
        c = int(np.shape(np.asarray(weights))[0])
        bucket = mesh_bucket(c, self.n_shards, self._elastic)
        padded, w, valid = E.pad_stacked(
            jax.tree.map(jnp.asarray, stacked_vars), weights,
            state.variables, bucket,
        )
        return self._update_cache(
            bucket,
            jax.device_put(state, self._rep),
            self._place_rows(padded),
            jax.device_put(w, self._rows),
            jax.device_put(valid, self._rows),
            jax.device_put(rkey, self._rep),
        )

"""Mesh-sharded FedAvg: the cohort sharded over the ``clients`` axis, each
client's batch optionally sharded over the ``data`` axis.

This is the TPU-native replacement for the reference's two distributed
layers at once:

- ``fedml_api/distributed/fedavg`` (one MPI rank per client, server rank 0,
  pickled state_dicts over ``comm.send``) -> clients become *mesh shards*;
  "upload model / aggregate / broadcast" becomes a weighted pytree ``psum``
  under ``shard_map`` — aggregation rides ICI, no server process exists.
- ``fedml_api/distributed/fedavg_cross_silo`` (DDP inside each silo over
  NCCL) -> the ``data`` mesh axis: per-batch gradient ``psum`` inside the
  compiled local update.

Weighted FedAvg identity used throughout:
``avg = psum(sum_local n_k * w_k) / psum(sum_local n_k)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core import robust, tree as T
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.algorithms.base import build_local_update, make_task, build_evaluator
from fedml_tpu.algorithms.fedavg import FedAvgSim, ServerState, make_server_optimizer
from fedml_tpu.models.base import FedModel


class ShardedFedAvg(FedAvgSim):
    """FedAvg with the round compiled over a (clients, data) mesh."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        mesh: Mesh,
    ):
        self.mesh = mesh
        self.client_axis = cfg.mesh.client_axis_name
        self.data_axis = cfg.mesh.data_axis_name
        self.n_client_shards = mesh.shape[self.client_axis]
        self.n_data_shards = mesh.shape[self.data_axis]
        assert cfg.fed.clients_per_round % self.n_client_shards == 0, (
            "clients_per_round must divide evenly over the clients mesh axis"
        )

        # FedAvgSim.__init__ builds the single-device local_update; rebuild
        # it with the data axis threaded through, then wrap the round in
        # shard_map.
        super().__init__(model, data, cfg)
        if self.n_data_shards > 1:
            self.local_update = build_local_update(
                model,
                self.task,
                cfg.train,
                self.batch_size,
                self.arrays.max_client_samples,
                data_axis=self.data_axis,
                data_axis_size=self.n_data_shards,
            )
        self._round_fn = jax.jit(self._sharded_round, donate_argnums=(0,))

    def _sharded_round(self, state: ServerState, arrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0),
            arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        idx_rows = arrays.idx[cohort]
        mask_rows = arrays.mask[cohort]

        cspec = P(self.client_axis)  # shard cohort; replicate over data axis
        rep = P()

        def shard_fn(variables, opt_state, idx_rows, mask_rows, ckeys, x, y):
            stacked_vars, n_k, msums = jax.vmap(
                self.local_update, in_axes=(None, 0, 0, None, None, 0)
            )(variables, idx_rows, mask_rows, x, y, ckeys)

            global_params = variables["params"]
            deltas = jax.tree.map(
                lambda s, g: s - g[None], stacked_vars["params"], global_params
            )
            if cfg.robust_norm_clip > 0:
                deltas = robust.clip_deltas_by_norm(
                    deltas, cfg.robust_norm_clip
                )

            n_total = jax.lax.psum(jnp.sum(n_k), self.client_axis)

            if self.cfg.fed.algorithm == "fednova":
                steps_pe = self.arrays.max_client_samples // self.batch_size
                tau = (
                    jnp.ceil(n_k / self.batch_size).clip(1, steps_pe)
                    * self.cfg.train.epochs
                )
                tau_eff = (
                    jax.lax.psum(jnp.sum(n_k * tau), self.client_axis)
                    / n_total
                )
                d = jax.tree.map(
                    lambda v: v / tau.reshape((-1,) + (1,) * (v.ndim - 1)),
                    deltas,
                )
                local_sum = T.tree_weighted_sum(d, n_k)
                agg_delta = jax.tree.map(
                    lambda v: tau_eff
                    * jax.lax.psum(v, self.client_axis)
                    / n_total,
                    local_sum,
                )
            elif cfg.robust_method in ("median", "trimmed_mean"):
                full = jax.tree.map(
                    lambda v: jax.lax.all_gather(
                        v, self.client_axis, tiled=True
                    ),
                    deltas,
                )
                agg_delta = (
                    robust.coordinate_median(full)
                    if cfg.robust_method == "median"
                    else robust.trimmed_mean(full)
                )
            else:
                local_sum = T.tree_weighted_sum(deltas, n_k)
                agg_delta = jax.tree.map(
                    lambda v: jax.lax.psum(v, self.client_axis) / n_total,
                    local_sum,
                )

            if cfg.robust_noise_stddev > 0:
                agg_delta = robust.add_gaussian_noise(
                    agg_delta,
                    cfg.robust_noise_stddev,
                    jax.random.fold_in(rkey, 1),
                )

            opt = make_server_optimizer(
                cfg.server_optimizer, cfg.server_lr, cfg.server_momentum
            )
            pseudo_grad = T.tree_scale(agg_delta, -1.0)
            updates, new_opt_state = opt.update(
                pseudo_grad, opt_state, global_params
            )
            new_params = optax.apply_updates(global_params, updates)

            other = {
                k: jax.tree.map(
                    lambda v: jax.lax.psum(v, self.client_axis) / n_total,
                    T.tree_weighted_sum(v, n_k),
                )
                for k, v in stacked_vars.items()
                if k != "params"
            }
            new_variables = {**other, "params": new_params}

            msums = jax.tree.map(
                lambda v: jax.lax.psum(jnp.sum(v), self.client_axis), msums
            )
            metrics = {
                "train_loss": msums["loss_sum"]
                / jnp.maximum(msums["count"], 1.0),
                "train_acc": msums["correct"]
                / jnp.maximum(msums["count"], 1.0),
            }
            return new_variables, new_opt_state, metrics

        new_variables, new_opt_state, metrics = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(rep, rep, cspec, cspec, cspec, rep, rep),
            out_specs=(rep, rep, rep),
            check_vma=False,
        )(
            state.variables,
            state.opt_state,
            idx_rows,
            mask_rows,
            ckeys,
            arrays.x,
            arrays.y,
        )
        new_state = ServerState(
            variables=new_variables,
            opt_state=new_opt_state,
            momentum=state.momentum,
            round=state.round + 1,
        )
        return new_state, metrics

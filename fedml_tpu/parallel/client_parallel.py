"""Mesh-sharded FedAvg: the cohort sharded over the ``clients`` axis, each
client's batch optionally sharded over the ``data`` axis.

This is the TPU-native replacement for the reference's two distributed
layers at once:

- ``fedml_api/distributed/fedavg`` (one MPI rank per client, server rank 0,
  pickled state_dicts over ``comm.send``) -> clients become *mesh shards*;
  "upload model / aggregate / broadcast" becomes a weighted pytree ``psum``
  under ``shard_map`` — aggregation rides ICI, no server process exists.
- ``fedml_api/distributed/fedavg_cross_silo`` (DDP inside each silo over
  NCCL) -> the ``data`` mesh axis: per-batch gradient ``psum`` inside the
  compiled local update.

The server step itself is the SAME function as the single-device simulator
(:func:`fedml_tpu.algorithms.fedavg.server_update`), instantiated with a
``psum``/``all_gather`` reducer — so the sharded path cannot drift from the
reference-equivalent math (and ``tests/test_sharded.py`` proves equality).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.algorithms.base import build_local_update, finalize_sums
from fedml_tpu.algorithms.fedavg import (
    FedAvgSim,
    ServerState,
    psum_reducer,
    server_update,
)
from fedml_tpu.models.base import FedModel


class ShardedFedAvg(FedAvgSim):
    """FedAvg with the round compiled over a (clients, data) mesh."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        mesh: Mesh,
    ):
        self.mesh = mesh
        self.client_axis = cfg.mesh.client_axis_name
        self.data_axis = cfg.mesh.data_axis_name
        self.n_client_shards = mesh.shape[self.client_axis]
        self.n_data_shards = mesh.shape[self.data_axis]
        cohort = min(cfg.fed.clients_per_round, cfg.data.num_clients)
        assert cohort % self.n_client_shards == 0, (
            f"effective cohort size {cohort} must divide evenly over the "
            f"{self.n_client_shards}-way clients mesh axis"
        )

        # FedAvgSim.__init__ builds the single-device local_update; rebuild
        # it with the data axis threaded through, then wrap the round in
        # shard_map.
        super().__init__(model, data, cfg)
        if self.n_data_shards > 1:
            self.local_update = build_local_update(
                model,
                self.task,
                cfg.train,
                self.batch_size,
                self.arrays.max_client_samples,
                data_axis=self.data_axis,
                data_axis_size=self.n_data_shards,
            )
        self._round_fn = jax.jit(self._sharded_round, donate_argnums=(0,))

    def _sharded_round(self, state: ServerState, arrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0),
            arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        idx_rows = arrays.idx[cohort]
        mask_rows = arrays.mask[cohort]

        cspec = P(self.client_axis)  # shard cohort; replicate over data axis
        rep = P()
        red = psum_reducer(self.client_axis)

        def shard_fn(state, idx_rows, mask_rows, ckeys, x, y):
            stacked_vars, n_k, msums = jax.vmap(
                self.local_update, in_axes=(None, 0, 0, None, None, 0)
            )(state.variables, idx_rows, mask_rows, x, y, ckeys)

            new_state = server_update(
                cfg,
                self.cfg.train,
                self.steps_per_epoch,
                self.batch_size,
                state,
                stacked_vars,
                n_k,
                rkey,
                red,
            )
            reduced = jax.tree.map(
                lambda v: jax.lax.psum(jnp.sum(v), self.client_axis), msums
            )
            fin = finalize_sums(reduced)
            metrics = {"train_loss": fin["loss"], "train_acc": fin["acc"]}
            return new_state, metrics

        new_state, metrics = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(rep, cspec, cspec, cspec, rep, rep),
            out_specs=(rep, rep),
            check_vma=False,
        )(state, idx_rows, mask_rows, ckeys, arrays.x, arrays.y)
        return new_state, metrics

"""Mesh-sharded FedAvg: the client population statically partitioned over
the ``clients`` axis — each shard owns a block of clients AND only their
samples — with each client's batch optionally sharded over the ``data``
axis.

This is the TPU-native replacement for the reference's two distributed
layers at once:

- ``fedml_api/distributed/fedavg`` (one MPI rank per client, server rank 0,
  pickled state_dicts over ``comm.send``) -> clients become *mesh shards*;
  "upload model / aggregate / broadcast" becomes a weighted pytree ``psum``
  under ``shard_map`` — aggregation rides ICI, no server process exists.
- ``fedml_api/distributed/fedavg_cross_silo`` (DDP inside each silo over
  NCCL, data local to the silo, ``DistWorker.py:31-54``) -> the ``data``
  mesh axis: per-batch gradient ``psum`` inside the compiled local update;
  and like the reference, sample banks stay LOCAL to their shard
  (:class:`fedml_tpu.data.federated.ShardedClientBanks`), so per-device
  HBM for the dataset is ~1/n_shards of the global set.

Cohort sampling is *stratified by shard*: every round each shard samples
``clients_per_round / n_shards`` of its own clients (deterministic in the
round key). :func:`fedml_tpu.core.random.sample_clients_stratified` is the
exact host-side mirror, so a single-device :class:`FedAvgSim` constructed
with that sampler follows the same trajectory — ``tests/test_sharded.py``
proves equality.

The server step itself is the SAME function as the single-device simulator
(:func:`fedml_tpu.algorithms.fedavg.server_update`), instantiated with a
``psum``/``all_gather`` reducer — so the sharded path cannot drift from the
reference-equivalent math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.core.compat import shard_map

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import bulk as BK
from fedml_tpu.core import elastic as E
from fedml_tpu.core import memscope as M
from fedml_tpu.core import random as R
from fedml_tpu.core import robust
from fedml_tpu.core import statebank as SB
from fedml_tpu.data.federated import FederatedData, shard_client_banks
from fedml_tpu.algorithms.base import (
    build_cohort_local_update,
    build_local_update,
    cohort_update_supported,
    finalize_sums,
)
from fedml_tpu.algorithms.fedavg import (
    FedAvgSim,
    ServerState,
    fold_block_partials,
    psum_reducer,
    server_update,
    server_update_from_partials,
)
from fedml_tpu.models.base import FedModel


class ShardedFedAvg(FedAvgSim):
    """FedAvg with the round compiled over a (clients, data) mesh.

    Performance observability (core/perf.py) rides the inherited
    :meth:`FedAvgSim.run` loop: with ``cfg.fed.profile_rounds > 0`` the
    sharded round gets the same jax-profiler capture windows —
    collectives (the client-axis ``psum``/``all_gather``) show up as
    the breakdown's ``collective`` share — and the live ``perf.mfu``
    gauge, whose peak-FLOPs denominator is the WHOLE mesh
    (``peak_per_chip x mesh.devices.size``, resolved by
    ``perf.build_sim_perf`` from :attr:`mesh`), not one chip."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        mesh: Mesh,
    ):
        if cfg.adversary.enabled():
            # the sharded round program calls server_update directly —
            # neither the adversary injection gate nor the non-finite
            # screen of FedAvgSim._round runs here, so an "adversarial"
            # sharded experiment would silently measure a clean run
            raise ValueError(
                "adversary injection is not wired into the "
                "mesh-sharded round (it covers the single-process "
                "FedAvgSim and the deploy-path client actor); run the "
                "Byzantine scenario there, or disable cfg.adversary"
            )
        if cfg.fed.compress != "none":
            # same honesty rule as the adversary gate: this runtime's
            # client<->server "wire" is the mesh ICI (psum/all_gather)
            # — there is no serialized delta payload to compress, and
            # silently skipping the codec would report compressed-run
            # results that measured a dense run
            raise ValueError(
                "wire compression is not wired into the mesh-sharded "
                "round (its aggregation rides ICI collectives, not a "
                "serialized wire); model the codec on FedAvgSim or "
                "the --role deploy path, or set compress='none'"
            )
        if (cfg.fed.client_block_size > 0
                and cfg.fed.robust_method not in ("mean", "", None)):
            # the streamed defense sketches (core/streamdef.py) fold
            # through ONE device's block scan; under shard_map each
            # shard would sketch only its own sub-cohort and the
            # cross-shard combine (histogram merge, projection
            # all_gather) is not built — reject rather than silently
            # defend each shard against only its local adversaries
            raise ValueError(
                "streamed Byzantine defenses are not wired into the "
                "mesh-sharded bulk round (the defense sketches fold "
                "on one device; the cross-shard sketch combine is not "
                "built); run defended bulk rounds on FedAvgSim, use "
                "the stacked sharded round (client_block_size=0), or "
                "set robust_method='mean'"
            )
        self.mesh = mesh
        self.client_axis = cfg.mesh.client_axis_name
        self.data_axis = cfg.mesh.data_axis_name
        self.n_client_shards = mesh.shape[self.client_axis]
        self.n_data_shards = mesh.shape[self.data_axis]
        cohort = min(cfg.fed.clients_per_round, cfg.data.num_clients)
        assert cohort % self.n_client_shards == 0, (
            f"effective cohort size {cohort} must divide evenly over the "
            f"{self.n_client_shards}-way clients mesh axis"
        )
        assert data.num_clients % self.n_client_shards == 0, (
            f"population {data.num_clients} must divide evenly over the "
            f"{self.n_client_shards}-way clients mesh axis (static "
            "client->shard placement)"
        )
        self.cohort_per_shard = cohort // self.n_client_shards
        # elastic shape bucketing (core/elastic.py): each shard's slice
        # of the cohort is padded to ITS power-of-two bucket, so a
        # cohort-size change (set_cohort_size) is a masked-row change,
        # not a recompile — the sharded twin of FedAvgSim's bucketing
        if cfg.fed.elastic_buckets:
            self.bucket_per_shard = min(
                E.bucket_for(self.cohort_per_shard),
                data.num_clients // self.n_client_shards,
            )
        else:
            self.bucket_per_shard = self.cohort_per_shard

        # FedAvgSim.__init__ builds the single-device local_update; our
        # _prepare_data override keeps the global arrays host-side and
        # builds the per-shard banks; rebuild the local update with the
        # data axis threaded through, then wrap the round in shard_map.
        super().__init__(model, data, cfg)
        # NOTE: super().__init__ may have LoRA-injected the model
        # (fedml_tpu.peft) — rebuilds below must use the injected one
        model = self.model
        if self.n_data_shards > 1:
            self.local_update = build_local_update(
                model,
                self.task,
                cfg.train,
                self.batch_size,
                self.arrays.max_client_samples,
                data_axis=self.data_axis,
                data_axis_size=self.n_data_shards,
                partition=self._peft.part if self._peft else None,
            )
        # per-shard cohort-grouped update (data axis 1 only: the cohort
        # network has no per-batch psum seam for intra-client DDP)
        self._shard_cohort_update = (
            build_cohort_local_update(
                model,
                self.task,
                cfg.train,
                self.batch_size,
                self.arrays.max_client_samples,
                self.cohort_per_shard,
            )
            if self.n_data_shards == 1
            and cfg.train.cohort_fused
            and cohort_update_supported(model, cfg.train)
            # the widened cohort network bakes the per-shard cohort
            # into its shapes — elastic bucketing uses the vmapped path
            and not self._elastic
            # the bulk engine streams the vmapped update per block
            and not self._bulk.enabled()
            # the partitioned (PEFT) update is vmapped-only
            and self._peft is None
            else None
        )
        # bulk-client streaming over the mesh (core/bulk.py): each
        # shard streams its OWN sub-cohort through blocks of B vmapped
        # local updates and psums only the O(model) partial sums at the
        # end — the stacked wmean/gather collectives never see a
        # [C, ...] operand. Block-count bucketing is per shard.
        if self._bulk.enabled():
            self._shard_blocks = BK.plan_blocks(
                self.cohort_per_shard, self._block_size, self._elastic
            )
            self._shard_slots = self._shard_blocks * self._block_size
            self._shard_max_live = min(
                self._shard_slots,
                data.num_clients // self.n_client_shards,
            )
            # the whole-sim grid the telemetry gauges report
            self._n_blocks = self._shard_blocks * self.n_client_shards
            self._slots = self._shard_slots * self.n_client_shards
            self._max_live = self._shard_max_live * self.n_client_shards
        # instrumented AOT site like the single-device round
        # (core/memscope.py): compile wall + memory_analysis recorded
        # per program, the donated state audited on first execution.
        # Personalized PEFT donates the adapter ClientStateBank too
        # (operand 4, the single-device layout) — it shards over the
        # client axis inside the round, each shard owning its own
        # K-row slice.
        personalized = self._peft is not None and self._peft.personalized
        self._round_fn = M.ProgramSite(
            self._sharded_round,
            family=(
                "sharded_bulk" if self._bulk.enabled()
                else "sharded_round"
            ),
            donate_argnums=(0, 4) if personalized else (0,),
        )
        # round fusion (docs/PERFORMANCE.md "Round fusion"): the
        # inherited _fused_block scans over whatever _round_impl names
        # — rebinding it here makes the fused block run the shard_map'd
        # round body, so fuse_rounds composes with the mesh unchanged
        # (same collectives per iteration, same whole-mesh MFU
        # denominator from perf.build_sim_perf). Compression is
        # rejected above, so the block never carries a residual.
        self._round_impl = self._sharded_round

    def _anatomy_path(self) -> str:
        # the anatomy ring labels the round body actually running
        # (docs/OBSERVABILITY.md "Round anatomy"); the inherited run
        # loop times the mesh round at the same sync points
        return "sharded"

    def set_cohort_size(self, n: int) -> None:
        """Elastic cohort change for the sharded runtime: ``n`` must
        divide evenly over the clients axis and each shard's slice must
        fit the compiled per-shard bucket."""
        if not self._elastic:
            raise ValueError(
                "set_cohort_size requires FedConfig(elastic_buckets="
                "True)"
            )
        if n % self.n_client_shards != 0:
            raise ValueError(
                f"cohort size {n} must divide evenly over the "
                f"{self.n_client_shards}-way clients mesh axis"
            )
        per = n // self.n_client_shards
        if self._bulk.enabled():
            if not (1 <= per <= self._shard_max_live):
                raise ValueError(
                    f"per-shard cohort {per} does not fit the compiled "
                    f"{self._shard_blocks}x{self._block_size} per-shard "
                    f"block grid (live per-shard cohort must stay in "
                    f"[1, {self._shard_max_live}])"
                )
            self._n_active = n
            return
        if not (1 <= per <= self.bucket_per_shard):
            raise ValueError(
                f"per-shard cohort {per} does not fit the compiled "
                f"per-shard bucket {self.bucket_per_shard}"
            )
        self._n_active = n

    def _prepare_data(self, data, cfg):
        """Training data lives ONLY in the per-shard banks (per-device HBM
        ~1/n_shards of the global set); the global FederatedArrays stays as
        host numpy and is transferred only when evaluation runs."""
        from fedml_tpu.data.federated import arrays_and_batch

        self.arrays, self.batch_size = arrays_and_batch(
            data, cfg.data, device=False
        )
        self.banks = shard_client_banks(
            data,
            self.n_client_shards,
            pad_multiple=1 if cfg.data.full_batch else cfg.data.batch_size,
        )
        assert self.banks.max_client_samples == self.arrays.max_client_samples

    def _sharded_round(self, state: ServerState, banks, n_active=None,
                       residual=None, bank=None):
        """One mesh round. The trailing ``(residual, bank)`` operands
        mirror :meth:`FedAvgSim._round`'s layout (the inherited fused
        block calls through it): compression is rejected at
        construction so ``residual`` is always None; ``bank`` is the
        personalized-PEFT adapter :class:`~fedml_tpu.core.statebank.
        ClientStateBank`, sharded over the client axis — inside the
        shard each body sees its own ``[K, ...]`` slice (local ids,
        local sentinel ``K``) and returns the updated slice, which
        shard_map stitches back to the full ``[num_clients, ...]``
        bank."""
        del residual  # compress is rejected at construction
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        ckey = jax.random.fold_in(rkey, 0)
        K = banks.clients_per_shard
        Kb = self.bucket_per_shard

        cspec = P(self.client_axis)  # shard banks; replicate over data axis
        rep = P()
        red = psum_reducer(self.client_axis)

        def shard_fn(state, x, y, idx, mask, *rest):
            # leading shard axis arrives with extent 1 inside the shard
            x, y = x[0], y[0]
            idx, mask = idx[0], mask[0]
            rest = list(rest)
            # the bank slice's leading axis is the CLIENT axis itself
            # (num_clients -> K per shard): no extent-1 unwrap
            bank_l = rest.pop(0) if bank is not None else None
            n_act = rest[0] if rest else None
            shard = jax.lax.axis_index(self.client_axis)
            if self._bulk.enabled():
                return self._bulk_shard_body(
                    state, x, y, idx, mask, shard, rkey, ckey, K, n_act,
                    bank_l,
                )
            if bank_l is not None:
                return self._personal_shard_body(
                    state, x, y, idx, mask, shard, rkey, ckey, K, Kb,
                    n_act, bank_l, red,
                )
            # stratified cohort: this shard samples its own clients (LOCAL
            # ids); keys use GLOBAL client ids so the host mirror matches.
            # Under elastic bucketing the shard samples its full BUCKET
            # and a traced per-shard live count masks the padded slots.
            local = R.sample_stratum(ckey, shard, K, Kb)
            ckeys = jax.vmap(
                lambda c: R.client_key(rkey, shard * K + c)
            )(local)
            if self._shard_cohort_update is not None:
                # cohort-grouped fast path per shard: this shard's slice
                # of the cohort runs as ONE widened network (see
                # fedml_tpu.models.cohort) — purely intra-shard compute,
                # so it composes with the client-axis psum unchanged
                stacked_vars, n_k, msums = self._shard_cohort_update(
                    state.variables, idx[local], mask[local], x, y, ckeys
                )
            else:
                stacked_vars, n_k, msums = jax.vmap(
                    self.local_update, in_axes=(None, 0, 0, None, None, 0)
                )(state.variables, idx[local], mask[local], x, y, ckeys)

            # PEFT view: the psum'd aggregation below only ever sees
            # the O(adapter) pruned subtree — the frozen base is a
            # replicated operand merged back bitwise after the step,
            # never re-shipped through a collective
            view = (
                state if self._peft is None
                else self._peft.view_state(state)
            )
            live = None
            if n_act is not None:
                live = E.active_mask(
                    Kb, n_act // self.n_client_shards
                )
                stacked_vars, n_k, msums = E.mask_padded(
                    stacked_vars, n_k, msums, view.variables, live
                )

            new_state = server_update(
                cfg,
                self.cfg.train,
                self.steps_per_epoch,
                self.batch_size,
                view,
                stacked_vars,
                n_k,
                rkey,
                red,
                valid=live,
            )
            if self._peft is not None:
                new_state = self._peft.merge_state(new_state, state)
            reduced = jax.tree.map(
                lambda v: jax.lax.psum(jnp.sum(v), self.client_axis), msums
            )
            fin = finalize_sums(reduced)
            metrics = {"train_loss": fin["loss"], "train_acc": fin["acc"]}
            return new_state, metrics

        in_specs = (rep, cspec, cspec, cspec, cspec)
        operands = (state, banks.x, banks.y, banks.idx, banks.mask)
        if bank is not None:
            # the adapter bank shards like the sample banks: P on the
            # leading (client) axis of every row leaf — shard s owns
            # rows [s*K, (s+1)*K) of the global bank
            in_specs += (cspec,)
            operands += (bank,)
        if n_active is not None:
            # the live count is a REPLICATED operand (not a closure):
            # closed-over tracers under shard_map are version-fragile
            in_specs += (rep,)
            operands += (n_active,)
        out_specs = (rep, rep, cspec) if bank is not None else (rep, rep)
        out = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )(*operands)
        return out

    def _bulk_shard_body(self, state, x, y, idx, mask, shard, rkey,
                         ckey, K, n_act, bank=None):
        """One shard's bulk round body (runs inside the shard_map):
        stream THIS shard's sub-cohort through fixed-size blocks
        folding O(model) partials, then psum the partials over the
        client axis and run the SAME
        :func:`~fedml_tpu.algorithms.fedavg.server_update_from_partials`
        finalize as the single-device bulk round (replicated on every
        shard, like the stacked path's server step). The collectives
        shrink from stacked wmean/gather to one psum of partials."""
        cfg = self.cfg.fed
        view = (
            state if self._peft is None
            else self._peft.view_state(state)
        )
        S = self._shard_slots
        draw = (
            min(S, K) if self._elastic else self.cohort_per_shard
        )
        local = R.sample_stratum(ckey, shard, K, draw)
        pad = S - draw
        if pad:
            # the LOCAL sentinel (= K, this shard's row count): the
            # clamped sample-bank gather reads a real row but the slot
            # is masked below, and a ClientStateBank scatter DROPS the
            # write entirely (mode="drop") — a padded slot can never
            # alias client 0's bank row
            local = jnp.concatenate(
                [local, jnp.full((pad,), K, jnp.int32)]
            )
        if n_act is not None:
            live = E.active_mask(S, n_act // self.n_client_shards)
        elif S != self.cohort_per_shard:
            live = E.active_mask(S, self.cohort_per_shard)
        else:
            live = None
        if bank is not None:
            return self._bulk_shard_personal(
                state, view, x, y, idx, mask, shard, rkey, K, local,
                live, bank,
            )

        def fold_block(block_ids, block_live):
            ckeys = jax.vmap(
                lambda c: R.client_key(rkey, shard * K + c)
            )(block_ids)
            stacked_vars, n_k, msums = jax.vmap(
                self.local_update, in_axes=(None, 0, 0, None, None, 0)
            )(state.variables, idx[block_ids], mask[block_ids], x, y,
              ckeys)
            if block_live is not None:
                stacked_vars, n_k, msums = E.mask_padded(
                    stacked_vars, n_k, msums, view.variables,
                    block_live,
                )
            # the sharded stacked path carries no non-finite screen
            # (adversary configs are rejected at construction) — the
            # bulk twin mirrors it: rejected stays 0
            return fold_block_partials(
                cfg, self.cfg.train, self.steps_per_epoch,
                self.batch_size, view, stacked_vars, n_k, msums,
                jnp.zeros((), jnp.float32),
            )

        partials = BK.stream_blocks(
            fold_block, local, live, self._block_size
        )
        partials = jax.tree.map(
            lambda v: jax.lax.psum(v, self.client_axis), partials
        )
        new_state = server_update_from_partials(
            cfg, view, partials, rkey
        )
        if self._peft is not None:
            new_state = self._peft.merge_state(new_state, state)
        fin = finalize_sums(partials.msums)
        return new_state, {
            "train_loss": fin["loss"], "train_acc": fin["acc"],
        }

    def _local_personal_update(self, state, x, y, idx, mask,
                               shard, rkey, K, ids, priv):
        """One stacked group of personalized local updates on THIS
        shard: merge each client's private adapter row into the shared
        model, train, and split the result back into (shared, private)
        halves — the per-shard twin of the bodies in
        :meth:`FedAvgSim._personal_round` / ``_bulk_personal``. ``ids``
        are LOCAL (in ``[0, K)``, sentinel ``K``); client keys use the
        GLOBAL id ``shard*K + c`` so the host stratified mirror
        matches."""
        plan = self._peft
        base_frozen = plan.private.frozen(state.variables["params"])
        ckeys = jax.vmap(
            lambda c: R.client_key(rkey, shard * K + c)
        )(ids)

        def one(priv_row, idx_row, mask_row, key):
            params_c = plan.private.merge(priv_row, base_frozen)
            vars_c = {**state.variables, "params": params_c}
            out_vars, n_k, msums = self.local_update(
                vars_c, idx_row, mask_row, x, y, key
            )
            trained = out_vars["params"]
            shared = {
                **{k: v for k, v in out_vars.items() if k != "params"},
                "params": plan.private.frozen(trained),
            }
            return (shared, plan.private.trainable(trained), n_k,
                    msums)

        return jax.vmap(one)(priv, idx[ids], mask[ids], ckeys)

    @staticmethod
    def _screen_personal(view, shared, new_priv, n_k, msums, live):
        """The both-halves non-finite screen shared by the stacked and
        bulk personal shard bodies (same contract as the single-device
        paths): a poisoned client contributes nothing to the shared
        aggregate AND keeps its pre-round bank row; non-live slots are
        healed/zero-weight and are neither rejections nor bank writes.
        Returns ``(shared, n_k, keep, rejected)``."""
        if live is not None:
            shared, n_k, msums = E.mask_padded(
                shared, n_k, msums, view.variables, live
            )
        ok = robust.finite_client_mask(
            {"shared": shared, "private": new_priv}, n_k
        )
        lv = jnp.ones(ok.shape, bool) if live is None else live
        ok = ok | ~lv

        def heal(s, g):
            m = ok.reshape((-1,) + (1,) * (s.ndim - 1))
            return jnp.where(m, s, g[None].astype(s.dtype))

        shared = jax.tree.map(heal, shared, view.variables)
        n_k = jnp.where(ok, n_k, jnp.zeros_like(n_k))
        rejected = (ok.shape[0] - jnp.sum(ok)).astype(jnp.float32)
        return shared, n_k, msums, ok & lv, rejected

    def _personal_shard_body(self, state, x, y, idx, mask, shard, rkey,
                             ckey, K, Kb, n_act, bank, red):
        """Stacked personalized round on one shard: gather this
        shard's cohort rows from its bank SLICE, train merged, psum
        only the SHARED half, scatter the trained rows back. The
        no-leak contract is structural exactly as on the single-device
        path — the psum'd view does not contain the private paths, and
        each bank row is written only from its own client's update."""
        cfg = self.cfg.fed
        plan = self._peft
        local = R.sample_stratum(ckey, shard, K, Kb)
        priv = bank.gather(local)
        shared, new_priv, n_k, msums = self._local_personal_update(
            state, x, y, idx, mask, shard, rkey, K, local, priv,
        )
        view = plan.view_state(state)
        live = None
        if n_act is not None:
            live = E.active_mask(Kb, n_act // self.n_client_shards)
        shared, n_k, msums, keep, rejected = self._screen_personal(
            view, shared, new_priv, n_k, msums, live
        )
        new_state = server_update(
            cfg, self.cfg.train, self.steps_per_epoch,
            self.batch_size, view, shared, n_k, rkey, red, valid=live,
        )
        new_state = plan.merge_state(new_state, state)
        new_bank = bank.put(local, new_priv, keep=keep, gathered=priv)
        reduced = jax.tree.map(
            lambda v: jax.lax.psum(jnp.sum(v), self.client_axis), msums
        )
        fin = finalize_sums(reduced)
        metrics = {
            "train_loss": fin["loss"],
            "train_acc": fin["acc"],
            "nonfinite_rejected": jax.lax.psum(
                rejected, self.client_axis
            ),
        }
        return new_state, metrics, new_bank

    def _bulk_shard_personal(self, state, view, x, y, idx, mask, shard,
                             rkey, K, local, live, bank):
        """Personalized PEFT x bulk x mesh: each shard streams its
        sub-cohort through blocks, gathering/scattering its bank SLICE
        through the block scan carry (local sentinel ``K`` — padded
        slots read a clamped row but never write one), then psums the
        O(model) shared partials. The bank never crosses the mesh: it
        is already partitioned the way the round consumes it."""
        cfg = self.cfg.fed
        plan = self._peft

        def fold_block(block_ids, block_live, bk):
            priv = bk.gather(block_ids)
            shared, new_priv, n_k, msums = self._local_personal_update(
                state, x, y, idx, mask, shard, rkey, K, block_ids,
                priv,
            )
            shared, n_k, msums, keep, rejected = self._screen_personal(
                view, shared, new_priv, n_k, msums, block_live
            )
            bk = bk.put(block_ids, new_priv, keep=keep, gathered=priv)
            p = fold_block_partials(
                cfg, self.cfg.train, self.steps_per_epoch,
                self.batch_size, view, shared, n_k, msums, rejected,
            )
            return p, bk

        partials, bank = BK.stream_blocks(
            fold_block, local, live, self._block_size, banks=bank
        )
        partials = jax.tree.map(
            lambda v: jax.lax.psum(v, self.client_axis), partials
        )
        new_state = server_update_from_partials(
            cfg, view, partials, rkey
        )
        new_state = plan.merge_state(new_state, state)
        fin = finalize_sums(partials.msums)
        return new_state, {
            "train_loss": fin["loss"],
            "train_acc": fin["acc"],
            "nonfinite_rejected": partials.rejected,
        }, bank

    def _program_key(self) -> tuple:
        return (self._shard_blocks, self._block_size)

    def _round_operand(self):
        return self.banks

    def run_round(self, state):
        personalized = (
            self._peft is not None and self._peft.personalized
        )
        if self._bulk.enabled():
            self._note_bulk_dispatch()
            key = self._program_key()
        else:
            key = self.bucket_per_shard
        n = (
            jnp.asarray(self._n_active, jnp.int32)
            if self._elastic else None
        )
        if personalized:
            # the adapter bank is a donated operand and comes back
            # updated (the single-device thread-through discipline);
            # per round each shard gathers+scatters its own slice once
            # per block
            self._ensure_adapter_bank(state)

            def call():
                return self._round_fn(
                    key, state, self.banks, n, None,
                    self._bank_adapter,
                )

            state, m, self._bank_adapter = (
                E.mirror_jit_cache(self._round_fn, call)
                if self._elastic else call()
            )
            io = self._n_blocks if self._bulk.enabled() else 1
            SB.note_round_io(io, io)
            return state, m
        if not self._elastic:
            return self._round_fn(key, state, self.banks)
        return E.mirror_jit_cache(
            self._round_fn,
            lambda: self._round_fn(key, state, self.banks, n),
        )

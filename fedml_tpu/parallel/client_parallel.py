"""Mesh-sharded FedAvg: the client population statically partitioned over
the ``clients`` axis — each shard owns a block of clients AND only their
samples — with each client's batch optionally sharded over the ``data``
axis.

This is the TPU-native replacement for the reference's two distributed
layers at once:

- ``fedml_api/distributed/fedavg`` (one MPI rank per client, server rank 0,
  pickled state_dicts over ``comm.send``) -> clients become *mesh shards*;
  "upload model / aggregate / broadcast" becomes a weighted pytree ``psum``
  under ``shard_map`` — aggregation rides ICI, no server process exists.
- ``fedml_api/distributed/fedavg_cross_silo`` (DDP inside each silo over
  NCCL, data local to the silo, ``DistWorker.py:31-54``) -> the ``data``
  mesh axis: per-batch gradient ``psum`` inside the compiled local update;
  and like the reference, sample banks stay LOCAL to their shard
  (:class:`fedml_tpu.data.federated.ShardedClientBanks`), so per-device
  HBM for the dataset is ~1/n_shards of the global set.

Cohort sampling is *stratified by shard*: every round each shard samples
``clients_per_round / n_shards`` of its own clients (deterministic in the
round key). :func:`fedml_tpu.core.random.sample_clients_stratified` is the
exact host-side mirror, so a single-device :class:`FedAvgSim` constructed
with that sampler follows the same trajectory — ``tests/test_sharded.py``
proves equality.

The server step itself is the SAME function as the single-device simulator
(:func:`fedml_tpu.algorithms.fedavg.server_update`), instantiated with a
``psum``/``all_gather`` reducer — so the sharded path cannot drift from the
reference-equivalent math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.core.compat import shard_map

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import bulk as BK
from fedml_tpu.core import elastic as E
from fedml_tpu.core import memscope as M
from fedml_tpu.core import random as R
from fedml_tpu.data.federated import FederatedData, shard_client_banks
from fedml_tpu.algorithms.base import (
    build_cohort_local_update,
    build_local_update,
    cohort_update_supported,
    finalize_sums,
)
from fedml_tpu.algorithms.fedavg import (
    FedAvgSim,
    ServerState,
    fold_block_partials,
    psum_reducer,
    server_update,
    server_update_from_partials,
)
from fedml_tpu.models.base import FedModel


class ShardedFedAvg(FedAvgSim):
    """FedAvg with the round compiled over a (clients, data) mesh.

    Performance observability (core/perf.py) rides the inherited
    :meth:`FedAvgSim.run` loop: with ``cfg.fed.profile_rounds > 0`` the
    sharded round gets the same jax-profiler capture windows —
    collectives (the client-axis ``psum``/``all_gather``) show up as
    the breakdown's ``collective`` share — and the live ``perf.mfu``
    gauge, whose peak-FLOPs denominator is the WHOLE mesh
    (``peak_per_chip x mesh.devices.size``, resolved by
    ``perf.build_sim_perf`` from :attr:`mesh`), not one chip."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        mesh: Mesh,
    ):
        if cfg.adversary.enabled():
            # the sharded round program calls server_update directly —
            # neither the adversary injection gate nor the non-finite
            # screen of FedAvgSim._round runs here, so an "adversarial"
            # sharded experiment would silently measure a clean run
            raise ValueError(
                "adversary injection is not wired into the "
                "mesh-sharded round (it covers the single-process "
                "FedAvgSim and the deploy-path client actor); run the "
                "Byzantine scenario there, or disable cfg.adversary"
            )
        if cfg.fed.compress != "none":
            # same honesty rule as the adversary gate: this runtime's
            # client<->server "wire" is the mesh ICI (psum/all_gather)
            # — there is no serialized delta payload to compress, and
            # silently skipping the codec would report compressed-run
            # results that measured a dense run
            raise ValueError(
                "wire compression is not wired into the mesh-sharded "
                "round (its aggregation rides ICI collectives, not a "
                "serialized wire); model the codec on FedAvgSim or "
                "the --role deploy path, or set compress='none'"
            )
        if getattr(cfg.fed, "peft_personalize", False):
            # the per-client adapter bank is a single-device donated
            # operand; sharding it over the client axis (per-shard
            # bank slices + the gather/scatter seam) is future work —
            # reject rather than silently train a shared-adapter run
            # under a "personalized" label
            raise ValueError(
                "peft_personalize is not wired into the mesh-sharded "
                "runtime (the private adapter bank lives on one "
                "device); run personalized PEFT on FedAvgSim, or drop "
                "peft_personalize (non-personalized peft='lora' "
                "composes with the sharded round)"
            )
        self.mesh = mesh
        self.client_axis = cfg.mesh.client_axis_name
        self.data_axis = cfg.mesh.data_axis_name
        self.n_client_shards = mesh.shape[self.client_axis]
        self.n_data_shards = mesh.shape[self.data_axis]
        cohort = min(cfg.fed.clients_per_round, cfg.data.num_clients)
        assert cohort % self.n_client_shards == 0, (
            f"effective cohort size {cohort} must divide evenly over the "
            f"{self.n_client_shards}-way clients mesh axis"
        )
        assert data.num_clients % self.n_client_shards == 0, (
            f"population {data.num_clients} must divide evenly over the "
            f"{self.n_client_shards}-way clients mesh axis (static "
            "client->shard placement)"
        )
        self.cohort_per_shard = cohort // self.n_client_shards
        # elastic shape bucketing (core/elastic.py): each shard's slice
        # of the cohort is padded to ITS power-of-two bucket, so a
        # cohort-size change (set_cohort_size) is a masked-row change,
        # not a recompile — the sharded twin of FedAvgSim's bucketing
        if cfg.fed.elastic_buckets:
            self.bucket_per_shard = min(
                E.bucket_for(self.cohort_per_shard),
                data.num_clients // self.n_client_shards,
            )
        else:
            self.bucket_per_shard = self.cohort_per_shard

        # FedAvgSim.__init__ builds the single-device local_update; our
        # _prepare_data override keeps the global arrays host-side and
        # builds the per-shard banks; rebuild the local update with the
        # data axis threaded through, then wrap the round in shard_map.
        super().__init__(model, data, cfg)
        # NOTE: super().__init__ may have LoRA-injected the model
        # (fedml_tpu.peft) — rebuilds below must use the injected one
        model = self.model
        if self.n_data_shards > 1:
            self.local_update = build_local_update(
                model,
                self.task,
                cfg.train,
                self.batch_size,
                self.arrays.max_client_samples,
                data_axis=self.data_axis,
                data_axis_size=self.n_data_shards,
                partition=self._peft.part if self._peft else None,
            )
        # per-shard cohort-grouped update (data axis 1 only: the cohort
        # network has no per-batch psum seam for intra-client DDP)
        self._shard_cohort_update = (
            build_cohort_local_update(
                model,
                self.task,
                cfg.train,
                self.batch_size,
                self.arrays.max_client_samples,
                self.cohort_per_shard,
            )
            if self.n_data_shards == 1
            and cfg.train.cohort_fused
            and cohort_update_supported(model, cfg.train)
            # the widened cohort network bakes the per-shard cohort
            # into its shapes — elastic bucketing uses the vmapped path
            and not self._elastic
            # the bulk engine streams the vmapped update per block
            and not self._bulk.enabled()
            # the partitioned (PEFT) update is vmapped-only
            and self._peft is None
            else None
        )
        # bulk-client streaming over the mesh (core/bulk.py): each
        # shard streams its OWN sub-cohort through blocks of B vmapped
        # local updates and psums only the O(model) partial sums at the
        # end — the stacked wmean/gather collectives never see a
        # [C, ...] operand. Block-count bucketing is per shard.
        if self._bulk.enabled():
            self._shard_blocks = BK.plan_blocks(
                self.cohort_per_shard, self._block_size, self._elastic
            )
            self._shard_slots = self._shard_blocks * self._block_size
            self._shard_max_live = min(
                self._shard_slots,
                data.num_clients // self.n_client_shards,
            )
            # the whole-sim grid the telemetry gauges report
            self._n_blocks = self._shard_blocks * self.n_client_shards
            self._slots = self._shard_slots * self.n_client_shards
            self._max_live = self._shard_max_live * self.n_client_shards
        # instrumented AOT site like the single-device round
        # (core/memscope.py): compile wall + memory_analysis recorded
        # per program, the donated state audited on first execution
        self._round_fn = M.ProgramSite(
            self._sharded_round,
            family=(
                "sharded_bulk" if self._bulk.enabled()
                else "sharded_round"
            ),
            donate_argnums=(0,),
        )
        # round fusion (docs/PERFORMANCE.md "Round fusion"): the
        # inherited _fused_block scans over whatever _round_impl names
        # — rebinding it here makes the fused block run the shard_map'd
        # round body, so fuse_rounds composes with the mesh unchanged
        # (same collectives per iteration, same whole-mesh MFU
        # denominator from perf.build_sim_perf). Compression is
        # rejected above, so the block never carries a residual.
        self._round_impl = self._sharded_round

    def _anatomy_path(self) -> str:
        # the anatomy ring labels the round body actually running
        # (docs/OBSERVABILITY.md "Round anatomy"); the inherited run
        # loop times the mesh round at the same sync points
        return "sharded"

    def set_cohort_size(self, n: int) -> None:
        """Elastic cohort change for the sharded runtime: ``n`` must
        divide evenly over the clients axis and each shard's slice must
        fit the compiled per-shard bucket."""
        if not self._elastic:
            raise ValueError(
                "set_cohort_size requires FedConfig(elastic_buckets="
                "True)"
            )
        if n % self.n_client_shards != 0:
            raise ValueError(
                f"cohort size {n} must divide evenly over the "
                f"{self.n_client_shards}-way clients mesh axis"
            )
        per = n // self.n_client_shards
        if self._bulk.enabled():
            if not (1 <= per <= self._shard_max_live):
                raise ValueError(
                    f"per-shard cohort {per} does not fit the compiled "
                    f"{self._shard_blocks}x{self._block_size} per-shard "
                    f"block grid (live per-shard cohort must stay in "
                    f"[1, {self._shard_max_live}])"
                )
            self._n_active = n
            return
        if not (1 <= per <= self.bucket_per_shard):
            raise ValueError(
                f"per-shard cohort {per} does not fit the compiled "
                f"per-shard bucket {self.bucket_per_shard}"
            )
        self._n_active = n

    def _prepare_data(self, data, cfg):
        """Training data lives ONLY in the per-shard banks (per-device HBM
        ~1/n_shards of the global set); the global FederatedArrays stays as
        host numpy and is transferred only when evaluation runs."""
        from fedml_tpu.data.federated import arrays_and_batch

        self.arrays, self.batch_size = arrays_and_batch(
            data, cfg.data, device=False
        )
        self.banks = shard_client_banks(
            data,
            self.n_client_shards,
            pad_multiple=1 if cfg.data.full_batch else cfg.data.batch_size,
        )
        assert self.banks.max_client_samples == self.arrays.max_client_samples

    def _sharded_round(self, state: ServerState, banks, n_active=None):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        ckey = jax.random.fold_in(rkey, 0)
        K = banks.clients_per_shard
        Kb = self.bucket_per_shard

        cspec = P(self.client_axis)  # shard banks; replicate over data axis
        rep = P()
        red = psum_reducer(self.client_axis)

        def shard_fn(state, x, y, idx, mask, *maybe_n):
            # leading shard axis arrives with extent 1 inside the shard
            x, y = x[0], y[0]
            idx, mask = idx[0], mask[0]
            n_act = maybe_n[0] if maybe_n else None
            shard = jax.lax.axis_index(self.client_axis)
            if self._bulk.enabled():
                return self._bulk_shard_body(
                    state, x, y, idx, mask, shard, rkey, ckey, K, n_act
                )
            # stratified cohort: this shard samples its own clients (LOCAL
            # ids); keys use GLOBAL client ids so the host mirror matches.
            # Under elastic bucketing the shard samples its full BUCKET
            # and a traced per-shard live count masks the padded slots.
            local = R.sample_stratum(ckey, shard, K, Kb)
            ckeys = jax.vmap(
                lambda c: R.client_key(rkey, shard * K + c)
            )(local)
            if self._shard_cohort_update is not None:
                # cohort-grouped fast path per shard: this shard's slice
                # of the cohort runs as ONE widened network (see
                # fedml_tpu.models.cohort) — purely intra-shard compute,
                # so it composes with the client-axis psum unchanged
                stacked_vars, n_k, msums = self._shard_cohort_update(
                    state.variables, idx[local], mask[local], x, y, ckeys
                )
            else:
                stacked_vars, n_k, msums = jax.vmap(
                    self.local_update, in_axes=(None, 0, 0, None, None, 0)
                )(state.variables, idx[local], mask[local], x, y, ckeys)

            # PEFT view: the psum'd aggregation below only ever sees
            # the O(adapter) pruned subtree — the frozen base is a
            # replicated operand merged back bitwise after the step,
            # never re-shipped through a collective
            view = (
                state if self._peft is None
                else self._peft.view_state(state)
            )
            live = None
            if n_act is not None:
                live = E.active_mask(
                    Kb, n_act // self.n_client_shards
                )
                stacked_vars, n_k, msums = E.mask_padded(
                    stacked_vars, n_k, msums, view.variables, live
                )

            new_state = server_update(
                cfg,
                self.cfg.train,
                self.steps_per_epoch,
                self.batch_size,
                view,
                stacked_vars,
                n_k,
                rkey,
                red,
                valid=live,
            )
            if self._peft is not None:
                new_state = self._peft.merge_state(new_state, state)
            reduced = jax.tree.map(
                lambda v: jax.lax.psum(jnp.sum(v), self.client_axis), msums
            )
            fin = finalize_sums(reduced)
            metrics = {"train_loss": fin["loss"], "train_acc": fin["acc"]}
            return new_state, metrics

        in_specs = (rep, cspec, cspec, cspec, cspec)
        operands = (state, banks.x, banks.y, banks.idx, banks.mask)
        if n_active is not None:
            # the live count is a REPLICATED operand (not a closure):
            # closed-over tracers under shard_map are version-fragile
            in_specs += (rep,)
            operands += (n_active,)
        new_state, metrics = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(rep, rep),
            check_vma=False,
        )(*operands)
        return new_state, metrics

    def _bulk_shard_body(self, state, x, y, idx, mask, shard, rkey,
                         ckey, K, n_act):
        """One shard's bulk round body (runs inside the shard_map):
        stream THIS shard's sub-cohort through fixed-size blocks
        folding O(model) partials, then psum the partials over the
        client axis and run the SAME
        :func:`~fedml_tpu.algorithms.fedavg.server_update_from_partials`
        finalize as the single-device bulk round (replicated on every
        shard, like the stacked path's server step). The collectives
        shrink from stacked wmean/gather to one psum of partials."""
        cfg = self.cfg.fed
        view = (
            state if self._peft is None
            else self._peft.view_state(state)
        )
        S = self._shard_slots
        draw = (
            min(S, K) if self._elastic else self.cohort_per_shard
        )
        local = R.sample_stratum(ckey, shard, K, draw)
        pad = S - draw
        if pad:
            local = jnp.concatenate(
                [local, jnp.zeros((pad,), jnp.int32)]
            )
        if n_act is not None:
            live = E.active_mask(S, n_act // self.n_client_shards)
        elif S != self.cohort_per_shard:
            live = E.active_mask(S, self.cohort_per_shard)
        else:
            live = None

        def fold_block(block_ids, block_live):
            ckeys = jax.vmap(
                lambda c: R.client_key(rkey, shard * K + c)
            )(block_ids)
            stacked_vars, n_k, msums = jax.vmap(
                self.local_update, in_axes=(None, 0, 0, None, None, 0)
            )(state.variables, idx[block_ids], mask[block_ids], x, y,
              ckeys)
            if block_live is not None:
                stacked_vars, n_k, msums = E.mask_padded(
                    stacked_vars, n_k, msums, view.variables,
                    block_live,
                )
            # the sharded stacked path carries no non-finite screen
            # (adversary configs are rejected at construction) — the
            # bulk twin mirrors it: rejected stays 0
            return fold_block_partials(
                cfg, self.cfg.train, self.steps_per_epoch,
                self.batch_size, view, stacked_vars, n_k, msums,
                jnp.zeros((), jnp.float32),
            )

        partials = BK.stream_blocks(
            fold_block, local, live, self._block_size
        )
        partials = jax.tree.map(
            lambda v: jax.lax.psum(v, self.client_axis), partials
        )
        new_state = server_update_from_partials(
            cfg, view, partials, rkey
        )
        if self._peft is not None:
            new_state = self._peft.merge_state(new_state, state)
        fin = finalize_sums(partials.msums)
        return new_state, {
            "train_loss": fin["loss"], "train_acc": fin["acc"],
        }

    def _program_key(self) -> tuple:
        return (self._shard_blocks, self._block_size)

    def _round_operand(self):
        return self.banks

    def run_round(self, state):
        if self._bulk.enabled():
            self._note_bulk_dispatch()
            key = self._program_key()
            if not self._elastic:
                return self._round_fn(key, state, self.banks)
            return E.mirror_jit_cache(
                self._round_fn,
                lambda: self._round_fn(
                    key, state, self.banks,
                    jnp.asarray(self._n_active, jnp.int32),
                ),
            )
        key = self.bucket_per_shard
        if not self._elastic:
            return self._round_fn(key, state, self.banks)
        return E.mirror_jit_cache(
            self._round_fn,
            lambda: self._round_fn(
                key, state, self.banks,
                jnp.asarray(self._n_active, jnp.int32),
            ),
        )

"""Mesh construction helpers."""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(
    client_axis: int | None = None,
    data_axis: int = 1,
    axis_names: tuple[str, str] = ("clients", "data"),
    devices=None,
) -> Mesh:
    """Build a 2-D (clients, data) mesh.

    ``client_axis=None`` uses all remaining devices. The ``clients`` axis is
    the FL population axis (the reference's one-process-per-client MPI
    layout, ``distributed/fedavg/FedAvgAPI.py:36-66``); the ``data`` axis is
    the intra-client DDP analog.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if client_axis is None:
        assert n % data_axis == 0, (n, data_axis)
        client_axis = n // data_axis
    assert client_axis * data_axis <= n, (client_axis, data_axis, n)
    grid = np.array(devices[: client_axis * data_axis]).reshape(
        client_axis, data_axis
    )
    return Mesh(grid, axis_names)


def make_client_mesh(
    n_devices: int | None = None,
    axis_name: str = "clients",
    devices=None,
) -> Mesh:
    """1-D mesh over the client axis — the layout of the sharded
    server-aggregation path (:mod:`fedml_tpu.parallel.sharded_agg`):
    the stacked ``[C, ...]`` client deltas partition row-wise over
    these devices, and only the final params are gathered back.

    ``n_devices=None`` uses every local device (a server process
    aggregating for a world of remote clients owns the whole host's
    accelerators)."""
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if not (1 <= n_devices <= len(devices)):
            raise ValueError(
                f"client mesh wants {n_devices} devices; "
                f"{len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))

"""Parallelism layer: device meshes, client-sharded rounds, hierarchical
aggregation.

The reference's scale-out axes (SURVEY.md §2.7) map onto a
``jax.sharding.Mesh``:

- client/population parallelism (one MPI rank per client) -> shard the
  sampled cohort over the ``clients`` mesh axis;
- intra-silo data parallelism (DDP over NCCL/Gloo,
  ``fedavg_cross_silo/process_group_manager.py``) -> shard the per-client
  batch over the ``data`` axis;
- hierarchical aggregation (``standalone/hierarchical_fl``) -> two-level
  ``psum`` (intra-group then inter-group).

All collectives are XLA collectives riding ICI; no NCCL/MPI anywhere.
"""

from fedml_tpu.parallel.mesh import make_client_mesh, make_mesh
from fedml_tpu.parallel.client_parallel import ShardedFedAvg
from fedml_tpu.parallel.sharded_agg import ShardedAggregator

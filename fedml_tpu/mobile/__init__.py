from fedml_tpu.mobile.export import (  # noqa: F401
    params_from_weight_lists,
    params_to_weight_lists,
    save_weight_lists,
    load_weight_lists,
)

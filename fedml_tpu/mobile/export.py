"""Mobile-client weight exchange: JSON weight lists.

Reference: mobile clients exchange model weights as nested JSON lists
(``is_mobile`` flag; ``fedml_api/distributed/fedavg/utils.py:7-16``
``transform_tensor_to_list`` / ``transform_list_to_tensor``), and the MNN
converters (``fedml_api/model/mobile/mnn_torch.py``) bridge torch
state_dicts to the MNN mobile engine by walking aligned weight lists.

TPU analog: a flax variables pytree <-> nested JSON-able lists, with the
tree structure (paths + shapes + dtypes) carried alongside so the inverse
is exact. This is the wire format an on-device (non-JAX) client can
produce/consume, and the unit the MNN-style converter walks.
"""

from __future__ import annotations

import json
from typing import Any

import jax
import numpy as np


def params_to_weight_lists(variables: Any) -> dict:
    """Pytree -> {"paths": [...], "shapes": [...], "dtypes": [...],
    "weights": [nested lists...]} (reference ``transform_tensor_to_list``,
    generalized to arbitrary pytrees with an exact inverse)."""
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(variables)[0]
    paths, weights, shapes, dtypes = [], [], [], []
    for path, leaf in leaves_with_paths:
        arr = np.asarray(leaf)
        paths.append(jax.tree_util.keystr(path))
        shapes.append(list(arr.shape))
        dtypes.append(str(arr.dtype))
        weights.append(arr.tolist())
    return {
        "paths": paths,
        "shapes": shapes,
        "dtypes": dtypes,
        "weights": weights,
    }


def params_from_weight_lists(template: Any, payload: dict) -> Any:
    """Inverse of :func:`params_to_weight_lists` onto a structure-matching
    template pytree (reference ``transform_list_to_tensor``)."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    assert len(leaves) == len(payload["weights"]), (
        len(leaves), len(payload["weights"])
    )
    new_leaves = [
        np.asarray(w, dtype=np.dtype(dt)).reshape(shape)
        for w, shape, dt in zip(
            payload["weights"], payload["shapes"], payload["dtypes"]
        )
    ]
    for a, b in zip(leaves, new_leaves):
        assert tuple(np.asarray(a).shape) == tuple(b.shape), (
            np.asarray(a).shape, b.shape
        )
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def save_weight_lists(variables: Any, path: str) -> None:
    with open(path, "w") as f:
        json.dump(params_to_weight_lists(variables), f)


def load_weight_lists(template: Any, path: str) -> Any:
    with open(path) as f:
        return params_from_weight_lists(template, json.load(f))

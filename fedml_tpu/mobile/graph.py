"""MNN-style model-graph conversion for mobile clients.

Reference: ``fedml_api/model/mobile/mnn_torch.py:8,27`` converts between
torch state_dicts and the MNN mobile engine's model file by walking a
layer-list description of the network. The TPU analog here is engine-
agnostic: a flax model + variables export to a JSON **graph description**
(ordered op list with attributes + weight tensors by name), and
:class:`NumpyGraphRunner` executes that description with numpy ONLY — the
proof that a non-JAX on-device runtime can consume it. Round-trip
(flax -> graph JSON -> numpy runner) reproduces the flax logits exactly
(tests/test_support.py).

Supported ops cover the mobile zoo (LeNet, the FedAvg-paper CNNs):
``conv2d`` (NHWC, SAME/VALID, arbitrary stride), ``maxpool``, ``relu``,
``flatten``, ``dense``.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

GRAPH_VERSION = 1


# ---------------------------------------------------------------------------
# Graph description
# ---------------------------------------------------------------------------


def _tensor(arr) -> dict:
    arr = np.asarray(arr)
    return {
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "data": arr.ravel().tolist(),
    }


def _untensor(t: dict) -> np.ndarray:
    return np.asarray(t["data"], dtype=np.dtype(t["dtype"])).reshape(
        t["shape"]
    )


class GraphBuilder:
    """Assemble an ordered op list (the converter's walk order IS the
    execution order, like the reference's aligned state_dict walk)."""

    def __init__(self):
        self.ops: list[dict] = []

    def conv2d(self, kernel, bias=None, strides=(1, 1), padding="SAME"):
        self.ops.append(
            {
                "op": "conv2d",
                "strides": list(strides),
                "padding": padding,
                "kernel": _tensor(kernel),  # HWIO
                "bias": _tensor(bias) if bias is not None else None,
            }
        )
        return self

    def maxpool(self, window=(2, 2), strides=(2, 2)):
        self.ops.append(
            {
                "op": "maxpool",
                "window": list(window),
                "strides": list(strides),
            }
        )
        return self

    def relu(self):
        self.ops.append({"op": "relu"})
        return self

    def flatten(self):
        self.ops.append({"op": "flatten"})
        return self

    def dense(self, kernel, bias=None):
        self.ops.append(
            {
                "op": "dense",
                "kernel": _tensor(kernel),
                "bias": _tensor(bias) if bias is not None else None,
            }
        )
        return self

    def build(self, input_shape) -> dict:
        return {
            "format": "fedml_tpu-mobile-graph",
            "version": GRAPH_VERSION,
            "input_shape": list(input_shape),
            "ops": self.ops,
        }


def export_lenet_graph(variables: dict, num_classes: int = 10,
                       input_shape=(28, 28, 1)) -> dict:
    """Flax LeNet (models.vision_extra.LeNet) variables -> graph
    description. The scope walk mirrors the module's __call__ exactly
    (the converter's contract, like ``mnn_torch.py``'s aligned walk)."""
    p = variables["params"]
    b = GraphBuilder()
    b.conv2d(p["Conv2D_0"]["kernel"], p["Conv2D_0"]["bias"])
    b.maxpool().relu()
    b.conv2d(p["Conv2D_1"]["kernel"], p["Conv2D_1"]["bias"])
    b.maxpool().relu()
    b.flatten()
    b.dense(p["Dense_0"]["kernel"], p["Dense_0"]["bias"]).relu()
    b.dense(p["Dense_1"]["kernel"], p["Dense_1"]["bias"])
    return b.build(input_shape)


def import_lenet_variables(graph: dict, template: dict) -> dict:
    """Graph description -> flax LeNet variables (inverse walk): the
    round-trip that lets a mobile-trained graph re-enter the TPU
    aggregation path."""
    convs = [op for op in graph["ops"] if op["op"] == "conv2d"]
    denses = [op for op in graph["ops"] if op["op"] == "dense"]
    p = {
        "Conv2D_0": {"kernel": _untensor(convs[0]["kernel"]),
                     "bias": _untensor(convs[0]["bias"])},
        "Conv2D_1": {"kernel": _untensor(convs[1]["kernel"]),
                     "bias": _untensor(convs[1]["bias"])},
        "Dense_0": {"kernel": _untensor(denses[0]["kernel"]),
                    "bias": _untensor(denses[0]["bias"])},
        "Dense_1": {"kernel": _untensor(denses[1]["kernel"]),
                    "bias": _untensor(denses[1]["bias"])},
    }
    # shape-check against the template tree
    tp = template["params"]
    for scope, leaves in p.items():
        for name, arr in leaves.items():
            want = tuple(np.asarray(tp[scope][name]).shape)
            assert arr.shape == want, (scope, name, arr.shape, want)
    return {"params": p}


def save_graph(graph: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(graph, f)


def load_graph(path: str) -> dict:
    with open(path) as f:
        graph = json.load(f)
    assert graph.get("format") == "fedml_tpu-mobile-graph", "bad file"
    return graph


# ---------------------------------------------------------------------------
# Pure-numpy runtime (the "mobile engine")
# ---------------------------------------------------------------------------


def _pad_same(x: np.ndarray, kh: int, kw: int, sh: int, sw: int):
    h, w = x.shape[1:3]
    oh, ow = -(-h // sh), -(-w // sw)
    ph = max((oh - 1) * sh + kh - h, 0)
    pw = max((ow - 1) * sw + kw - w, 0)
    return np.pad(
        x,
        ((0, 0), (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2),
         (0, 0)),
    )


def _conv2d(x: np.ndarray, k: np.ndarray, strides, padding) -> np.ndarray:
    kh, kw, ci, co = k.shape
    sh, sw = strides
    if padding == "SAME":
        x = _pad_same(x, kh, kw, sh, sw)
    n, h, w, _ = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    # im2col: [n, oh, ow, kh*kw*ci] @ [kh*kw*ci, co]
    s = x.strides
    patches = np.lib.stride_tricks.as_strided(
        x,
        shape=(n, oh, ow, kh, kw, ci),
        strides=(s[0], s[1] * sh, s[2] * sw, s[1], s[2], s[3]),
        writeable=False,
    )
    return patches.reshape(n, oh, ow, kh * kw * ci) @ k.reshape(
        kh * kw * ci, co
    )


def _maxpool(x: np.ndarray, window, strides) -> np.ndarray:
    wh, ww = window
    sh, sw = strides
    n, h, w, c = x.shape
    oh = (h - wh) // sh + 1
    ow = (w - ww) // sw + 1
    s = x.strides
    patches = np.lib.stride_tricks.as_strided(
        x,
        shape=(n, oh, ow, wh, ww, c),
        strides=(s[0], s[1] * sh, s[2] * sw, s[1], s[2], s[3]),
        writeable=False,
    )
    return patches.max(axis=(3, 4))


class NumpyGraphRunner:
    """Execute a graph description with numpy only (no jax/flax import
    anywhere on this path) — the stand-in for the mobile inference
    engine."""

    def __init__(self, graph: dict):
        assert graph.get("version") == GRAPH_VERSION
        self.graph = graph
        # materialize weights once
        self._ops = []
        for op in graph["ops"]:
            op = dict(op)
            for key in ("kernel", "bias"):
                if op.get(key) is not None:
                    op[key] = _untensor(op[key])
            self._ops.append(op)

    def __call__(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        for op in self._ops:
            kind = op["op"]
            if kind == "conv2d":
                x = _conv2d(x, op["kernel"], op["strides"], op["padding"])
                if op.get("bias") is not None:
                    x = x + op["bias"]
            elif kind == "maxpool":
                x = _maxpool(x, op["window"], op["strides"])
            elif kind == "relu":
                x = np.maximum(x, 0.0)
            elif kind == "flatten":
                x = x.reshape(x.shape[0], -1)
            elif kind == "dense":
                x = x @ op["kernel"]
                if op.get("bias") is not None:
                    x = x + op["bias"]
            else:
                raise ValueError(f"unknown op {kind!r}")
        return x

"""CLI entry: ``python -m fedml_tpu.experiments.run ...``.

Replaces the reference's per-algorithm ``main_<algo>.py`` argparse scripts
(``fedml_experiments/{standalone,distributed}/*/main_*.py``) with one typed
entry over the algorithm registry. Config precedence: ``--config`` JSON
(the full :class:`ExperimentConfig` shape) overridden by explicit flags.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

# Honor JAX_PLATFORMS even on hosts whose sitecustomize pins the platform
# via jax.config (where the env var alone is silently ignored). This is
# the general escape hatch for forcing a backend on such hosts — e.g.
# JAX_PLATFORMS=cpu for a deterministic CPU run; when unset, the host's
# default backend is used.
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.experiments.harness import ALGORITHMS, Experiment


def parse_args(argv=None) -> tuple[ExperimentConfig, argparse.Namespace]:
    p = argparse.ArgumentParser(
        prog="fedml_tpu.experiments.run",
        description="TPU-native federated learning experiment runner",
    )
    p.add_argument("--config", type=str, default=None,
                   help="JSON file with the full ExperimentConfig")
    p.add_argument("--algorithm", type=str, default=None,
                   choices=sorted(ALGORITHMS))
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--data_dir", type=str, default=None)
    p.add_argument("--model", type=str, default=None)
    p.add_argument("--num_classes", type=int, default=None)
    p.add_argument("--input_shape", type=int, nargs="+", default=None)
    p.add_argument("--client_num_in_total", type=int, default=None)
    p.add_argument("--client_num_per_round", type=int, default=None)
    p.add_argument("--comm_round", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--client_optimizer", type=str, default=None)
    p.add_argument("--compute_dtype", type=str, default=None,
                   choices=["float32", "bfloat16"],
                   help="mixed-precision compute dtype (params stay f32)")
    p.add_argument("--no_cohort_fused", action="store_true",
                   help="disable the cohort-grouped fast path (always "
                        "vmap the per-client local update)")
    p.add_argument("--partition_method", type=str, default=None)
    p.add_argument("--partition_alpha", type=float, default=None)
    p.add_argument("--frequency_of_the_test", type=int, default=None)
    p.add_argument("--robust_method", type=str, default=None,
                   choices=["mean", "median", "trimmed_mean"])
    p.add_argument("--robust_norm_clip", type=float, default=None)
    p.add_argument("--robust_noise_stddev", type=float, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--repetitions", type=int, default=1)
    p.add_argument("--run_name", type=str, default=None)
    p.add_argument("--out_dir", type=str, default=None)
    p.add_argument("--checkpoint_every", type=int, default=None,
                   help="checkpoint round state every N rounds into "
                        "<out_dir>/<run>/ckpt and resume from the "
                        "latest checkpoint on restart (0 = off)")
    # -- process-separated deployment (reference mpirun/run_server.sh
    # surface: one OS process per rank; scripts/run_distributed.sh is the
    # localhost launcher) --------------------------------------------------
    p.add_argument("--role", type=str, default=None,
                   choices=["server", "client"],
                   help="run ONE deployment rank instead of the local "
                        "simulator (requires --world_size; clients also "
                        "--rank)")
    p.add_argument("--rank", type=int, default=None,
                   help="this process's rank (server=0, clients>=1)")
    p.add_argument("--world_size", type=int, default=None,
                   help="total process count (1 server + N clients)")
    p.add_argument("--backend", type=str, default="grpc",
                   choices=["tcp", "grpc", "trpc", "pubsub", "pubsub_blob"],
                   help="deployment transport backend")
    p.add_argument("--ip_config", type=str, default=None,
                   help='JSON file {"rank": ["host", port], ...} '
                        "(tcp/grpc/trpc backends)")
    p.add_argument("--broker", type=str, default=None,
                   help="host:port of the pub/sub broker daemon "
                        "(pubsub/pubsub_blob backends; start one with "
                        "python -m fedml_tpu.core.transport.broker)")
    p.add_argument("--blob_dir", type=str, default=None,
                   help="shared directory for the file-backed blob store "
                        "(pubsub_blob backend)")
    p.add_argument("--ready_timeout", type=float, default=120.0,
                   help="seconds a client re-announces readiness before "
                        "giving up")
    a = p.parse_args(argv)

    if a.config:
        with open(a.config) as f:
            cfg = ExperimentConfig.from_dict(json.load(f))
    else:
        cfg = ExperimentConfig()

    def rep(obj, **kw):
        kw = {k: v for k, v in kw.items() if v is not None}
        return dataclasses.replace(obj, **kw) if kw else obj

    cfg = rep(
        cfg,
        data=rep(
            cfg.data,
            dataset=a.dataset,
            data_dir=a.data_dir,
            num_clients=a.client_num_in_total,
            # batch_size=-1 == the reference's full-batch `combine_batches`
            # mode (fedml_experiments/standalone/utils/dataset.py:158-164)
            batch_size=None if a.batch_size == -1 else a.batch_size,
            full_batch=True if a.batch_size == -1 else None,
            partition_method=a.partition_method,
            partition_alpha=a.partition_alpha,
        ),
        model=rep(
            cfg.model,
            name=a.model,
            num_classes=a.num_classes,
            input_shape=tuple(a.input_shape) if a.input_shape else None,
        ),
        train=rep(
            cfg.train, lr=a.lr, epochs=a.epochs,
            optimizer=a.client_optimizer,
            compute_dtype=a.compute_dtype,
            cohort_fused=False if a.no_cohort_fused else None,
        ),
        fed=rep(
            cfg.fed,
            algorithm=a.algorithm,
            num_rounds=a.comm_round,
            clients_per_round=a.client_num_per_round,
            eval_every=a.frequency_of_the_test,
            robust_method=a.robust_method,
            robust_norm_clip=a.robust_norm_clip,
            robust_noise_stddev=a.robust_noise_stddev,
        ),
        seed=a.seed,
        run_name=a.run_name,
        out_dir=a.out_dir,
        checkpoint_every=a.checkpoint_every,
    )
    return cfg, a


def _deploy_config(a) -> "DeployConfig":
    from fedml_tpu.experiments.deploy import DeployConfig, load_ip_config

    if a.world_size is None:
        raise SystemExit("--role requires --world_size")
    if a.world_size < 2:
        raise SystemExit(
            "--world_size must be >= 2 (1 server + at least 1 client); "
            "for a single-process run drop --role and use the simulator"
        )
    rank = a.rank if a.rank is not None else (0 if a.role == "server" else None)
    if rank is None:
        raise SystemExit("--role client requires --rank >= 1")
    if a.role == "server" and rank != 0:
        raise SystemExit("server is always rank 0")
    if a.role == "client" and not (1 <= rank < a.world_size):
        raise SystemExit("client rank must be in [1, world_size)")
    broker = None
    if a.broker is not None:
        host, _, port = a.broker.rpartition(":")
        broker = (host, int(port))
    return DeployConfig(
        role=a.role,
        rank=rank,
        world_size=a.world_size,
        backend=a.backend,
        ip_config=load_ip_config(a.ip_config) if a.ip_config else None,
        broker=broker,
        blob_dir=a.blob_dir,
        ready_timeout=a.ready_timeout,
    )


def main(argv=None) -> int:
    cfg, a = parse_args(argv)
    if a.role is not None:
        from fedml_tpu.experiments.deploy import run_role

        print(json.dumps(run_role(cfg, _deploy_config(a)), default=float))
        return 0
    summaries = Experiment(cfg, a.repetitions).run()
    for s in summaries:
        print(json.dumps(s, default=float))
    return 0


if __name__ == "__main__":
    sys.exit(main())

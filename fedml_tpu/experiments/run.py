"""CLI entry: ``python -m fedml_tpu.experiments.run ...``.

Replaces the reference's per-algorithm ``main_<algo>.py`` argparse scripts
(``fedml_experiments/{standalone,distributed}/*/main_*.py``) with one typed
entry over the algorithm registry. Config precedence: ``--config`` JSON
(the full :class:`ExperimentConfig` shape) overridden by explicit flags.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

# Honor JAX_PLATFORMS even on hosts whose sitecustomize pins the platform
# via jax.config (where the env var alone is silently ignored). This is
# the general escape hatch for forcing a backend on such hosts — e.g.
# JAX_PLATFORMS=cpu for a deterministic CPU run; when unset, the host's
# default backend is used.
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.experiments.harness import ALGORITHMS, Experiment

# the FedAvg-family simulators whose compiled round wires in adversary
# injection, the wire codec, round fusion, and bulk streaming — every
# other sim ignores those knobs (main() warns per flag), so their
# compatibility matrices must neither be enforced nor reported there
_ADVERSARY_SIMS = {"fedavg", "fedopt", "fedprox", "fednova",
                   "fedavg_robust", "fedavg_multiclient", "fedseg"}


def parse_args(argv=None) -> tuple[ExperimentConfig, argparse.Namespace]:
    p = argparse.ArgumentParser(
        prog="fedml_tpu.experiments.run",
        description="TPU-native federated learning experiment runner",
    )
    p.add_argument("--config", type=str, default=None,
                   help="JSON file with the full ExperimentConfig")
    p.add_argument("--algorithm", type=str, default=None,
                   choices=sorted(ALGORITHMS))
    p.add_argument("--dataset", type=str, default=None)
    p.add_argument("--data_dir", type=str, default=None)
    p.add_argument("--model", type=str, default=None)
    p.add_argument("--num_classes", type=int, default=None)
    p.add_argument("--input_shape", type=int, nargs="+", default=None)
    p.add_argument("--client_num_in_total", type=int, default=None)
    p.add_argument("--client_num_per_round", type=int, default=None)
    p.add_argument("--comm_round", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--client_optimizer", type=str, default=None)
    # -- server-side optimization (FedOpt family; fedavg.py
    # make_server_optimizer). Previously settable ONLY by hand-editing
    # a --config JSON, which bypassed parse-time validation — the
    # fedlint parse-time-validation rule flagged the gap
    # (docs/STATIC_ANALYSIS.md).
    p.add_argument("--server_optimizer", type=str, default=None,
                   choices=["sgd", "adam", "adagrad", "yogi"],
                   help="server-side optimizer applied to the "
                        "aggregated delta (FedOpt; 'sgd' with "
                        "--server_lr 1.0 == plain FedAvg)")
    p.add_argument("--server_lr", type=float, default=None,
                   help="server optimizer learning rate (> 0)")
    p.add_argument("--server_momentum", type=float, default=None,
                   help="server SGD momentum (in [0, 1))")
    p.add_argument("--gmf", type=float, default=None,
                   help="FedNova global momentum factor (in [0, 1); "
                        "0 disables the momentum buffer)")
    p.add_argument("--compute_dtype", type=str, default=None,
                   choices=["float32", "bfloat16"],
                   help="mixed-precision compute dtype (params stay f32)")
    p.add_argument("--no_cohort_fused", action="store_true",
                   help="disable the cohort-grouped fast path (always "
                        "vmap the per-client local update)")
    p.add_argument("--partition_method", type=str, default=None)
    p.add_argument("--partition_alpha", type=float, default=None)
    p.add_argument("--frequency_of_the_test", type=int, default=None)
    _DEFENSES = ["mean", "median", "trimmed_mean", "krum", "multikrum",
                 "fltrust"]
    p.add_argument("--robust_method", type=str, default=None,
                   choices=_DEFENSES)
    p.add_argument("--defense", type=str, default=None,
                   choices=_DEFENSES,
                   help="aggregation defense rule (alias of "
                        "--robust_method, taking precedence; composes "
                        "with --robust_norm_clip / "
                        "--robust_noise_stddev — see "
                        "docs/FAULT_TOLERANCE.md 'Threat model')")
    p.add_argument("--defense_num_adversaries", type=int, default=None,
                   help="assumed adversary count f for the Krum-family "
                        "defenses (selection keeps the C-f-2 nearest "
                        "neighbors per score)")
    p.add_argument("--defense_multikrum_m", type=int, default=None,
                   help="multi-Krum keep count m (0 = auto: C - f)")
    p.add_argument("--defense_trim_frac", type=float, default=None,
                   help="trimmed_mean per-side trim fraction (raise it "
                        "for small cohorts: floor(0.1*C) trims nobody "
                        "below C=10)")
    p.add_argument("--robust_norm_clip", type=float, default=None)
    p.add_argument("--robust_noise_stddev", type=float, default=None)
    # -- compressed + sharded weight-update path (core/compress.py,
    # parallel/sharded_agg.py; docs/PERFORMANCE.md) -------------------------
    p.add_argument("--compress", type=str, default=None,
                   choices=["none", "int8", "topk", "topk_int8"],
                   help="wire codec for the client->server delta "
                        "payload: int8 absmax quantization, top-k "
                        "sparsification, or both — with client-side "
                        "error feedback so compression error is "
                        "telescoping carry, not bias. 'none' (default) "
                        "keeps the dense wire byte-identical. Applies "
                        "to the fedavg-family sim and --role paths; "
                        "set it identically on EVERY rank of a world")
    p.add_argument("--compress_topk_frac", type=float, default=None,
                   help="fraction of each leaf's entries the topk "
                        "family keeps (>= 1 entry per leaf)")
    p.add_argument("--shard_aggregation", action="store_true",
                   help="server rank: shard the aggregation pass "
                        "(decompress -> clip -> defense-reduce -> "
                        "optimizer step) over the client axis of a "
                        "mesh spanning this host's devices, "
                        "all-gathering only the final params "
                        "(parallel/sharded_agg.py; the sims' sharded "
                        "runtime is ShardedFedAvg)")
    # -- async + tiered aggregation (core/async_agg.py, core/tier.py;
    # docs/FAULT_TOLERANCE.md "Async + tiered worlds") ---------------------
    p.add_argument("--async_buffer_k", type=int, default=None,
                   help="server rank: FedBuff-style buffered-async "
                        "aggregation — fold every arriving screened "
                        "delta into a staleness-weighted buffer and "
                        "emit a new model every K arrivals, re-syncing "
                        "each client individually the moment its "
                        "result lands (no round barrier; a slow "
                        "client never blocks a fast one). 0 (default) "
                        "keeps the synchronous rounds byte-identical")
    p.add_argument("--staleness_fn", type=str, default=None,
                   choices=["poly", "const"],
                   help="staleness discount for async folds: poly = "
                        "(1+lag)^-alpha over the version lag, const = "
                        "full weight for every arrival")
    p.add_argument("--staleness_alpha", type=float, default=None,
                   help="exponent of the poly staleness discount "
                        "(0.5 = the FedAsync default)")
    p.add_argument("--tier_spec", type=str, default=None,
                   help="tier topology, e.g. root:2 — one root "
                        "aggregator serving 2 leaf aggregators, each "
                        "leaf terminating its own clients' transports "
                        "in its own world and forwarding one partial "
                        "[sum, n, count] upstream per flush. Set on "
                        "the root (--role server) and every leaf "
                        "(--role leaf); clients are topology-blind")
    p.add_argument("--uplink_ip_config", type=str, default=None,
                   help="leaf rank: the ROOT world's rank table "
                        "(--ip_config stays this leaf's own world, "
                        "where it is rank 0)")
    p.add_argument("--tier_client_base", type=int, default=None,
                   help="leaf rank: global client id of this leaf's "
                        "slot 0 (default: contiguous equal-size "
                        "blocks per leaf rank)")
    # -- parameter-efficient fine-tuning (fedml_tpu.peft;
    # docs/PERFORMANCE.md "Parameter-efficient federated
    # fine-tuning") --------------------------------------------------------
    p.add_argument("--peft", type=str, default=None,
                   choices=["none", "lora"],
                   help="parameter-efficient fine-tuning: 'lora' "
                        "wraps the transformer's targeted Dense "
                        "projections with zero-init low-rank "
                        "branches and trains/aggregates ONLY the "
                        "adapter + LM-head subtree — the frozen base "
                        "takes no optimizer state, builds no delta, "
                        "and ships no wire bytes (composes "
                        "multiplicatively with --compress). "
                        "Transformer models + FedAvg-family sims "
                        "only; round 0 is byte-identical to the base "
                        "model")
    p.add_argument("--lora_rank", type=int, default=None,
                   help="LoRA rank r (>= 1); the adapter branch is "
                        "(alpha/r) * x A B with A [in, r] seeded and "
                        "B [r, out] zero-init")
    p.add_argument("--lora_alpha", type=float, default=None,
                   help="LoRA scale alpha (> 0)")
    p.add_argument("--lora_targets", type=str, nargs="+", default=None,
                   help="which named TransformerLM projections get "
                        "adapters (subset of q_proj k_proj v_proj "
                        "attn_out mlp_up mlp_down; default: the "
                        "classic q_proj v_proj pair); resolved "
                        "against the model's Dense names at parse "
                        "time")
    p.add_argument("--peft_personalize", action="store_true",
                   help="keep each client's adapters in a PRIVATE "
                        "per-client bank — only the shared LM head "
                        "aggregates; client i's adapters never reach "
                        "the server or client j "
                        "(fedml_tpu.peft.personal). The bank is a "
                        "client-state bank (core/statebank.py), so it "
                        "composes with --client_block_size, "
                        "--elastic, --fuse_rounds, the sharded "
                        "runtime, and --checkpoint_every; compress / "
                        "defended robust_method / adversary combos "
                        "are rejected at parse time")
    # -- seeded Byzantine adversary injection (core/adversary.py) ----------
    p.add_argument("--adversary_mode", type=str, default=None,
                   choices=["none", "sign_flip", "scale_boost", "gauss",
                            "zero", "constant", "collude"],
                   help="make selected clients emit malicious deltas "
                        "(simulator: client ids; deployment: worker "
                        "ranks). Deterministic given --adversary_seed")
    p.add_argument("--adversary_seed", type=int, default=None,
                   help="seed for the adversary stream (selection + "
                        "corruption draws)")
    p.add_argument("--adversary_ranks", type=int, nargs="+",
                   default=None,
                   help="explicit adversarial identities (client ids "
                        "on the simulator path, ranks >= 1 under "
                        "--role); overrides --adversary_num")
    p.add_argument("--adversary_num", type=int, default=None,
                   help="seeded choice of this many adversaries when "
                        "--adversary_ranks is not given")
    p.add_argument("--adversary_scale", type=float, default=None,
                   help="attack magnitude (sign_flip/scale_boost "
                        "multiplier, constant fill, collude delta norm)")
    p.add_argument("--adversary_noise", type=float, default=None,
                   help="gauss-mode perturbation stddev")
    # -- cross-round reputation / quarantine (server rank) -----------------
    p.add_argument("--quarantine_threshold", type=float, default=0.0,
                   help="EWMA anomaly score above which a client is "
                        "quarantined — excluded from aggregation but "
                        "still served, so a false positive can earn "
                        "its way back (0 = off; server rank, fedavg "
                        "family; survives server restarts via "
                        "--checkpoint_every)")
    p.add_argument("--quarantine_decay", type=float, default=0.7,
                   help="EWMA memory for the reputation score "
                        "(higher = slower to trip and to forgive)")
    p.add_argument("--quarantine_evict_after", type=int, default=0,
                   help="rounds a rank may sit in quarantine without "
                        "earning release before it is PERMANENTLY "
                        "evicted from the membership ledger (0 = "
                        "never; docs/FAULT_TOLERANCE.md 'Elastic "
                        "membership')")
    # -- elastic membership / shape bucketing ------------------------------
    p.add_argument("--elastic", action="store_true",
                   help="elastic world: pad cohorts to power-of-two "
                        "buckets so membership churn (mid-run client "
                        "admission via JOIN from ranks >= world_size, "
                        "graceful --leave_after_round departures) "
                        "costs a compile-cache hit instead of an XLA "
                        "recompile; rides config.json as "
                        "fed.elastic_buckets")
    p.add_argument("--leave_after_round", type=int, default=None,
                   help="client rank: after submitting the result for "
                        "this round, announce a graceful LEAVE and "
                        "exit 0 (no dead-peer suspicion, no restart "
                        "budget spent)")
    p.add_argument("--presumed_left", type=int, nargs="*", default=(),
                   help="server rank, set by the supervisor on a "
                        "restart: ranks whose final summary reported a "
                        "departure — marked LEFT before the ready "
                        "barrier even when the restored checkpoint "
                        "predates the LEAVE (they are never respawned, "
                        "so waiting would hang the relaunch)")
    p.add_argument("--presumed_evicted", type=int, nargs="*",
                   default=(),
                   help="server rank, set by the supervisor on a "
                        "restart: ranks whose final summary reported "
                        "an EVICTION — re-evicted before the ready "
                        "barrier even when the restored checkpoint "
                        "predates the ban (marking them merely LEFT "
                        "would let the banned rank JOIN back in)")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--repetitions", type=int, default=1)
    p.add_argument("--run_name", type=str, default=None)
    p.add_argument("--out_dir", type=str, default=None)
    p.add_argument("--checkpoint_every", type=int, default=None,
                   help="checkpoint round state every N rounds into "
                        "<out_dir>/<run>/ckpt and resume from the "
                        "latest checkpoint on restart (0 = off; works "
                        "for the simulator AND the fedavg-family "
                        "--role server deployment path; splitnn "
                        "deployments do not checkpoint)")
    # -- telemetry (docs/OBSERVABILITY.md) ---------------------------------
    p.add_argument("--telemetry_dir", type=str, default=None,
                   help="enable the telemetry plane and write THIS "
                        "rank's artifacts here: trace_rank<r>.json span "
                        "dump, metrics_rank<r>.json snapshot, "
                        "flight_rank<r>_*.json crash rings; merge the "
                        "span dumps with scripts/merge_trace.py")
    p.add_argument("--trace", action="store_true",
                   help="enable span tracing + metrics without naming a "
                        "directory (dumps to <out_dir>/<run>/telemetry; "
                        "implied by --telemetry_dir)")
    p.add_argument("--trace_jax", action="store_true",
                   help="additionally wrap tracer spans in "
                        "jax.profiler.TraceAnnotation so device "
                        "timelines line up with host spans in a jax "
                        "profile")
    # -- round fusion (core/fuse.py; docs/PERFORMANCE.md "Round
    # fusion") --------------------------------------------------------------
    p.add_argument("--fuse_rounds", type=int, default=None,
                   help="simulator: run K complete rounds as ONE "
                        "compiled program (a lax.scan over the round "
                        "body, state + error-feedback residual as "
                        "donated carries) with per-block host metric "
                        "consumption — the MFU-recovery path. Cohort "
                        "sampling inside the fused block is bitwise-"
                        "identical to the unfused loop; eval/"
                        "checkpoint rounds force a block boundary. 1 "
                        "(default) keeps the per-round loop byte-"
                        "identical. FedAvg-family sims only")
    # -- bulk-client streaming (core/bulk.py; docs/PERFORMANCE.md
    # "Bulk-client execution") ---------------------------------------------
    p.add_argument("--client_block_size", type=int, default=None,
                   help="simulator: stream the sampled cohort through "
                        "the device in fixed-size blocks of B clients "
                        "(the device-resident bulk-client engine): "
                        "each block runs the vmapped local update and "
                        "is folded into an O(model) partial-sum scan "
                        "carry, so round memory is O(B + model) "
                        "instead of O(cohort) — the 10k-client-real-"
                        "training path. Composes with --elastic "
                        "(block-count buckets), --fuse_rounds (nested "
                        "scans), --compress (client-id-keyed error-"
                        "feedback bank, core/statebank.py), "
                        "--peft_personalize (streamed adapter bank), "
                        "every --robust_method (streamed defense "
                        "sketches, core/streamdef.py), and every "
                        "adversary mode. 0/unset = the stacked "
                        "[C, ...] round")
    # -- performance observability (docs/OBSERVABILITY.md) -----------------
    p.add_argument("--profile_rounds", type=int, default=None,
                   help="capture a jax.profiler window around each of "
                        "the first K compiled rounds and parse it into "
                        "a per-round device-time breakdown (compute/"
                        "collective/host/idle) under "
                        "<telemetry_dir>/jax_profile/, plus live "
                        "perf.* gauges (round rate, MFU, dispatch-"
                        "bound detector) for the whole run; composes "
                        "with --trace_jax (span annotations land "
                        "inside the captures). Implies telemetry.")
    p.add_argument("--metrics_interval", type=float, default=None,
                   help="seconds between periodic metrics snapshots "
                        "appended to metrics_rank<r>.jsonl in the "
                        "telemetry dir (round-latency SLO time "
                        "series: histograms carry p50/p95/p99); "
                        "implies telemetry")
    # -- memory observability (core/memscope.py; docs/OBSERVABILITY.md
    # "Memory & compilation") ----------------------------------------------
    p.add_argument("--mem_headroom_warn", type=float, default=None,
                   help="used fraction of device HBM capacity at which "
                        "the memory monitor leaves its one "
                        "mem_headroom flight-recorder event (default "
                        "0.9). The monitor itself rides the telemetry "
                        "plane: per-device mem.bytes_in_use/"
                        "mem.peak_bytes gauges at round boundaries, "
                        "per-program mem.program.* accounting at every "
                        "compile, RSS fallback on backends without "
                        "memory_stats")
    # -- live observability plane (core/export.py, core/slo.py;
    # docs/OBSERVABILITY.md "Live export and SLOs") -------------------------
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve THIS rank's live metrics over HTTP: "
                        "/metrics (OpenMetrics text a stock Prometheus "
                        "scrape parses, with real histogram buckets "
                        "and the fleet.* aggregates federated from "
                        "client heartbeats), /statusz (JSON run "
                        "introspection: round, membership, async "
                        "buffer, SLO verdicts), /healthz — all on one "
                        "stdlib listener. 0 binds an ephemeral port "
                        "(read it back from export_rank<r>.json in "
                        "the telemetry dir); unset (default) opens no "
                        "socket. Implies telemetry")
    p.add_argument("--metrics_host", type=str, default="0.0.0.0",
                   help="interface the metrics listener binds "
                        "(default 0.0.0.0 so a remote Prometheus can "
                        "scrape; the endpoints are unauthenticated "
                        "and /statusz exposes run introspection — on "
                        "a shared network bind 127.0.0.1)")
    p.add_argument("--slo", action="append", default=None,
                   metavar="SPEC",
                   help="declarative SLO (repeatable), e.g. "
                        "'perf.round_wall_s:p99<2.0@60s': metric, "
                        "statistic (p50/p95/p99/mean/max/min over the "
                        "window, 'value' for gauges, 'rate' for "
                        "counters), healthy relation, threshold, "
                        "window. Evaluated on the metrics time-series "
                        "cadence; exports slo.ok/slo.breach_seconds/"
                        "slo.burn_rate gauges, records ONE flight "
                        "event per breach transition, and writes "
                        "slo_rank<r>.json verdicts at shutdown. "
                        "Implies telemetry")
    # -- round anatomy + breach-triggered deep profiling
    # (core/anatomy.py; docs/OBSERVABILITY.md "Round anatomy") -------------
    p.add_argument("--anatomy", action="store_true",
                   help="enable the round-anatomy plane: per-phase "
                        "wall-time attribution (perf.phase.* "
                        "histograms + dominant-phase gauge) timed at "
                        "the sync points each round path already has, "
                        "a last-N-rounds /tracez ring on the "
                        "--metrics_port listener, and cross-rank "
                        "straggler/critical-path accounting on the "
                        "deploy server. Off (default) costs one "
                        "attribute check per round and keeps results "
                        "byte-identical. Implies telemetry")
    p.add_argument("--profile_on_breach", action="store_true",
                   help="arm a one-shot jax.profiler deep-profile "
                        "window fired on an SLO breach TRANSITION or "
                        "the mem_headroom crossing, written under "
                        "<telemetry_dir>/profiles/ with a flight "
                        "event linking breach -> artifact path. "
                        "Requires an armed breach source (--slo or "
                        "--mem_headroom_warn); rank 0 only under "
                        "--supervise (like --metrics_port). Capture "
                        "never extends a round deadline. Implies "
                        "telemetry")
    p.add_argument("--profile_window_s", type=float, default=None,
                   help="breach-profile capture window in seconds "
                        "(> 0; default 5)")
    p.add_argument("--profile_max_captures", type=int, default=None,
                   help="lifetime cap on breach-profile captures "
                        "(>= 1; default 3) — re-armed breaches after "
                        "the cap are counted in profile.skipped, "
                        "never captured")
    # -- process-separated deployment (reference mpirun/run_server.sh
    # surface: one OS process per rank; scripts/run_distributed.sh is the
    # localhost launcher) --------------------------------------------------
    p.add_argument("--role", type=str, default=None,
                   choices=["server", "client", "leaf"],
                   help="run ONE deployment rank instead of the local "
                        "simulator (requires --world_size; clients and "
                        "leaf aggregators also --rank)")
    p.add_argument("--rank", type=int, default=None,
                   help="this process's rank (server=0, clients>=1)")
    p.add_argument("--world_size", type=int, default=None,
                   help="total process count (1 server + N clients)")
    p.add_argument("--backend", type=str, default="grpc",
                   choices=["tcp", "grpc", "trpc", "pubsub", "pubsub_blob"],
                   help="deployment transport backend")
    p.add_argument("--ip_config", type=str, default=None,
                   help='JSON file {"rank": ["host", port], ...} '
                        "(tcp/grpc/trpc backends)")
    p.add_argument("--broker", type=str, default=None,
                   help="host:port of the pub/sub broker daemon "
                        "(pubsub/pubsub_blob backends; start one with "
                        "python -m fedml_tpu.core.transport.broker)")
    p.add_argument("--blob_dir", type=str, default=None,
                   help="shared directory for the file-backed blob store "
                        "(pubsub_blob backend)")
    p.add_argument("--ready_timeout", type=float, default=120.0,
                   help="seconds a client re-announces readiness before "
                        "giving up")
    # -- fault tolerance (docs/FAULT_TOLERANCE.md) -------------------------
    p.add_argument("--no_heartbeats", action="store_true",
                   help="disable the liveness protocol (heartbeats + "
                        "dead-peer detection)")
    p.add_argument("--heartbeat_interval", type=float, default=2.0,
                   help="seconds between liveness beacons")
    p.add_argument("--heartbeat_timeout", type=float, default=30.0,
                   help="seconds of peer silence before it is declared "
                        "dead")
    p.add_argument("--quorum_fraction", type=float, default=1.0,
                   help="fraction of live workers whose results close a "
                        "round once --round_deadline expires (server "
                        "rank; fedavg family)")
    p.add_argument("--round_deadline", type=float, default=None,
                   help="per-round wall-clock budget in seconds: at "
                        "expiry the round closes with >= quorum results "
                        "or the run aborts (0/unset = no deadline)")
    # -- crash recovery (docs/FAULT_TOLERANCE.md "Recovery") ---------------
    p.add_argument("--recovery_extensions", type=int, default=0,
                   help="times a round deadline that expires UNDER "
                        "quorum re-arms (waiting for restarted ranks "
                        "to rejoin) before the quorum-lost abort fires")
    p.add_argument("--supervise", action="store_true",
                   help="launch ALL ranks of the deployment on this "
                        "host under a Supervisor that restarts crashed "
                        "processes with capped backoff (requires "
                        "--world_size; do not pass --role/--rank)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="per-rank restart budget under --supervise")
    # -- seeded fault injection for THIS rank (chaos testing) --------------
    p.add_argument("--fault_seed", type=int, default=0,
                   help="seed for the deterministic fault stream")
    p.add_argument("--fault_drop", type=float, default=0.0,
                   help="per-message send drop probability")
    p.add_argument("--fault_delay", type=float, default=0.0,
                   help="per-message send delay probability")
    p.add_argument("--fault_delay_max", type=float, default=0.05,
                   help="max injected delay in seconds")
    p.add_argument("--fault_dup", type=float, default=0.0,
                   help="per-message duplication probability")
    p.add_argument("--fault_reorder", type=float, default=0.0,
                   help="per-message reorder probability")
    p.add_argument("--fault_corrupt", type=float, default=0.0,
                   help="per-message payload bit-flip probability "
                        "(seeded; the CRC32 frame checksum on the "
                        "tcp/pubsub codecs detects and drops the "
                        "frame — transport.corrupt_frames — and the "
                        "retry/straggler machinery heals the loss)")
    p.add_argument("--fault_crash_round", type=int, default=None,
                   help="crash this rank on the first message tagged "
                        "with round_idx >= N")
    p.add_argument("--fault_crash_mode", type=str, default="silent",
                   choices=["silent", "exit"],
                   help="silent: the rank stops communicating; exit: "
                        "the process dies (os._exit) like kill -9")
    # the shared registration checker (fedml_tpu/analysis/flags.py):
    # run.py OWNS the reserved --slo/--metrics_port names, so owner
    # mode asserts they are registered AND nothing is duplicated —
    # bench.py and the supervisor run the non-owner side of the same
    # contract
    from fedml_tpu.analysis.flags import check_flag_registry

    check_flag_registry(p, owner=True,
                        entrypoint="fedml_tpu.experiments.run")
    a = p.parse_args(argv)

    if a.config:
        with open(a.config) as f:
            cfg = ExperimentConfig.from_dict(json.load(f))
    else:
        cfg = ExperimentConfig()

    def rep(obj, **kw):
        kw = {k: v for k, v in kw.items() if v is not None}
        return dataclasses.replace(obj, **kw) if kw else obj

    cfg = rep(
        cfg,
        data=rep(
            cfg.data,
            dataset=a.dataset,
            data_dir=a.data_dir,
            num_clients=a.client_num_in_total,
            # batch_size=-1 == the reference's full-batch `combine_batches`
            # mode (fedml_experiments/standalone/utils/dataset.py:158-164)
            batch_size=None if a.batch_size == -1 else a.batch_size,
            full_batch=True if a.batch_size == -1 else None,
            partition_method=a.partition_method,
            partition_alpha=a.partition_alpha,
        ),
        model=rep(
            cfg.model,
            name=a.model,
            num_classes=a.num_classes,
            input_shape=tuple(a.input_shape) if a.input_shape else None,
        ),
        train=rep(
            cfg.train, lr=a.lr, epochs=a.epochs,
            optimizer=a.client_optimizer,
            compute_dtype=a.compute_dtype,
            cohort_fused=False if a.no_cohort_fused else None,
        ),
        fed=rep(
            cfg.fed,
            algorithm=a.algorithm,
            num_rounds=a.comm_round,
            clients_per_round=a.client_num_per_round,
            eval_every=a.frequency_of_the_test,
            server_optimizer=a.server_optimizer,
            server_lr=a.server_lr,
            server_momentum=a.server_momentum,
            gmf=a.gmf,
            robust_method=a.defense or a.robust_method,
            robust_norm_clip=a.robust_norm_clip,
            robust_noise_stddev=a.robust_noise_stddev,
            robust_num_adversaries=a.defense_num_adversaries,
            robust_multikrum_m=a.defense_multikrum_m,
            robust_trim_frac=a.defense_trim_frac,
            elastic_buckets=True if a.elastic else None,
            async_buffer_k=a.async_buffer_k,
            staleness_fn=a.staleness_fn,
            staleness_alpha=a.staleness_alpha,
            compress=a.compress,
            compress_topk_frac=a.compress_topk_frac,
            shard_aggregation=True if a.shard_aggregation else None,
            profile_rounds=a.profile_rounds,
            mem_headroom_warn=a.mem_headroom_warn,
            client_block_size=a.client_block_size,
            fuse_rounds=a.fuse_rounds,
            slos=tuple(a.slo) if a.slo else None,
            anatomy=True if a.anatomy else None,
            profile_on_breach=True if a.profile_on_breach else None,
            profile_window_s=a.profile_window_s,
            profile_max_captures=a.profile_max_captures,
            peft=a.peft,
            lora_rank=a.lora_rank,
            lora_alpha=a.lora_alpha,
            lora_targets=(
                tuple(a.lora_targets) if a.lora_targets else None
            ),
            peft_personalize=True if a.peft_personalize else None,
        ),
        adversary=rep(
            cfg.adversary,
            mode=a.adversary_mode,
            seed=a.adversary_seed,
            ranks=tuple(a.adversary_ranks) if a.adversary_ranks else None,
            num_adversaries=a.adversary_num,
            scale=a.adversary_scale,
            noise_stddev=a.adversary_noise,
        ),
        seed=a.seed,
        run_name=a.run_name,
        out_dir=a.out_dir,
        checkpoint_every=a.checkpoint_every,
    )
    # surface defense/quarantine/adversary config errors at argument
    # time (unconditionally — e.g. a bad --quarantine_decay with the
    # threshold off would otherwise crash the server actor at
    # construction): under --supervise a construction-time ValueError
    # would crash-loop the server through its whole restart budget
    from fedml_tpu.core.compress import CompressionSpec
    from fedml_tpu.core.reputation import QuarantinePolicy
    from fedml_tpu.core.robust import DefensePipeline, check_fednova_compat

    from fedml_tpu.core.async_agg import AsyncConfig
    from fedml_tpu.core.tier import TierSpec

    if cfg.fed.fuse_rounds < 1:
        raise SystemExit(
            f"--fuse_rounds must be >= 1, got {cfg.fed.fuse_rounds}"
        )
    try:
        # server-optimizer plane: validate HERE, not at first round
        # close where a supervised server would crash-loop its restart
        # budget (the fednova+defense lesson; fedlint
        # parse-time-validation)
        from fedml_tpu.algorithms.fedavg import make_server_optimizer

        make_server_optimizer(cfg.fed.server_optimizer,
                              cfg.fed.server_lr,
                              cfg.fed.server_momentum)
        if cfg.fed.server_lr <= 0:
            raise ValueError(
                f"--server_lr must be > 0, got {cfg.fed.server_lr}"
            )
        if not (0.0 <= cfg.fed.server_momentum < 1.0):
            raise ValueError(
                f"--server_momentum must be in [0, 1), got "
                f"{cfg.fed.server_momentum}"
            )
        if not (0.0 <= cfg.fed.gmf < 1.0):
            raise ValueError(
                f"--gmf must be in [0, 1), got {cfg.fed.gmf}"
            )
        DefensePipeline.from_fed(cfg.fed)
        CompressionSpec.from_fed(cfg.fed)
        QuarantinePolicy(threshold=a.quarantine_threshold,
                         decay=a.quarantine_decay,
                         evict_after=a.quarantine_evict_after)
        check_fednova_compat(cfg.fed.algorithm, cfg.fed.robust_method)
        AsyncConfig.from_fed(cfg.fed)
        # bulk-client streaming: the PR-14 composition walls (selection
        # defenses, compress, the gauss adversary) have fallen — the
        # client-state banks and streamed defense sketches carry them —
        # so check_bulk_compat accepts everything; it stays called as
        # the parse-time seam (fedlint parse-time-validation
        # discipline) for any future wall. Only for processes that
        # will actually RUN a simulator: under --role/--supervise the
        # flag is inert (warned below).
        from fedml_tpu.core.bulk import BulkSpec, check_bulk_compat

        bulk = BulkSpec.from_fed(cfg.fed)
        if bulk.enabled() and a.role is None and not a.supervise \
                and cfg.fed.algorithm in _ADVERSARY_SIMS:
            check_bulk_compat(cfg.fed, cfg.adversary)
            if bulk.block_size >= cfg.fed.clients_per_round:
                print(
                    f"warning: --client_block_size "
                    f"{bulk.block_size} >= clients_per_round "
                    f"{cfg.fed.clients_per_round}: the whole cohort "
                    "fits one block — the stacked round "
                    "(client_block_size=0) compiles the same work "
                    "without the streaming wrapper and wins",
                    file=sys.stderr,
                )
        # PEFT/LoRA: the whole spec (rank >= 1, alpha > 0, targets
        # resolved against the model's Dense names) and the
        # personalization compatibility matrix fail HERE, not at
        # simulator construction (fedlint parse-time-validation
        # discipline). Algorithm families outside the FedAvg-family
        # round program would silently fine-tune the FULL model under
        # a 'lora' label — rejected, not warned. Like the bulk gate
        # above, the matrix applies only to processes that will RUN a
        # simulator: under --role/--supervise the flag is inert
        # (warned below, keyed on the merged config) and a shared
        # sim-oriented config must not hard-fail a rank PEFT cannot
        # affect.
        from fedml_tpu.config import FedConfig as _FC

        _fd = _FC()  # field defaults, to detect MERGED-config drift
        if cfg.fed.peft == "none" and not cfg.fed.peft_personalize \
                and (cfg.fed.lora_rank != _fd.lora_rank
                     or cfg.fed.lora_alpha != _fd.lora_alpha
                     or cfg.fed.lora_targets != _fd.lora_targets):
            # lora_* knobs without peft='lora' — keyed on the MERGED
            # config (a --config JSON carrying lora_* but no peft key
            # is the same footgun as the bare flags): say so loudly
            # rather than letting the user think a LoRA run was
            # configured
            print(
                "warning: lora_rank/lora_alpha/lora_targets are "
                "inert without peft='lora' — this run fine-tunes the "
                "FULL model",
                file=sys.stderr,
            )
        if cfg.fed.peft != "none" or cfg.fed.peft_personalize:
            from fedml_tpu.peft import (
                LoRASpec, check_model_supported, check_peft_compat,
            )

            LoRASpec.from_fed(cfg.fed)
            if a.role is not None or a.supervise:
                # PEFT covers the compiled simulators only; the deploy
                # actors ship full deltas. Keyed on the MERGED config
                # (not the bare CLI flag) so a --config JSON carrying
                # fed.peft cannot silently measure full fine-tuning
                # under a 'lora' label.
                print(
                    "warning: peft covers the compiled simulators "
                    "(FedAvgSim/ShardedFedAvg) and is inert under "
                    "--role/--supervise — this deployment trains and "
                    "ships the FULL model (docs/PERFORMANCE.md "
                    "'Parameter-efficient federated fine-tuning')",
                    file=sys.stderr,
                )
            else:
                check_peft_compat(cfg.fed, cfg.adversary,
                                  checkpoint_every=cfg.checkpoint_every)
                check_model_supported(cfg.model.name)
                if cfg.fed.algorithm not in _ADVERSARY_SIMS:
                    raise ValueError(
                        f"--peft covers the FedAvg-family compiled "
                        f"round ({sorted(_ADVERSARY_SIMS)}); the "
                        f"{cfg.fed.algorithm!r} simulator would "
                        "silently fine-tune the full model under a "
                        "'lora' label"
                    )
        if cfg.fed.slos:
            from fedml_tpu.core.slo import parse_specs

            parse_specs(cfg.fed.slos)
        if a.metrics_port is not None and not (
                0 <= a.metrics_port < 65536):
            raise ValueError(
                f"--metrics_port must be in [0, 65535] (0 = "
                f"ephemeral), got {a.metrics_port}"
            )
        if not (0.0 < cfg.fed.mem_headroom_warn <= 1.0):
            raise ValueError(
                f"--mem_headroom_warn is a used FRACTION of device "
                f"memory in (0, 1], got {cfg.fed.mem_headroom_warn}"
            )
        # breach profiling (core/anatomy.py BreachProfiler): keyed on
        # the MERGED config so a --config JSON carrying the knobs gets
        # the same parse-time gate as the bare flags (fedlint
        # parse-time-validation discipline)
        if cfg.fed.profile_window_s <= 0:
            raise ValueError(
                f"--profile_window_s must be > 0, got "
                f"{cfg.fed.profile_window_s}"
            )
        if cfg.fed.profile_max_captures < 1:
            raise ValueError(
                f"--profile_max_captures must be >= 1, got "
                f"{cfg.fed.profile_max_captures}"
            )
        if cfg.fed.profile_on_breach and not cfg.fed.slos \
                and a.mem_headroom_warn is None:
            # without a breach SOURCE the armed profiler can never
            # fire — the operator thinks deep profiles are coming and
            # none ever do
            raise ValueError(
                "--profile_on_breach needs an armed breach source: "
                "add --slo spec(s) and/or an explicit "
                "--mem_headroom_warn threshold"
            )
        if (cfg.fed.profile_window_s != 5.0
                or cfg.fed.profile_max_captures != 3) \
                and not cfg.fed.profile_on_breach:
            print(
                "warning: --profile_window_s/--profile_max_captures "
                "are inert without --profile_on_breach",
                file=sys.stderr,
            )
        if a.tier_spec is not None:
            TierSpec.parse(a.tier_spec)
        from fedml_tpu.algorithms.async_actors import check_async_compat

        check_async_compat(cfg)
    except ValueError as err:
        raise SystemExit(str(err))
    return cfg, a


def _parse_broker(value: str) -> tuple[str, int]:
    """``host:port`` -> tuple, with a clear SystemExit on malformed input
    (a bare ``--broker localhost`` used to crash with a ValueError
    traceback from ``int('localhost')``)."""
    host, sep, port = value.rpartition(":")
    if not sep or not host or not port.isdigit():
        raise SystemExit(
            f"--broker expects host:port (e.g. 127.0.0.1:29950), "
            f"got {value!r}"
        )
    port_num = int(port)
    if not (0 < port_num < 65536):
        raise SystemExit(
            f"--broker port must be in [1, 65535], got {port_num}"
        )
    return host, port_num


def _fault_policy(a) -> "FaultPolicy | None":
    from fedml_tpu.core.transport.chaos import FaultPolicy

    policy = FaultPolicy(
        seed=a.fault_seed,
        drop_prob=a.fault_drop,
        delay_prob=a.fault_delay,
        delay_max_s=a.fault_delay_max,
        dup_prob=a.fault_dup,
        reorder_prob=a.fault_reorder,
        corrupt_prob=a.fault_corrupt,
        crash_at_round=a.fault_crash_round,
        crash_mode=a.fault_crash_mode,
    )
    return policy if policy.enabled() else None


def _deploy_config(a) -> "DeployConfig":
    from fedml_tpu.experiments.deploy import DeployConfig, load_ip_config

    if a.world_size is None:
        raise SystemExit("--role requires --world_size")
    if a.world_size < 2:
        raise SystemExit(
            "--world_size must be >= 2 (1 server + at least 1 client); "
            "for a single-process run drop --role and use the simulator"
        )
    rank = a.rank if a.rank is not None else (0 if a.role == "server" else None)
    if rank is None:
        raise SystemExit(f"--role {a.role} requires --rank >= 1")
    if a.role == "server" and rank != 0:
        raise SystemExit("server is always rank 0")
    if a.role == "client" and rank < 1:
        raise SystemExit("client rank must be >= 1")
    if a.role == "leaf":
        # a leaf aggregator lives in TWO worlds: rank 0 of its own
        # leaf world (--ip_config) and member rank of the root world
        # (--uplink_ip_config) — docs/FAULT_TOLERANCE.md "Async +
        # tiered worlds"
        if not a.tier_spec:
            raise SystemExit("--role leaf requires --tier_spec")
        if not a.uplink_ip_config:
            raise SystemExit(
                "--role leaf requires --uplink_ip_config (the root "
                "world's rank table; --ip_config is this leaf's own "
                "client-facing world)"
            )
        from fedml_tpu.core.tier import TierSpec

        try:
            spec = TierSpec.parse(a.tier_spec)
        except ValueError as err:
            raise SystemExit(str(err))
        if not (1 <= rank <= spec.n_leaves):
            raise SystemExit(
                f"leaf rank must be in 1..{spec.n_leaves} of the root "
                f"world ({a.tier_spec}), got {rank}"
            )
        if a.backend not in ("tcp", "grpc", "trpc"):
            raise SystemExit(
                "tier worlds need a rank-addressed backend "
                "(tcp/grpc/trpc): the pub/sub topic space cannot host "
                "two overlapping rank worlds on one broker"
            )
    if (a.role == "client" and rank >= a.world_size
            and not a.elastic):
        # a rank beyond the launch world is a mid-run ADMISSION — it
        # only makes sense against an elastic server, whose membership
        # ledger will admit the JOIN (docs/FAULT_TOLERANCE.md "Elastic
        # membership"); a static server drops it and this client would
        # time out
        raise SystemExit(
            f"client rank {rank} is outside the launch world "
            f"[1, {a.world_size}); joining a running world mid-run "
            "requires --elastic (on BOTH the server and this client)"
        )
    # simulator-only knobs are silently inert under --role — say so
    # loudly rather than letting the user think they took effect
    if a.profile_rounds:
        print(
            "warning: --profile_rounds capture windows cover the "
            "simulator paths; under --role the aggregation path "
            "reports perf.agg_wall_s / perf.host_wait_s / idle-gap "
            "signals instead (docs/OBSERVABILITY.md 'Performance "
            "observability')",
            file=sys.stderr,
        )
    if a.fuse_rounds and a.fuse_rounds > 1:
        # rounds on the deploy path close on the transport barrier —
        # there is no compiled multi-round program to fuse
        print(
            "warning: --fuse_rounds covers the compiled simulator "
            "round loop and is inert under --role (deploy rounds "
            "close on the transport barrier; docs/PERFORMANCE.md "
            "'Round fusion')",
            file=sys.stderr,
        )
    if a.repetitions != 1:
        print(
            "warning: --repetitions is a simulator flag and is ignored "
            "under --role (each deployment process runs exactly one rank)",
            file=sys.stderr,
        )
    if a.client_block_size:
        # deploy clients are one process each — there is no stacked
        # cohort on a rank to stream in blocks
        print(
            "warning: --client_block_size covers the compiled "
            "simulators (FedAvgSim/ShardedFedAvg) and is inert under "
            "--role (docs/PERFORMANCE.md 'Bulk-client execution')",
            file=sys.stderr,
        )
    # (peft inertness under --role/--supervise is warned at parse
    # time, keyed on the MERGED config so --config JSON is covered)
    if a.recovery_extensions and not a.round_deadline:
        # fail at argument time with the pairing rule, not per-rank
        # (under a supervisor the server would otherwise crash-loop on
        # RoundPolicy's ValueError until the restart budget is spent)
        raise SystemExit(
            "--recovery_extensions requires --round_deadline: "
            "extensions re-arm the round deadline, so without one "
            "there is nothing to extend"
        )
    broker = _parse_broker(a.broker) if a.broker is not None else None
    return DeployConfig(
        role=a.role,
        rank=rank,
        world_size=a.world_size,
        telemetry_dir=a.telemetry_dir,
        trace=a.trace,
        trace_jax=a.trace_jax,
        metrics_interval=a.metrics_interval,
        metrics_port=a.metrics_port,
        metrics_host=a.metrics_host,
        backend=a.backend,
        ip_config=load_ip_config(a.ip_config) if a.ip_config else None,
        broker=broker,
        blob_dir=a.blob_dir,
        ready_timeout=a.ready_timeout,
        heartbeats=not a.no_heartbeats,
        heartbeat_interval_s=a.heartbeat_interval,
        heartbeat_timeout_s=a.heartbeat_timeout,
        quorum_fraction=a.quorum_fraction,
        round_deadline_s=(
            a.round_deadline if a.round_deadline else None
        ),
        checkpoint_every=a.checkpoint_every or 0,
        recovery_extensions=a.recovery_extensions,
        fault=_fault_policy(a),
        quarantine_threshold=a.quarantine_threshold,
        quarantine_decay=a.quarantine_decay,
        quarantine_evict_after=a.quarantine_evict_after,
        leave_after_round=a.leave_after_round,
        presumed_left=tuple(a.presumed_left),
        presumed_evicted=tuple(a.presumed_evicted),
        tier_spec=a.tier_spec,
        uplink_ip_config=(
            load_ip_config(a.uplink_ip_config)
            if a.uplink_ip_config else None
        ),
        tier_client_base=a.tier_client_base,
    )


def _strip_flags(
    argv: list[str], bare=(), valued=(), prefixes=()
) -> list[str]:
    """Remove flags from a raw argv list: ``bare`` take no value,
    ``valued`` (and any flag matching a ``prefixes`` entry) consume the
    next token unless given as ``--flag=value``."""
    out, i = [], 0
    while i < len(argv):
        tok = argv[i]
        name = tok.split("=", 1)[0]
        if name in bare:
            i += 1
            continue
        if name in valued or any(name.startswith(p) for p in prefixes):
            i += 1 if "=" in tok else 2
            continue
        out.append(tok)
        i += 1
    return out


def _run_supervised(a, argv: list[str]) -> int:
    """``--supervise``: launch the whole world (server + clients) on
    this host under a :class:`~fedml_tpu.experiments.deploy.Supervisor`.
    Every rank runs this same CLI with ``--role``/``--rank`` appended;
    restarted incarnations run WITHOUT the ``--fault_*`` chaos flags,
    so an injected crash happens once and its replacement runs clean
    (the kill -> restart -> rejoin -> converge loop,
    docs/FAULT_TOLERANCE.md "Recovery")."""
    from fedml_tpu.experiments.deploy import RankSpec, Supervisor

    if a.role is not None:
        raise SystemExit(
            "--supervise launches every rank itself; drop --role/--rank"
        )
    if a.world_size is None or a.world_size < 2:
        raise SystemExit("--supervise requires --world_size >= 2")
    if a.tier_spec:
        raise SystemExit(
            "--supervise launches one flat world (server + clients); "
            "tier worlds span several worlds — start the root, "
            "leaves, and clients explicitly (scripts/async_smoke.py "
            "shows the shape)"
        )
    if a.no_heartbeats:
        raise SystemExit(
            "--supervise requires the liveness protocol: after a "
            "server restart the readiness barrier completes via the "
            "surviving clients' heartbeats — with --no_heartbeats the "
            "restarted server would wait forever"
        )
    if a.recovery_extensions and not a.round_deadline:
        raise SystemExit(
            "--recovery_extensions requires --round_deadline: "
            "extensions re-arm the round deadline, so without one "
            "there is nothing to extend"
        )
    if a.telemetry_dir:
        from fedml_tpu.core import telemetry

        # the supervisor is its own telemetry process; rank world_size
        # (one past the last client) keeps its artifacts from
        # colliding with the server's rank-0 files
        telemetry.configure(telemetry_dir=a.telemetry_dir,
                            rank=a.world_size)
    base = _strip_flags(argv, bare={"--supervise"},
                        valued={"--max_restarts"})
    clean = _strip_flags(base, prefixes=("--fault_",))
    # --metrics_port names ONE port: the server keeps it (its /metrics
    # carries the federated fleet.* view anyway); clients would all
    # collide on the same bind, so the flag is stripped from their
    # argv. --profile_on_breach is rank-0-only the same way (one deep
    # profiler per world, armed where rounds close); its window/cap
    # companions go with it so the clients don't warn about inert
    # knobs. --anatomy stays on every rank: the clients' phase
    # histograms are what fleet federation forwards.
    _c_bare = {"--profile_on_breach"}
    _c_valued = {"--metrics_port", "--profile_window_s",
                 "--profile_max_captures"}
    c_base = _strip_flags(base, bare=_c_bare, valued=_c_valued)
    c_clean = _strip_flags(clean, bare=_c_bare, valued=_c_valued)
    entry = [sys.executable, "-m", "fedml_tpu.experiments.run"]
    specs = [
        RankSpec(
            rank=0,
            argv=[*entry, *base, "--role", "server"],
            restart_argv=[*entry, *clean, "--role", "server"],
        )
    ]
    for r in range(1, a.world_size):
        specs.append(
            RankSpec(
                rank=r,
                argv=[*entry, *c_base, "--role", "client",
                      "--rank", str(r)],
                restart_argv=[*entry, *c_clean, "--role", "client",
                              "--rank", str(r)],
            )
        )
    sup = Supervisor(
        specs, max_restarts=a.max_restarts, env=dict(os.environ)
    )
    result = sup.run()
    print(json.dumps(
        {**result["summary"], "restarts": result["restarts"]},
        default=float,
    ))
    return 0


def main(argv=None) -> int:
    cfg, a = parse_args(argv)
    if a.supervise:
        return _run_supervised(
            a, list(sys.argv[1:] if argv is None else argv)
        )
    if a.role is not None:
        from fedml_tpu.experiments.deploy import run_role

        # telemetry for the role path is configured inside run_role
        # (DeployConfig carries the knobs, so library callers get the
        # same wiring as the CLI)
        print(json.dumps(run_role(cfg, _deploy_config(a)), default=float))
        return 0
    if a.quarantine_threshold:
        # the reputation plane lives in the server ACTOR; the compiled
        # simulator applies per-round defenses (--defense) but has no
        # per-client identity to quarantine across rounds
        print(
            "warning: --quarantine_threshold is a deployment flag and "
            "is ignored by the simulator (use --role/--supervise; "
            "--defense still applies here)",
            file=sys.stderr,
        )
    if a.leave_after_round is not None:
        # departure is an actor-protocol event (MSG_TYPE_C2S_LEAVE);
        # the compiled simulator has no per-rank processes to depart
        print(
            "warning: --leave_after_round is a deployment flag and is "
            "ignored by the simulator (use --role client; "
            "set_cohort_size drives churn in the simulator)",
            file=sys.stderr,
        )
    if cfg.fed.async_buffer_k:
        # the async buffer lives in the deploy server actor: the
        # compiled simulator IS one synchronous program — there is no
        # arrival stream to fold without a barrier
        print(
            "warning: --async_buffer_k is a deployment flag and is "
            "ignored by the simulator (use --role/--supervise; "
            "docs/FAULT_TOLERANCE.md 'Async + tiered worlds')",
            file=sys.stderr,
        )
    if a.tier_spec:
        print(
            "warning: --tier_spec is a deployment flag and is ignored "
            "by the simulator (tier worlds are --role server/leaf/"
            "client processes)",
            file=sys.stderr,
        )
    if cfg.fed.shard_aggregation:
        # the sharded server update lives in the deploy server actor;
        # the sims' sharded runtime is ShardedFedAvg (library API)
        print(
            "warning: --shard_aggregation covers the --role server "
            "aggregation path and is ignored by the simulator "
            "(parallel.ShardedFedAvg is the sims' sharded runtime)",
            file=sys.stderr,
        )
    # adversary injection is wired into the FedAvgSim round program;
    # other sims (mpc/secure-agg, GAN family, splitnn, ...) aggregate
    # elsewhere and would silently run a vacuous Byzantine experiment
    # (_ADVERSARY_SIMS is module-level: parse_args gates the bulk
    # compatibility matrix on the same family)
    if (cfg.adversary.enabled()
            and cfg.fed.algorithm not in _ADVERSARY_SIMS):
        print(
            f"warning: --adversary_* flags are ignored by the "
            f"{cfg.fed.algorithm!r} simulator (adversary injection "
            "covers the FedAvg-family round program: "
            f"{sorted(_ADVERSARY_SIMS)})",
            file=sys.stderr,
        )
    if (cfg.fed.fuse_rounds > 1
            and cfg.fed.algorithm not in _ADVERSARY_SIMS):
        # the fused block scans the FedAvg-family round body; other
        # sims fall back to the per-round loop (the harness warns too,
        # but say it at launch where the flag was typed)
        print(
            f"warning: --fuse_rounds is ignored by the "
            f"{cfg.fed.algorithm!r} simulator (round fusion covers "
            "the FedAvg-family compiled round: "
            f"{sorted(_ADVERSARY_SIMS)}); this run executes per-round",
            file=sys.stderr,
        )
    if (cfg.fed.client_block_size
            and cfg.fed.algorithm not in _ADVERSARY_SIMS):
        # same honesty rule as fuse_rounds: the block scan wraps the
        # FedAvg-family round body only
        print(
            f"warning: --client_block_size is ignored by the "
            f"{cfg.fed.algorithm!r} simulator (bulk streaming covers "
            "the FedAvg-family compiled round: "
            f"{sorted(_ADVERSARY_SIMS)}); this run executes stacked",
            file=sys.stderr,
        )
    if (cfg.fed.compress != "none"
            and cfg.fed.algorithm not in _ADVERSARY_SIMS):
        # same honesty rule as the adversary gate: only the
        # FedAvg-family round wires the codec in — a summary labeled
        # topk_int8 must not have measured a dense run
        print(
            f"warning: --compress is ignored by the "
            f"{cfg.fed.algorithm!r} simulator (the wire codec covers "
            "the FedAvg-family round program: "
            f"{sorted(_ADVERSARY_SIMS)}); results here are DENSE",
            file=sys.stderr,
        )
    if (a.telemetry_dir or a.trace or a.trace_jax
            or cfg.fed.profile_rounds or a.metrics_interval
            or a.metrics_port is not None or cfg.fed.slos
            or cfg.fed.anatomy or cfg.fed.profile_on_breach):
        from fedml_tpu.core import telemetry

        telemetry.configure(
            telemetry_dir=a.telemetry_dir
            or telemetry.default_dir(cfg.out_dir, cfg.run_name),
            rank=0,
            jax_profiler=a.trace_jax,
            metrics_interval=a.metrics_interval,
            metrics_port=a.metrics_port,
            metrics_host=a.metrics_host,
            slos=cfg.fed.slos,
            slo_scope=cfg.run_name,
        )
        if cfg.fed.anatomy or cfg.fed.profile_on_breach:
            # the anatomy plane rides the telemetry dir configured
            # above (breach profiles land under <dir>/profiles/)
            from fedml_tpu.core import anatomy

            anatomy.configure(
                anatomy=cfg.fed.anatomy,
                profile_on_breach=cfg.fed.profile_on_breach,
                profile_window_s=cfg.fed.profile_window_s,
                profile_max_captures=cfg.fed.profile_max_captures,
            )
    summaries = Experiment(cfg, a.repetitions).run()
    for s in summaries:
        print(json.dumps(s, default=float))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Experiment harness: algorithm registry + repetition runner.

TPU-native equivalent of the fork's ``ExperimentBase``
(``fedml_experiments/standalone/utils/experiment.py:16``: repetition loop
with group ids ``:27-39``, per-repetition seeding ``:69-76``) and the
per-algorithm ``main_<algo>.py`` entry scripts. One registry maps algorithm
names to sim builders; :class:`Experiment` runs N seeded repetitions and
writes JSONL metrics + a summary per repetition.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import telemetry
from fedml_tpu.data.loaders import load_dataset
from fedml_tpu.metrics.sink import MetricsSink
from fedml_tpu.models import create_model


def _fedavg_family(algorithm: str):
    def build(cfg: ExperimentConfig):
        from fedml_tpu.algorithms.fedavg import FedAvgSim

        cfg = dataclasses.replace(
            cfg, fed=dataclasses.replace(cfg.fed, algorithm=algorithm)
        )
        data = load_dataset(cfg.data)
        return FedAvgSim(create_model(cfg.model), data, cfg)

    return build


def _build_decentralized(method):
    def build(cfg: ExperimentConfig):
        from fedml_tpu.algorithms.decentralized import DecentralizedSim

        data = load_dataset(cfg.data)
        return DecentralizedSim(
            create_model(cfg.model), data, cfg, method=method
        )

    return build


def _build_hierarchical(cfg: ExperimentConfig):
    from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvg

    data = load_dataset(cfg.data)
    return HierarchicalFedAvg(create_model(cfg.model), data, cfg)


def _build_gan(name):
    def build(cfg: ExperimentConfig):
        from fedml_tpu.algorithms import gan_core as G
        from fedml_tpu.algorithms.gan_family import (
            FedDTGSim, FedGANSim, FedGDKDSim,
        )
        from fedml_tpu.algorithms.sgan import FedSSGANSim, FedUAGANSim
        from fedml_tpu.models.gan import (
            ACGANDiscriminator, generator_from_config,
        )

        data = load_dataset(cfg.data)
        shape = cfg.model.input_shape
        gen = generator_from_config(
            cfg.gan, cfg.model.num_classes, shape[0], shape[-1]
        )
        if name == "fedgdkd":
            return FedGDKDSim(gen, create_model(cfg.model), data, cfg)
        disc = G.DiscHandle(
            module=ACGANDiscriminator(num_classes=cfg.model.num_classes),
            has_validity_head=True,
        )
        if name == "fedgan":
            return FedGANSim(gen, disc, data, cfg)
        if name == "feddtg":
            return FedDTGSim(gen, disc, create_model(cfg.model), data, cfg)
        if name == "fedssgan":
            return FedSSGANSim(
                gen,
                G.DiscHandle(
                    module=ACGANDiscriminator(
                        num_classes=cfg.model.num_classes
                    )
                ),
                data, cfg,
            )
        if name == "feduagan":
            return FedUAGANSim(gen, disc, data, cfg)
        raise ValueError(name)

    return build


def _build_distill(name):
    def build(cfg: ExperimentConfig):
        from fedml_tpu.algorithms.distill import FDSim, FedArjunSim, FedMDSim

        data = load_dataset(cfg.data)
        if name == "fedmd":
            return FedMDSim(create_model(cfg.model), data, cfg)
        if name == "fd_faug":
            return FDSim(create_model(cfg.model), data, cfg)
        if name == "fedarjun":
            local = dataclasses.replace(cfg.model, name="lr")
            return FedArjunSim(
                create_model(cfg.model), create_model(local), data, cfg
            )
        raise ValueError(name)

    return build


def _build_fedgkt(cfg: ExperimentConfig):
    from fedml_tpu.algorithms.split import FedGKTSim
    from fedml_tpu.models.gkt import GKTClientResNet, GKTServerResNet

    data = load_dataset(cfg.data)
    nc = cfg.model.num_classes
    return FedGKTSim(
        GKTClientResNet(num_classes=nc),
        GKTServerResNet(num_classes=nc),
        data, cfg,
    )


def _build_splitnn(cfg: ExperimentConfig):
    from fedml_tpu.algorithms.split import SplitNNSim
    from fedml_tpu.models.gkt import SplitClientNet, SplitServerNet

    data = load_dataset(cfg.data)
    return SplitNNSim(
        SplitClientNet(), SplitServerNet(num_classes=cfg.model.num_classes),
        data, cfg,
    )


def _build_vfl(cfg: ExperimentConfig):
    """Two-party classical vertical FL (reference
    ``standalone/classical_vertical_fl/vfl_fixture.py``): guest holds the
    labels, both parties contribute logit components from their feature
    slice. Datasets: ``nus_wide`` / ``lending_club`` real files under
    ``data_dir``, else ``fake_vfl`` — a seeded linearly-separable
    two-party set so offline smoke runs converge."""
    import numpy as np

    from fedml_tpu.algorithms.split import VFLSim
    from fedml_tpu.models.gkt import VFLDenseModel, VFLLocalModel

    ds = cfg.data.dataset
    if ds == "nus_wide":
        from fedml_tpu.data.vertical import load_nus_wide_two_party

        # VFLSim is a binary sigmoid-BCE model (reference vfl.py), so the
        # multi-concept labels must be binarized; "person"-vs-rest is the
        # reference experiments' usual positive concept
        d = load_nus_wide_two_party(
            cfg.data.data_dir, binary_positive="person"
        )
    elif ds == "lending_club":
        from fedml_tpu.data.vertical import load_lending_club_two_party

        d = load_lending_club_two_party(cfg.data.data_dir)
    else:  # fake_vfl / any offline name
        rng = np.random.default_rng(cfg.data.seed)
        n, dim = 512, 24
        w = rng.normal(size=(dim,))
        x = rng.normal(size=(n, dim)).astype(np.float32)
        xt = rng.normal(size=(n // 4, dim)).astype(np.float32)
        d = {
            "train": (x, (x @ w > 0).astype(np.float32)),
            "test": (xt, (xt @ w > 0).astype(np.float32)),
            "splits": [(0, dim // 2), (dim // 2, dim)],
        }
    return VFLSim(
        party_models=[
            (VFLLocalModel(out_dim=8, hidden=16), VFLDenseModel())
            for _ in d["splits"]
        ],
        feature_splits=d["splits"],
        x_train=d["train"][0],
        y_train=d["train"][1],
        x_test=d["test"][0],
        y_test=d["test"][1],
        cfg=cfg,
    )


def _build_turboaggregate(cfg: ExperimentConfig):
    """FedAvg with TurboAggregate secure aggregation as the server rule
    (reference ``distributed/turboaggregate``)."""
    from fedml_tpu.algorithms.mpc import SecureFedAvgSim

    data = load_dataset(cfg.data)
    return SecureFedAvgSim(create_model(cfg.model), data, cfg)


def _build_fednas(cfg: ExperimentConfig):
    from fedml_tpu.algorithms.fednas import FedNASSim
    from fedml_tpu.models.darts import DARTSNetwork

    data = load_dataset(cfg.data)
    return FedNASSim(
        DARTSNetwork(num_classes=cfg.model.num_classes), data, cfg
    )


def _build_baseline(cfg: ExperimentConfig):
    from fedml_tpu.algorithms.local_baselines import BaselineSim

    data = load_dataset(cfg.data)
    return BaselineSim(create_model(cfg.model), data, cfg)


def _build_centralized(cfg: ExperimentConfig):
    from fedml_tpu.algorithms.local_baselines import CentralizedTrainer

    data = load_dataset(cfg.data)
    return CentralizedTrainer(create_model(cfg.model), data, cfg)


def _build_dol(method):
    """Decentralized ONLINE learning (regret metric; reference
    ``main_dol.py``): dataset in {susy, ro} reads the UCI files under
    data_dir; anything else uses the procedural SUSY-shaped stream.
    ``comm_round`` doubles as the iteration count T. The adversarial
    ``beta`` fraction is taken from ``partition_alpha`` ONLY when
    ``partition_method == "hetero"`` was explicitly requested — the
    default run is fully stochastic (beta=0), matching the reference
    ``main_dol.py`` default."""

    def build(cfg: ExperimentConfig):
        from fedml_tpu.algorithms.decentralized import OnlineDecentralizedSim
        from fedml_tpu.data import streaming as S

        name = cfg.data.dataset.lower()
        n, t = cfg.data.num_clients, cfg.fed.num_rounds
        beta = (
            cfg.data.partition_alpha
            if cfg.data.partition_method == "hetero"
            else 0.0
        )
        if name in ("susy", "ro"):
            xs, ys = S.load_uci_stream(
                name, cfg.data.data_dir, n, t, beta=beta,
                seed=cfg.data.seed,
            )
        else:
            xs, ys = S.make_susy_like_stream(
                n, t, beta=beta, seed=cfg.data.seed
            )
        sim = OnlineDecentralizedSim(
            xs, ys, method=method, lr=cfg.train.lr,
            weight_decay=cfg.train.weight_decay, seed=cfg.seed,
        )
        sim.log_every = cfg.fed.eval_every  # harness eval cadence
        return sim

    return build


ALGORITHMS: dict[str, Callable[[ExperimentConfig], Any]] = {
    # FedAvg family: one compiled round, configured per variant
    "fedavg": _fedavg_family("fedavg"),
    "fedopt": _fedavg_family("fedopt"),
    "fedprox": _fedavg_family("fedavg"),  # prox_mu in TrainConfig
    "fednova": _fedavg_family("fednova"),
    "fedavg_robust": _fedavg_family("fedavg"),  # robust_* in FedConfig
    "fedavg_multiclient": _fedavg_family("fedavg"),
    "fedseg": _fedavg_family("fedavg"),  # segmentation task via dataset
    "decentralized_dsgd": _build_decentralized("dsgd"),
    "decentralized_pushsum": _build_decentralized("pushsum"),
    "dol_dsgd": _build_dol("dsgd"),
    "dol_pushsum": _build_dol("pushsum"),
    "hierarchical": _build_hierarchical,
    "fedgan": _build_gan("fedgan"),
    "fedgdkd": _build_gan("fedgdkd"),
    "feddtg": _build_gan("feddtg"),
    "fedssgan": _build_gan("fedssgan"),
    "feduagan": _build_gan("feduagan"),
    "fedmd": _build_distill("fedmd"),
    "fd_faug": _build_distill("fd_faug"),
    "fedarjun": _build_distill("fedarjun"),
    "fedgkt": _build_fedgkt,
    "splitnn": _build_splitnn,
    "vfl": _build_vfl,
    "classical_vertical_fl": _build_vfl,
    "turboaggregate": _build_turboaggregate,
    "fednas": _build_fednas,
    "baseline": _build_baseline,
    "centralized": _build_centralized,
}


def build_sim(cfg: ExperimentConfig):
    algo = cfg.fed.algorithm
    if algo not in ALGORITHMS:
        raise ValueError(
            f"unknown algorithm: {algo}; known: {sorted(ALGORITHMS)}"
        )
    return ALGORITHMS[algo](cfg)


class Experiment:
    """Seeded repetition runner (fork ``ExperimentBase``)."""

    def __init__(self, cfg: ExperimentConfig, repetitions: int = 1):
        self.cfg = cfg
        self.repetitions = repetitions

    def run(self) -> list[dict]:
        summaries = []
        for rep in range(self.repetitions):
            cfg = dataclasses.replace(
                self.cfg,
                seed=self.cfg.seed + rep,
                data=dataclasses.replace(
                    self.cfg.data, seed=self.cfg.data.seed + rep
                ),
                run_name=f"{self.cfg.run_name}_rep{rep}",
            )
            out_dir = os.path.join(cfg.out_dir, cfg.run_name)
            sink = MetricsSink(path=os.path.join(out_dir, "metrics.jsonl"))
            with open(
                _ensure(os.path.join(out_dir, "config.json")), "w"
            ) as f:
                f.write(cfg.to_json())
            sim = build_sim(cfg)
            self._run_sim(sim, cfg, sink)
            sink.close()
            summaries.append(dict(sink.summary, run_name=cfg.run_name))
        return summaries

    @staticmethod
    def _run_sim(sim, cfg: ExperimentConfig, sink: MetricsSink):
        """Drive any sim shape: prefer its own ``run``; else the
        run_round/evaluate protocol. With ``cfg.checkpoint_every`` > 0
        the generic loop takes over for sims exposing the
        init/run_round state protocol, so round state checkpoints
        atomically every N rounds and a restarted run resumes from the
        latest step. Sims without device-resident round state
        (host-driven or run-only shapes) cannot checkpoint — the flag
        warns and falls back to a plain run. On resume after a
        mid-interval crash, rounds after the last checkpoint re-run and
        re-log: metrics.jsonl may carry a duplicate round record — the
        later one is authoritative, machine-checkably so: every row a
        resumed incarnation logs carries ``resumed: true`` (consumers
        keep the ``resumed`` row when a round number appears twice)."""
        ckpt = None
        start_round = 0
        checkpointable = (
            cfg.checkpoint_every > 0
            and hasattr(sim, "init")
            and hasattr(sim, "run_round")
        )
        if cfg.checkpoint_every > 0 and not checkpointable:
            import warnings

            warnings.warn(
                f"checkpoint_every={cfg.checkpoint_every} ignored: "
                f"{type(sim).__name__} does not expose the "
                "init/run_round state protocol",
                stacklevel=2,
            )
        if not checkpointable:
            if (hasattr(sim, "run") and not isinstance(sim, type)
                    and _run_accepts_sink(sim)):
                try:
                    # run-shaped sims drive their own loop: one span
                    # covers the whole trajectory (round-level spans
                    # come from the generic loop below otherwise)
                    with telemetry.maybe_span(
                        "sim_run", sim=type(sim).__name__
                    ):
                        sim.run(metrics_sink=sink)
                    return
                except TypeError:
                    pass
        state = sim.init() if hasattr(sim, "init") else None
        if checkpointable and state is not None:
            from fedml_tpu.utils.checkpoint import RoundCheckpointer

            ckpt = RoundCheckpointer(
                os.path.join(
                    os.path.dirname(sink.path) if sink.path else
                    cfg.out_dir, "ckpt"
                )
            )
            state, start_round = Experiment._restore_state(
                ckpt, sim, state
            )
            if start_round:
                sink.log({"resumed_from": start_round})
        elif checkpointable:
            import warnings

            warnings.warn(
                "checkpoint_every ignored: sim has no device-resident "
                "round state (init() returned None)",
                stacklevel=2,
            )
        try:
            Experiment._round_loop(sim, cfg, sink, state, start_round,
                                   ckpt)
        finally:
            if ckpt is not None:
                ckpt.close()

    @staticmethod
    def _round_loop(sim, cfg, sink, state, start_round, ckpt):
        import time as _time

        from fedml_tpu.core import perf as P

        # perf observability (core/perf.py): same wiring as
        # FedAvgSim.run for sims the generic loop drives
        # (checkpointable runs, run_round-protocol sims). Inert unless
        # cfg.fed.profile_rounds > 0.
        profiler, monitor = P.build_sim_perf(sim)
        try:
            Experiment._instrumented_loop(
                sim, cfg, sink, state, start_round, ckpt, profiler,
                monitor, _time,
            )
        finally:
            if profiler is not None:
                profiler.finish()

    @staticmethod
    def _instrumented_loop(sim, cfg, sink, state, start_round, ckpt,
                           profiler, monitor, _time):
        fuse = int(getattr(cfg.fed, "fuse_rounds", 1) or 1)
        if fuse > 1:
            if hasattr(sim, "run_block") and state is not None:
                return Experiment._fused_loop(
                    sim, cfg, sink, state, start_round, ckpt, profiler,
                    monitor, _time,
                )
            import warnings

            warnings.warn(
                f"fuse_rounds={fuse} ignored: {type(sim).__name__} "
                "does not expose the run_block state protocol (round "
                "fusion covers the FedAvg-family compiled sims); "
                "running per-round",
                stacklevel=2,
            )
        for r in range(start_round, cfg.fed.num_rounds):
            t0 = _time.perf_counter()
            if telemetry.METRICS.enabled:
                # /statusz "run" block (core/export.py): the sim loop
                # has no actor to register, so the live round rides
                # the cheap run-state dict instead
                from fedml_tpu.core import export as _export

                _export.set_run_state(
                    round=r, num_rounds=cfg.fed.num_rounds,
                    run_name=cfg.run_name,
                )
            if profiler is not None:
                profiler.start_round(r)
            with telemetry.maybe_span("sim_round", round=r):
                if state is None:  # host-driven sims (HeteroFedGDKD)
                    m = sim.run_round()
                else:
                    out = (
                        sim.run_round(state, r)
                        if _wants_round(sim) else sim.run_round(state)
                    )
                    state, m = out
            record = {"round": r}
            if start_round:
                # this incarnation resumed mid-run: its rows win over
                # any pre-crash row for the same round
                record["resumed"] = True
            if isinstance(m, dict):
                from fedml_tpu.algorithms.fedavg import (
                    consume_round_counters,
                )

                m = consume_round_counters(_batched_get(dict(m)))
                record.update({k: _f(v) for k, v in m.items()
                               if _scalar(v)})
            # the scalar conversion above forced the round's metrics to
            # host, so the capture window and wall time cover the
            # device execution, not just the dispatch
            if profiler is not None:
                profiler.end_round(r)
            if monitor is not None:
                monitor.note_round(_time.perf_counter() - t0)
            if (r + 1) % cfg.fed.eval_every == 0 or (
                r == cfg.fed.num_rounds - 1
            ):
                record.update(Experiment._eval_record(sim, state))
            sink.log(record)
            if ckpt is not None and (
                (r + 1) % cfg.checkpoint_every == 0
                or r == cfg.fed.num_rounds - 1
            ):
                Experiment._save_state(ckpt, sim, r, state)

    @staticmethod
    def _save_state(ckpt, sim, r, state):
        """Checkpoint one round: sims carrying client-state banks
        (docs/FAULT_TOLERANCE.md "Client-state banks" — the compress
        error-feedback residual, the PEFT private adapter bank) save
        the ``{"server": state, "bank": {name: rows}}`` composite so a
        SIGKILLed run restores every client's row bitwise; bankless
        sims keep the bare-state layout unchanged."""
        banks = sim.bank_state() if hasattr(sim, "bank_state") else {}
        if banks:
            ckpt.save(r, {"server": state, "bank": banks})
        else:
            ckpt.save(r, state)

    @staticmethod
    def _restore_state(ckpt, sim, state):
        """The restore half of :meth:`_save_state`. Bank-aware sims
        restore through the raw (template-free) path so the composite's
        variable bank payload never has to match a shape template; a
        legacy bare-state checkpoint (or a composite from a config
        without this sim's banks) restores the server state and leaves
        the lazily-initialized fresh banks in place — exactly what the
        pre-bank checkpoint encoded."""
        if not (hasattr(sim, "restore_banks")
                and hasattr(sim, "bank_state")):
            return ckpt.restore_or(state)
        raw, nxt = ckpt.restore_raw()
        if raw is None:
            return state, 0
        from fedml_tpu.utils.checkpoint import from_savable

        bank_blob = None
        if isinstance(raw, dict) and "server" in raw:
            bank_blob = raw.get("bank")
            raw = raw["server"]
        restored = from_savable(state, raw)
        sim.restore_banks(restored, bank_blob)
        return restored, nxt

    @staticmethod
    def _eval_record(sim, state) -> dict:
        """Run the sim's evaluator (first of the known protocol names)
        and normalize bare test-split {acc, loss} to the test_* names
        the summary consumers (battery table, wandb groupings) key
        on."""
        for ev_name in ("evaluate_global", "evaluate_clients",
                        "evaluate_consensus", "evaluate"):
            if hasattr(sim, ev_name):
                ev = getattr(sim, ev_name)(state) if state is not \
                    None else getattr(sim, ev_name)()
                rename = {"acc": "test_acc", "loss": "test_loss"}
                return {rename.get(k, k): _f(v)
                        for k, v in ev.items() if _scalar(v)}
        return {}

    @staticmethod
    def _fused_loop(sim, cfg, sink, state, start_round, ckpt, profiler,
                    monitor, _time):
        """Block-driven round loop for run_block sims (docs/
        PERFORMANCE.md "Round fusion"): dispatch blocks of up to
        ``fuse_rounds`` rounds, convert the PREVIOUS block's stacked
        metrics while the current one runs on device (one batched
        transfer per block), and sync only at eval / checkpoint /
        profiler-capture boundaries. The loop itself is
        ``core.fuse.drive`` (shared with ``FedAvgSim._run_fused``);
        ``core.fuse.plan_blocks`` places boundaries so evaluation and
        checkpoints see exactly the same round's state as the
        per-round loop."""
        from fedml_tpu.core import fuse as F
        from fedml_tpu.algorithms.fedavg import consume_round_counters

        ckpt_every = cfg.checkpoint_every if ckpt is not None else 0
        total = cfg.fed.num_rounds
        box = [state]

        def run_block(length):
            box[0], dm = sim.run_block(box[0], length)
            return dm

        def make_records(start, rows):
            records = []
            for i, row in enumerate(rows):
                row = consume_round_counters(row)
                rec = {"round": start + i}
                if start_round:
                    rec["resumed"] = True
                rec.update({k: _f(v) for k, v in row.items()
                            if _scalar(v)})
                records.append(rec)
            return records

        def boundary_hook(r_last, last):
            if telemetry.METRICS.enabled:
                from fedml_tpu.core import export as _export

                _export.set_run_state(
                    round=r_last, num_rounds=total,
                    run_name=cfg.run_name,
                )
            if (r_last + 1) % cfg.fed.eval_every == 0 or (
                r_last == total - 1
            ):
                last.update(Experiment._eval_record(sim, box[0]))
            sink.log(last)
            if ckpt is not None and (
                (r_last + 1) % cfg.checkpoint_every == 0
                or r_last == total - 1
            ):
                Experiment._save_state(ckpt, sim, r_last, box[0])

        F.drive(
            run_block,
            F.plan_blocks(start_round, total, int(cfg.fed.fuse_rounds),
                          cfg.fed.eval_every, ckpt_every),
            profiler=profiler,
            monitor=monitor,
            make_records=make_records,
            log=sink.log,
            boundary_hook=boundary_hook,
            span=lambda start, rounds: telemetry.maybe_span(
                "sim_block", start=start, rounds=rounds),
        )


def _wants_round(sim) -> bool:
    import inspect

    try:
        return len(inspect.signature(sim.run_round).parameters) >= 2
    except (TypeError, ValueError):
        return False


def _run_accepts_sink(sim) -> bool:
    """Signature gate for the ``sim.run(metrics_sink=...)`` fast path —
    checked up front so a sim without the kwarg falls through to the
    generic loop WITHOUT a probe call (which would record a phantom
    error-tagged sim_run span when tracing is on)."""
    import inspect

    try:
        params = inspect.signature(sim.run).parameters
    except (TypeError, ValueError):
        return True  # unintrospectable: fall back to the call probe
    return "metrics_sink" in params or any(
        p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values()
    )


def _batched_get(m: dict) -> dict:
    """Fetch every device-array leaf of a round's metric dict in ONE
    batched ``jax.device_get`` (async copies first, then one block)
    instead of a device sync per ``float(leaf)``; non-array values
    (host-driven sims mix types) pass through untouched."""
    import jax

    arrs = {k: v for k, v in m.items() if isinstance(v, jax.Array)}
    if arrs:
        m = {**m, **jax.device_get(arrs)}
    return m


def _scalar(v) -> bool:
    return isinstance(v, (int, float)) or getattr(v, "ndim", None) == 0


def _f(v):
    return float(v)


def _ensure(path: str) -> str:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    return path

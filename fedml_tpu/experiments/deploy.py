"""Process-separated deployment: one OS process per rank, over a socket.

The reference's canonical deployment is N separate OS processes —
``mpirun -np $PROCESS_NUM`` launching a per-rank ``main_fedavg.py``
(``fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:1-20``)
and the cross-silo shell launchers that start a server role and client
roles on separate machines
(``fedml_experiments/distributed/fedavg_cross_silo/run_server.sh``,
``run_client.sh``). This module is that surface for the TPU framework:
``python -m fedml_tpu.experiments.run --role server|client --rank N
--world_size W --backend grpc|tcp|trpc|pubsub|pubsub_blob ...`` runs ONE
rank; ``scripts/run_distributed.sh`` is the mpirun-shaped localhost
launcher.

Equality contract: every process derives its data partition, model init,
and rng keys from the shared seeded config, and the actors are the same
:mod:`fedml_tpu.algorithms.distributed_fedavg` /
:mod:`fedml_tpu.algorithms.split_actors` classes whose loopback runs are
equality-pinned against the compiled sims — so an N-process run over real
sockets matches the compiled simulator to float round-off
(``tests/test_deploy.py`` pins it cross-process).

Readiness: socket transports have no MPI-style barrier, and the pub/sub
path drops publishes with no subscriber (MQTT QoS-0 semantics). Clients
therefore re-announce ``MSG_TYPE_C2S_READY`` every 0.5 s until the
server ACKs (``MSG_TYPE_S2C_ACK`` reply to each READY) or any other
server message arrives; the server starts round 0 once all
``world_size - 1`` distinct ranks have announced. The ACK matters:
liveness must not be inferred from WORK traffic — a later-rank SplitNN
client legitimately idles for the whole of its predecessors' epochs, and
before the ACK existed it would hit ``ready_timeout`` and kill a healthy
run. Send failures during announcement (server socket not yet bound) are
retried, which makes process launch order irrelevant — the reference
gets the same property from MQTT broker buffering + its client
"register" message.

Liveness (docs/FAULT_TOLERANCE.md): once the run is underway both sides
heartbeat (``MSG_TYPE_HEARTBEAT``) and watch per-peer last-seen times.
The server routes dead peers into the actor's straggler logic
(``FedAvgServerActor.on_peer_dead`` — quorum/deadline rounds) instead of
blocking forever on its inbox; clients detect a dead server and exit
loudly. Deterministic fault injection for all of this lives in
:mod:`fedml_tpu.core.transport.chaos` and is threaded here via
``DeployConfig.fault``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import threading
import time

import jax
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import telemetry
from fedml_tpu.core.manager import Manager, ServerManager, create_transport
from fedml_tpu.core.message import (
    MSG_TYPE_C2S_READY,
    MSG_TYPE_S2C_ACK,
    Message,
)
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.core.transport.chaos import ChaosTransport, FaultPolicy

FEDAVG_FAMILY = ("fedavg", "fedopt", "fednova")
DEPLOY_ALGORITHMS = FEDAVG_FAMILY + ("splitnn",)


@dataclasses.dataclass(frozen=True)
class DeployConfig:
    """One rank's deployment coordinates (the reference passes these as
    ``--client_id/--server_ip`` flags + ``ip_config`` CSV tables,
    ``ip_config_utils.py``)."""

    role: str  # "server" | "client"
    rank: int  # 0 = server, >=1 = client
    world_size: int
    backend: str = "grpc"
    ip_config: dict[int, tuple[str, int]] | None = None
    broker: tuple[str, int] | None = None  # pubsub* backends
    blob_dir: str | None = None  # pubsub_blob file-backed store
    ready_timeout: float = 120.0
    # -- fault tolerance (docs/FAULT_TOLERANCE.md) -------------------------
    heartbeats: bool = True  # arm the liveness protocol once underway
    heartbeat_interval_s: float = 2.0
    heartbeat_timeout_s: float = 30.0
    # straggler-tolerant rounds (fedavg family): fraction of live workers
    # whose results close a round at the deadline; None deadline = wait
    # for every live worker (dead ones are still skipped via heartbeats)
    quorum_fraction: float = 1.0
    round_deadline_s: float | None = None
    # seeded fault injection for THIS rank (None/disabled = real traffic)
    fault: FaultPolicy | None = None
    # -- telemetry (docs/OBSERVABILITY.md) ---------------------------------
    # directory for THIS rank's artifacts: trace_rank<r>.json span dump,
    # metrics_rank<r>.json snapshot, flight_rank<r>_*.json crash rings;
    # None + trace=False keeps the telemetry plane fully disabled
    telemetry_dir: str | None = None
    trace: bool = False  # span tracing without (or in addition to) a dir
    trace_jax: bool = False  # wrap spans in jax.profiler.TraceAnnotation


def load_ip_config(path: str) -> dict[int, tuple[str, int]]:
    """JSON ``{"0": ["host", port], ...}`` -> rank table (the reference
    uses CSV ``ip_config`` files; JSON keeps the one-file shape)."""
    with open(path) as f:
        raw = json.load(f)
    return {int(r): (str(h), int(p)) for r, (h, p) in raw.items()}


def _make_transport(dep: DeployConfig) -> BaseTransport:
    backend = dep.backend.upper()
    if backend in ("PUBSUB", "MQTT", "PUBSUB_BLOB", "MQTT_S3"):
        from fedml_tpu.core.transport.broker import RemoteTopicBus
        from fedml_tpu.core.transport.pubsub import BlobStore

        assert dep.broker is not None, f"{dep.backend} needs --broker"
        bus = RemoteTopicBus(*dep.broker)
        store = None
        if backend in ("PUBSUB_BLOB", "MQTT_S3"):
            assert dep.blob_dir is not None, (
                "pubsub_blob needs --blob_dir (file-backed cross-process "
                "blob store)"
            )
            store = BlobStore(root=dep.blob_dir)
        transport = create_transport(
            dep.backend, dep.rank, bus=bus, store=store,
            size=dep.world_size,
        )
    else:
        assert dep.ip_config is not None, f"{dep.backend} needs --ip_config"
        transport = create_transport(
            dep.backend, dep.rank, ip_config=dep.ip_config
        )
    if dep.fault is not None and dep.fault.enabled():
        transport = ChaosTransport(transport, dep.fault)
    return transport


# ---------------------------------------------------------------------------
# readiness handshake + liveness
# ---------------------------------------------------------------------------


def _server_dead_peer_cb(server: ServerManager):
    """Route heartbeat-detected client deaths into the actor.

    Actors with straggler-tolerant rounds (``on_peer_dead``) absorb the
    death — the round closes over the survivors or aborts with a quorum
    diagnostic. Actors without it (SplitNN's strictly-sequential
    round-robin cannot skip a rank) record the failure and stop the
    transport, which is exactly the "fail loudly instead of hanging"
    contract from ADVICE round-5 (``deploy.py:125``)."""

    def on_dead(rank: int) -> None:
        handler = getattr(server, "on_peer_dead", None)
        if handler is not None:
            handler(rank)  # dumps its own flight artifact
            return
        server._liveness_failure = (
            f"client rank {rank} became unreachable mid-run "
            "(heartbeats stopped)"
        )
        telemetry.flight_dump(
            "dead_peer", peer=rank, detail=server._liveness_failure
        )
        server.transport.stop()

    return on_dead


def _serve_with_ready_barrier(
    server: ServerManager, dep: DeployConfig, kickoff
) -> None:
    """ACK every READY, start round 0 once all clients have announced,
    arm the dead-client watchdog, then drain until the actor finishes."""
    ready: set[int] = set()
    started = threading.Event()

    def on_ready(msg: Message) -> None:
        # ACK unconditionally (duplicates arrive by design — clients
        # re-announce until acknowledged): the ACK tells a client the
        # control channel works BOTH ways, independent of when its
        # first work message will come (a later-rank SplitNN client may
        # idle for the whole of its predecessors' epochs)
        try:
            server.send_message(
                Message(MSG_TYPE_S2C_ACK, 0, msg.sender, {})
            )
        except Exception:
            pass  # client endpoint flapped; it will re-announce
        ready.add(msg.sender)
        if len(ready) >= dep.world_size - 1 and not started.is_set():
            started.set()
            if dep.heartbeats:
                server.enable_liveness(
                    range(1, dep.world_size),
                    interval_s=dep.heartbeat_interval_s,
                    timeout_s=dep.heartbeat_timeout_s,
                    on_dead=_server_dead_peer_cb(server),
                )
            kickoff()

    # NOTE: no per-deploy heartbeat handler anymore. A client's liveness
    # view must be satisfiable BEFORE the barrier completes (its watchdog
    # arms at ACK time, but the server's own beats only start at kickoff)
    # — the Manager's default handler covers this: every beat carrying
    # ``hb_ts`` is echoed back, which both refreshes the client's
    # last-seen table and closes its RTT gauge loop.
    server.register_message_receive_handler(MSG_TYPE_C2S_READY, on_ready)
    server.transport.start()
    server.run()  # blocks until the actor's finish path stops the transport


def _announce_until_first_message(
    mgr: Manager, dep: DeployConfig
) -> tuple[threading.Event, list[str]]:
    """Client side: re-send READY until the server's ACK (or any other
    server message) arrives, then arm the server-liveness watchdog.

    Returns ``(first-inbound event, failure log)``. If ``ready_timeout``
    expires before any server message, the loop STOPS the transport so
    the caller's ``run()`` unblocks — the caller must then check the
    event and fail loudly (a silently-hung client would wedge the whole
    launcher run). Once the server HAS been heard from, the heartbeat
    monitor takes over: a server that goes silent mid-run (crashed
    endpoint, dead broker) stops the transport and records the failure
    for the caller to raise. Pub/sub caveat: a publish to a dead peer
    succeeds silently (MQTT QoS-0), so there the staleness detector is
    the only signal — which is why BOTH sides beat."""
    got = threading.Event()
    failures: list[str] = []

    class _FirstInbound:
        def receive_message(self, msg_type: int, msg: Message) -> None:
            got.set()

    mgr.transport.add_observer(_FirstInbound())

    def on_server_dead(rank: int) -> None:
        failures.append(
            "server became unreachable mid-run (no inbound traffic for "
            f"{dep.heartbeat_timeout_s}s)"
        )
        telemetry.flight_dump("dead_peer", peer=rank, detail=failures[0])
        mgr.transport.stop()

    def loop() -> None:
        deadline = time.monotonic() + dep.ready_timeout
        while not got.is_set() and time.monotonic() < deadline:
            try:
                mgr.send_message(
                    Message(MSG_TYPE_C2S_READY, mgr.rank, 0, {})
                )
            except Exception:
                pass  # server endpoint not up yet — retry
            got.wait(0.5)
        if not got.is_set():
            mgr.transport.stop()  # unblock run() -> caller raises
            return
        if dep.heartbeats:
            mgr.enable_liveness(
                [0],
                interval_s=dep.heartbeat_interval_s,
                timeout_s=dep.heartbeat_timeout_s,
                on_dead=on_server_dead,
            )

    threading.Thread(target=loop, daemon=True).start()
    return got, failures


def _check_contacted(got: threading.Event, dep: DeployConfig) -> None:
    if not got.is_set():
        raise RuntimeError(
            f"server never contacted this client within "
            f"--ready_timeout {dep.ready_timeout}s — is the server rank "
            "up and reachable?"
        )


def _run_client(mgr: Manager, dep: DeployConfig) -> None:
    """Client main loop: announce, drain until FINISH (or a detected
    server death / readiness timeout), fail loudly on either."""
    mgr.transport.start()
    got, failures = _announce_until_first_message(mgr, dep)
    mgr.run()
    _check_contacted(got, dep)
    if failures:
        raise RuntimeError(failures[0])


# ---------------------------------------------------------------------------
# rank entrypoints
# ---------------------------------------------------------------------------


def _params_digest(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def _run_dir(cfg: ExperimentConfig) -> str:
    d = os.path.join(cfg.out_dir, cfg.run_name)
    os.makedirs(d, exist_ok=True)
    return d


def _write_final(cfg: ExperimentConfig, tag: str, tree) -> str:
    """Persist final variables (numpy pytree pickle — the cross-process
    equality artifact the tests and the launcher compare)."""
    path = os.path.join(_run_dir(cfg), f"{tag}.pkl")
    host = jax.tree.map(np.asarray, tree)
    with open(path, "wb") as f:
        pickle.dump(host, f, protocol=5)
    return path


def _run_fedavg_rank(cfg: ExperimentConfig, dep: DeployConfig) -> dict:
    from fedml_tpu.algorithms.distributed_fedavg import (
        FedAvgClientActor,
        FedAvgServerActor,
    )
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    # every rank rebuilds the identical seeded dataset + partition (the
    # reference ships the same data path to every MPI rank too,
    # main_fedavg.py load_data before FedML_FedAvg_distributed)
    data = load_dataset(cfg.data)
    model = create_model(cfg.model)
    transport = _make_transport(dep)

    if dep.role == "server":
        from fedml_tpu.algorithms.distributed_fedavg import (
            QuorumLostError,
            RoundPolicy,
        )

        server = FedAvgServerActor(
            dep.world_size, transport, model, cfg,
            num_clients=cfg.data.num_clients, data=data,
            round_policy=RoundPolicy(
                quorum_fraction=dep.quorum_fraction,
                round_deadline_s=dep.round_deadline_s,
            ),
        )
        _serve_with_ready_barrier(server, dep, server.start_round)
        if server.failure is not None:
            raise QuorumLostError(
                f"run aborted (straggler tolerance exhausted): "
                f"{server.failure}"
            )
        if not server.done.is_set():
            raise RuntimeError(
                f"server stopped before completing {cfg.fed.num_rounds} "
                f"rounds (round_idx={server.round_idx})"
            )
        path = _write_final(cfg, "final_params", server.variables)
        # global test metrics on the final model (reference
        # test_on_server_for_all_clients, FedAVGAggregator.py:110-164)
        from fedml_tpu.algorithms.base import build_evaluator, make_task

        arrays = data.to_arrays(pad_multiple=cfg.data.batch_size)
        ev = build_evaluator(model, make_task(data.task))
        metrics = {
            k: float(v)
            for k, v in ev(server.variables, arrays.test_x,
                           arrays.test_y).items()
        }
        return {
            "role": "server",
            "algorithm": cfg.fed.algorithm,
            "backend": dep.backend,
            "world_size": dep.world_size,
            "rounds": server.round_idx,
            "final_params": path,
            "params_digest": _params_digest(server.variables),
            "dead_peers": sorted(server.dead_peers),
            **metrics,
        }

    client = FedAvgClientActor(
        dep.rank, dep.world_size, transport, model, data, cfg
    )
    _run_client(client, dep)
    return {"role": "client", "rank": dep.rank, "status": "finished"}


def _run_splitnn_rank(cfg: ExperimentConfig, dep: DeployConfig) -> dict:
    from fedml_tpu.algorithms.split import SplitNNSim
    from fedml_tpu.algorithms.split_actors import (
        SplitNNClientActor,
        SplitNNServerActor,
    )
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models.gkt import SplitClientNet, SplitServerNet

    if dep.world_size != cfg.data.num_clients + 1:
        raise ValueError(
            "splitnn deployment: world_size must be num_clients+1 "
            f"(got {dep.world_size} vs {cfg.data.num_clients}+1)"
        )
    data = load_dataset(cfg.data)
    client_model = SplitClientNet()
    server_model = SplitServerNet(num_classes=cfg.model.num_classes)
    # the seeded sim init is the shared starting point: each rank takes
    # only its own piece (the reference distributes initial weights by
    # broadcast; here init is deterministic so no round-0 broadcast of
    # the lower stacks is needed)
    sim = SplitNNSim(client_model, server_model, data, cfg)
    state0 = sim.init()
    transport = _make_transport(dep)

    if dep.role == "server":
        server = SplitNNServerActor(
            dep.world_size, transport, server_model,
            state0.server_vars, cfg,
        )
        _serve_with_ready_barrier(server, dep, server.start_round)
        if not server.done.is_set():
            liveness = getattr(server, "_liveness_failure", None)
            raise RuntimeError(
                liveness
                if liveness is not None
                else f"splitnn server stopped before completing "
                     f"{cfg.fed.num_rounds} rounds (round_idx="
                     f"{server.round_idx})"
            )
        path = _write_final(cfg, "final_server_params", server.server_vars)
        return {
            "role": "server",
            "algorithm": "splitnn",
            "backend": dep.backend,
            "world_size": dep.world_size,
            "rounds": len(server.metrics_history),
            "final_params": path,
            "params_digest": _params_digest(server.server_vars),
            "metrics_history": server.metrics_history,
        }

    client = SplitNNClientActor(
        dep.rank, dep.world_size, transport, client_model,
        jax.tree.map(lambda s: s[dep.rank - 1], state0.client_stack),
        data, cfg,
    )
    _run_client(client, dep)
    path = _write_final(
        cfg, f"final_client{dep.rank}_params", client.c_vars
    )
    return {
        "role": "client",
        "rank": dep.rank,
        "status": "finished",
        "final_params": path,
        "params_digest": _params_digest(client.c_vars),
    }


def run_role(cfg: ExperimentConfig, dep: DeployConfig) -> dict:
    """Run THIS process's rank to completion; returns the rank summary."""
    if dep.telemetry_dir or dep.trace or dep.trace_jax:
        telemetry.configure(
            # --trace without a dir still gets dumps, in the run dir
            telemetry_dir=dep.telemetry_dir
            or telemetry.default_dir(cfg.out_dir, cfg.run_name),
            rank=dep.rank,
            jax_profiler=dep.trace_jax,
        )
    algo = cfg.fed.algorithm
    if algo in FEDAVG_FAMILY:
        return _run_fedavg_rank(cfg, dep)
    if algo == "splitnn":
        return _run_splitnn_rank(cfg, dep)
    raise ValueError(
        f"algorithm {algo!r} has no deployment path; deployable: "
        f"{DEPLOY_ALGORITHMS} (every other algorithm runs via the "
        "compiled simulator, python -m fedml_tpu.experiments.run without "
        "--role)"
    )

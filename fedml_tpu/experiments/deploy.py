"""Process-separated deployment: one OS process per rank, over a socket.

The reference's canonical deployment is N separate OS processes —
``mpirun -np $PROCESS_NUM`` launching a per-rank ``main_fedavg.py``
(``fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:1-20``)
and the cross-silo shell launchers that start a server role and client
roles on separate machines
(``fedml_experiments/distributed/fedavg_cross_silo/run_server.sh``,
``run_client.sh``). This module is that surface for the TPU framework:
``python -m fedml_tpu.experiments.run --role server|client --rank N
--world_size W --backend grpc|tcp|trpc|pubsub|pubsub_blob ...`` runs ONE
rank; ``scripts/run_distributed.sh`` is the mpirun-shaped localhost
launcher.

Equality contract: every process derives its data partition, model init,
and rng keys from the shared seeded config, and the actors are the same
:mod:`fedml_tpu.algorithms.distributed_fedavg` /
:mod:`fedml_tpu.algorithms.split_actors` classes whose loopback runs are
equality-pinned against the compiled sims — so an N-process run over real
sockets matches the compiled simulator to float round-off
(``tests/test_deploy.py`` pins it cross-process).

Readiness: socket transports have no MPI-style barrier, and the pub/sub
path drops publishes with no subscriber (MQTT QoS-0 semantics). Clients
therefore re-announce ``MSG_TYPE_C2S_READY`` every 0.5 s until the
server ACKs (``MSG_TYPE_S2C_ACK`` reply to each READY) or any other
server message arrives; the server starts round 0 once all
``world_size - 1`` distinct ranks have announced. The ACK matters:
liveness must not be inferred from WORK traffic — a later-rank SplitNN
client legitimately idles for the whole of its predecessors' epochs, and
before the ACK existed it would hit ``ready_timeout`` and kill a healthy
run. Send failures during announcement (server socket not yet bound) are
retried, which makes process launch order irrelevant — the reference
gets the same property from MQTT broker buffering + its client
"register" message.

Liveness (docs/FAULT_TOLERANCE.md): once the run is underway both sides
heartbeat (``MSG_TYPE_HEARTBEAT``) and watch per-peer last-seen times.
The server routes dead peers into the actor's straggler logic
(``FedAvgServerActor.on_peer_dead`` — quorum/deadline rounds) instead of
blocking forever on its inbox; clients detect a dead server and exit
loudly. Deterministic fault injection for all of this lives in
:mod:`fedml_tpu.core.transport.chaos` and is threaded here via
``DeployConfig.fault``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import threading
import time

import jax
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import telemetry
from fedml_tpu.core.manager import Manager, ServerManager, create_transport
from fedml_tpu.core.message import (
    MSG_TYPE_C2S_JOIN,
    MSG_TYPE_C2S_READY,
    MSG_TYPE_S2C_ACK,
    Message,
)
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.core.transport.chaos import ChaosTransport, FaultPolicy

FEDAVG_FAMILY = ("fedavg", "fedopt", "fednova")
DEPLOY_ALGORITHMS = FEDAVG_FAMILY + ("splitnn",)


@dataclasses.dataclass(frozen=True)
class DeployConfig:
    """One rank's deployment coordinates (the reference passes these as
    ``--client_id/--server_ip`` flags + ``ip_config`` CSV tables,
    ``ip_config_utils.py``)."""

    role: str  # "server" | "client"
    rank: int  # 0 = server, >=1 = client
    world_size: int
    backend: str = "grpc"
    ip_config: dict[int, tuple[str, int]] | None = None
    broker: tuple[str, int] | None = None  # pubsub* backends
    blob_dir: str | None = None  # pubsub_blob file-backed store
    ready_timeout: float = 120.0
    # -- fault tolerance (docs/FAULT_TOLERANCE.md) -------------------------
    heartbeats: bool = True  # arm the liveness protocol once underway
    heartbeat_interval_s: float = 2.0
    heartbeat_timeout_s: float = 30.0
    # straggler-tolerant rounds (fedavg family): fraction of live workers
    # whose results close a round at the deadline; None deadline = wait
    # for every live worker (dead ones are still skipped via heartbeats)
    quorum_fraction: float = 1.0
    round_deadline_s: float | None = None
    # -- crash recovery (docs/FAULT_TOLERANCE.md "Recovery") ---------------
    # server rank: checkpoint ServerState every N closed rounds under
    # <run_dir>/ckpt and resume from the latest checkpoint on restart
    # (0 = off; the same flag drives the simulator path)
    checkpoint_every: int = 0
    # deadline-under-quorum re-arms before the quorum-lost abort fires —
    # under a supervisor a crashed rank is seconds from rejoining
    recovery_extensions: int = 0
    # seeded fault injection for THIS rank (None/disabled = real traffic)
    fault: FaultPolicy | None = None
    # -- Byzantine defense (docs/FAULT_TOLERANCE.md "Threat model") --------
    # server rank: quarantine clients whose cross-round EWMA anomaly
    # score exceeds the threshold (0 = off); they stay served but their
    # results are excluded from aggregation, and the reputation state
    # rides the round checkpoint so it survives server restarts
    quarantine_threshold: float = 0.0
    quarantine_decay: float = 0.7
    # rounds a rank may sit in quarantine before it is PERMANENTLY
    # evicted from the membership ledger (0 = never escalate)
    quarantine_evict_after: int = 0
    # -- elastic membership (docs/FAULT_TOLERANCE.md "Elastic
    # membership"): client rank — after submitting the result for this
    # round, announce a graceful LEAVE and wind down (None = stay for
    # the whole run)
    leave_after_round: int | None = None
    # server rank, set by the SUPERVISOR on a restart: ranks whose final
    # summary reported a graceful LEAVE (or eviction) — they are never
    # respawned, so even if the restored checkpoint predates the
    # departure the barrier must not wait for them (the ledger is
    # brought up to date before the required set is computed)
    presumed_left: tuple[int, ...] = ()
    # like presumed_left but for ranks whose summary said "evicted":
    # the restored ledger must mark them EVICTED, not LEFT — a LEFT
    # rank may JOIN back, a ban must survive the restart
    presumed_evicted: tuple[int, ...] = ()
    # -- async + tiered aggregation (docs/FAULT_TOLERANCE.md "Async +
    # tiered worlds"): the tier topology this world runs under
    # (``root:<L>`` — one root, L leaf aggregators; None = flat).
    # Roles "server" (the root) and "leaf" consume it; clients are
    # topology-blind (they only ever talk to rank 0 of THEIR world).
    tier_spec: str | None = None
    # leaf rank only: the ROOT world's rank table (the leaf's own
    # ``ip_config`` is its leaf world, where it is rank 0)
    uplink_ip_config: dict[int, tuple[str, int]] | None = None
    # leaf rank only: global client id of this leaf's slot 0 (None =
    # the TierSpec default — contiguous equal-size blocks)
    tier_client_base: int | None = None
    # -- telemetry (docs/OBSERVABILITY.md) ---------------------------------
    # directory for THIS rank's artifacts: trace_rank<r>.json span dump,
    # metrics_rank<r>.json snapshot, flight_rank<r>_*.json crash rings;
    # None + trace=False keeps the telemetry plane fully disabled
    telemetry_dir: str | None = None
    trace: bool = False  # span tracing without (or in addition to) a dir
    trace_jax: bool = False  # wrap spans in jax.profiler.TraceAnnotation
    # periodic metrics time-series flush: seconds between snapshot rows
    # appended to metrics_rank<r>.jsonl (None = off; the round-latency
    # SLO surface of a long-lived server — histograms carry p50/p95/p99
    # — docs/OBSERVABILITY.md "Performance observability")
    metrics_interval: float | None = None
    # live OpenMetrics exporter (core/export.py, docs/OBSERVABILITY.md
    # "Live export and SLOs"): serve /metrics + /statusz + /healthz on
    # this port (0 = ephemeral; None = no socket, the default — the
    # zero-cost-when-off rule). SLO specs ride FedConfig.slos. The
    # endpoints are unauthenticated; metrics_host restricts the bind
    # (default any-interface so a remote Prometheus can scrape).
    metrics_port: int | None = None
    metrics_host: str = "0.0.0.0"


def load_ip_config(path: str) -> dict[int, tuple[str, int]]:
    """JSON ``{"0": ["host", port], ...}`` -> rank table (the reference
    uses CSV ``ip_config`` files; JSON keeps the one-file shape)."""
    with open(path) as f:
        raw = json.load(f)
    return {int(r): (str(h), int(p)) for r, (h, p) in raw.items()}


def _make_transport(dep: DeployConfig) -> BaseTransport:
    backend = dep.backend.upper()
    if backend in ("PUBSUB", "MQTT", "PUBSUB_BLOB", "MQTT_S3"):
        from fedml_tpu.core.transport.broker import RemoteTopicBus
        from fedml_tpu.core.transport.pubsub import BlobStore

        assert dep.broker is not None, f"{dep.backend} needs --broker"
        bus = RemoteTopicBus(*dep.broker)
        store = None
        if backend in ("PUBSUB_BLOB", "MQTT_S3"):
            assert dep.blob_dir is not None, (
                "pubsub_blob needs --blob_dir (file-backed cross-process "
                "blob store)"
            )
            store = BlobStore(root=dep.blob_dir)
        transport = create_transport(
            dep.backend, dep.rank, bus=bus, store=store,
            size=dep.world_size,
        )
    else:
        assert dep.ip_config is not None, f"{dep.backend} needs --ip_config"
        transport = create_transport(
            dep.backend, dep.rank, ip_config=dep.ip_config
        )
    if dep.fault is not None and dep.fault.enabled():
        if dep.fault.corrupt_prob and backend not in (
                "TCP", "PUBSUB", "MQTT", "PUBSUB_BLOB", "MQTT_S3"):
            import sys as _sys

            print(
                "warning: --fault_corrupt flips bits in the sealed "
                "tcp/pubsub frame codecs; the "
                f"{dep.backend} backend does not seal frames, so the "
                "corrupt fault is inert here",
                file=_sys.stderr,
            )
        transport = ChaosTransport(transport, dep.fault)
    return transport


# ---------------------------------------------------------------------------
# readiness handshake + liveness
# ---------------------------------------------------------------------------


def _server_dead_peer_cb(server: ServerManager):
    """Route heartbeat-detected client deaths into the actor.

    Actors with straggler-tolerant rounds (``on_peer_dead``) absorb the
    death — the round closes over the survivors or aborts with a quorum
    diagnostic. Actors without it (SplitNN's strictly-sequential
    round-robin cannot skip a rank) record the failure and stop the
    transport, which is exactly the "fail loudly instead of hanging"
    contract from ADVICE round-5 (``deploy.py:125``)."""

    def on_dead(rank: int) -> None:
        handler = getattr(server, "on_peer_dead", None)
        if handler is not None:
            handler(rank)  # dumps its own flight artifact
            return
        server._liveness_failure = (
            f"client rank {rank} became unreachable mid-run "
            "(heartbeats stopped)"
        )
        telemetry.flight_dump(
            "dead_peer", peer=rank, detail=server._liveness_failure
        )
        server.transport.stop()

    return on_dead


class _AliveObserver:
    """Second transport observer on the server: counts the SENDER of
    every inbound message toward the readiness barrier. In a fresh run
    this is inert (a fresh client's first message IS its JOIN); after a
    supervised server restart it is what completes the barrier — the
    surviving clients are blocked mid-run waiting for the next sync and
    only emit heartbeats, which prove they are up and reachable."""

    def __init__(self, note):
        self._note = note

    def receive_message(self, msg_type: int, msg: Message) -> None:
        self._note(msg.sender)


def _serve_with_ready_barrier(
    server: ServerManager, dep: DeployConfig, kickoff
) -> None:
    """ACK every READY/JOIN, start (or resume) the run once all clients
    have announced or otherwise proven liveness, arm the dead-client
    watchdog, then drain until the actor finishes. A JOIN arriving AFTER
    kickoff is a rejoin: it is routed to the actor's ``on_peer_rejoin``
    (docs/FAULT_TOLERANCE.md "Recovery")."""
    ready: set[int] = set()
    started = threading.Event()
    # the barrier's required set: normally the launch world, but a
    # server RESTORED from an elastic checkpoint serves the ledger's
    # world — a rank that gracefully LEFT before the crash must not be
    # waited on (it is never coming back), and a mid-run admission that
    # outlived the crash completes the barrier like any member
    for r in dep.presumed_left:
        # the supervisor SAW these ranks depart (their final summary
        # said "left") and will never respawn them; if the restored
        # checkpoint predates the departure the ledger still lists
        # them ACTIVE and the barrier would wait forever — bring the
        # ledger up to date first
        leave = getattr(server, "on_peer_leave", None)
        if leave is not None:
            leave(r)
    for r in dep.presumed_evicted:
        # same, but the departure was a PERMANENT ban: replaying it as
        # a LEAVE would let the banned (possibly adversarial) rank
        # JOIN back in — re-evict so the restored ledger rejects it.
        # notify=False: the rank's process already exited with
        # status "evicted"; a FINISH to its gone endpoint would only
        # sit out the transport retry budget and delay the barrier
        evict = getattr(server, "evict_rank", None)
        if evict is not None:
            evict(r, notify=False)
    required = (set(server.client_ranks())
                - set(dep.presumed_left)
                - set(dep.presumed_evicted))
    # an empty required set normally means an actor without a ledger —
    # fall back to waiting for the launch world. But when departures
    # EXPLAIN the emptiness (every restored member departed by design)
    # the launch ranks are never respawned: falling back would wedge
    # the relaunch forever — wait for the next admission instead
    # (note_alive grows the set as JOINs are admitted)
    all_departed = (not required
                    and bool(dep.presumed_left or dep.presumed_evicted))
    if not required and not all_departed:
        required = set(range(1, dep.world_size))

    def note_alive(sender: int) -> None:
        # observers run on the single dispatch thread — no lock needed
        if started.is_set():
            return
        if sender not in required:
            if all_departed and sender in server.client_ranks():
                # admitted after the all-departed barrier was computed:
                # this rank IS the world now — it completes the barrier
                required.add(sender)
            else:
                return
        ready.add(sender)
        if len(ready) >= len(required):
            started.set()
            if dep.heartbeats:
                server.enable_liveness(
                    # re-read: ranks admitted DURING the barrier window
                    # are watched from kickoff too
                    server.client_ranks(),
                    interval_s=dep.heartbeat_interval_s,
                    timeout_s=dep.heartbeat_timeout_s,
                    on_dead=_server_dead_peer_cb(server),
                )
            kickoff()

    def on_ready(msg: Message) -> None:
        # ACK unconditionally (duplicates arrive by design — clients
        # re-announce until acknowledged): the ACK tells a client the
        # control channel works BOTH ways, independent of when its
        # first work message will come (a later-rank SplitNN client may
        # idle for the whole of its predecessors' epochs)
        try:
            server.send_message(
                Message(MSG_TYPE_S2C_ACK, 0, msg.sender, {})
            )
        except Exception:
            pass  # client endpoint flapped; it will re-announce
        note_alive(msg.sender)

    def on_join(msg: Message) -> None:
        join = getattr(server, "on_peer_join",
                       getattr(server, "on_peer_rejoin", None))
        membership = getattr(server, "membership", None)
        if (membership is not None
                and msg.sender in membership.get("evicted", ())):
            # a restored ledger may ban a rank INSIDE the launch world
            # (--quarantine_evict_after before the restart): its JOIN is
            # never ACKed, pre-kickoff included — ACKing would park the
            # banned client waiting forever for a sync it will never
            # get, masquerading as a healthy member
            return
        if started.is_set():
            if join is not None:
                # unified membership entry: rejoin for active members
                # (WELCOMEd with the current round's sync), mid-run
                # ADMISSION for ranks beyond the launch world, silent
                # rejection for evicted ranks
                # (docs/FAULT_TOLERANCE.md "Elastic membership")
                if join(msg.sender) == "admitted":
                    # an admission is not synced until the NEXT round
                    # boundary: ACK now so the joiner's announce loop
                    # stops waiting instead of racing ready_timeout
                    # against an in-flight round that may outlast it
                    # (without heartbeats the ACK is its only contact)
                    try:
                        server.send_message(
                            Message(MSG_TYPE_S2C_ACK, 0, msg.sender, {})
                        )
                    except Exception:
                        pass  # joiner endpoint flapped; it re-JOINs
                return
            # actor without mid-run membership (SplitNN's strictly
            # sequential rounds): ACK so the client stops announcing
            on_ready(msg)
            return
        returning = (membership is not None
                     and msg.sender in membership.get("left", ()))
        if join is not None and (
                not (1 <= msg.sender < dep.world_size) or returning):
            # a beyond-world rank announcing BEFORE kickoff — or an
            # in-world rank a RESTORED ledger marks LEFT (departed
            # before the server was SIGKILLed, relaunched now): admit
            # it into the ledger (first cohort slot at the next round
            # boundary); without the re-admission the LEFT rank would
            # be ACKed but never served — parked forever outside
            # client_ranks(). It neither counts toward nor blocks the
            # launch barrier, which still waits for the configured
            # world. An EVICTED rank is never ACKed — its announce
            # loop times out loudly on its side.
            if join(msg.sender) == "rejected":
                return
        on_ready(msg)

    # NOTE: no per-deploy heartbeat handler anymore. A client's liveness
    # view must be satisfiable BEFORE the barrier completes (its watchdog
    # arms at ACK time, but the server's own beats only start at kickoff)
    # — the Manager's default handler covers this: every beat carrying
    # ``hb_ts`` is echoed back, which both refreshes the client's
    # last-seen table and closes its RTT gauge loop.
    server.register_message_receive_handler(MSG_TYPE_C2S_READY, on_ready)
    server.register_message_receive_handler(MSG_TYPE_C2S_JOIN, on_join)
    server.transport.add_observer(_AliveObserver(note_alive))
    server.transport.start()
    server.run()  # blocks until the actor's finish path stops the transport


def _announce_until_first_message(
    mgr: Manager, dep: DeployConfig
) -> tuple[threading.Event, list[str]]:
    """Client side: re-send JOIN until the server's ACK (fresh run), its
    WELCOME (mid-run rejoin), or any other server message arrives, then
    arm the server-liveness watchdog. A fresh start and a supervised
    restart are deliberately indistinguishable here — the SERVER decides
    (pre-kickoff JOIN counts toward the barrier like READY; post-kickoff
    JOIN is a rejoin, docs/FAULT_TOLERANCE.md "Recovery").

    Returns ``(first-inbound event, failure log)``. If ``ready_timeout``
    expires before any server message, the loop STOPS the transport so
    the caller's ``run()`` unblocks — the caller must then check the
    event and fail loudly (a silently-hung client would wedge the whole
    launcher run). Once the server HAS been heard from, the heartbeat
    monitor takes over: a server that goes silent mid-run (crashed
    endpoint, dead broker) stops the transport and records the failure
    for the caller to raise. Pub/sub caveat: a publish to a dead peer
    succeeds silently (MQTT QoS-0), so there the staleness detector is
    the only signal — which is why BOTH sides beat."""
    got = threading.Event()
    failures: list[str] = []

    class _FirstInbound:
        def receive_message(self, msg_type: int, msg: Message) -> None:
            got.set()

    mgr.transport.add_observer(_FirstInbound())

    def on_server_dead(rank: int) -> None:
        failures.append(
            "server became unreachable mid-run (no inbound traffic for "
            f"{dep.heartbeat_timeout_s}s)"
        )
        telemetry.flight_dump("dead_peer", peer=rank, detail=failures[0])
        mgr.transport.stop()

    def loop() -> None:
        deadline = time.monotonic() + dep.ready_timeout
        while not got.is_set() and time.monotonic() < deadline:
            try:
                mgr.send_message(
                    Message(MSG_TYPE_C2S_JOIN, mgr.rank, 0, {})
                )
            except Exception:
                pass  # server endpoint not up yet — retry
            got.wait(0.5)
        if not got.is_set():
            mgr.transport.stop()  # unblock run() -> caller raises
            return
        if dep.heartbeats:
            mgr.enable_liveness(
                [0],
                interval_s=dep.heartbeat_interval_s,
                timeout_s=dep.heartbeat_timeout_s,
                on_dead=on_server_dead,
            )

    threading.Thread(target=loop, daemon=True).start()
    return got, failures


def _check_contacted(got: threading.Event, dep: DeployConfig) -> None:
    if not got.is_set():
        raise RuntimeError(
            f"server never contacted this client within "
            f"--ready_timeout {dep.ready_timeout}s — is the server rank "
            "up and reachable?"
        )


def _run_client(mgr: Manager, dep: DeployConfig) -> None:
    """Client main loop: announce, drain until FINISH (or a detected
    server death / readiness timeout), fail loudly on either."""
    mgr.transport.start()
    got, failures = _announce_until_first_message(mgr, dep)
    mgr.run()
    _check_contacted(got, dep)
    if failures:
        raise RuntimeError(failures[0])


# ---------------------------------------------------------------------------
# rank entrypoints
# ---------------------------------------------------------------------------


def _params_digest(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def _run_dir(cfg: ExperimentConfig) -> str:
    d = os.path.join(cfg.out_dir, cfg.run_name)
    os.makedirs(d, exist_ok=True)
    return d


def _write_final(cfg: ExperimentConfig, tag: str, tree) -> str:
    """Persist final variables (numpy pytree pickle — the cross-process
    equality artifact the tests and the launcher compare)."""
    path = os.path.join(_run_dir(cfg), f"{tag}.pkl")
    host = jax.tree.map(np.asarray, tree)
    with open(path, "wb") as f:
        pickle.dump(host, f, protocol=5)
    return path


def _run_tier_leaf_rank(cfg: ExperimentConfig, dep: DeployConfig) -> dict:
    """Run ONE leaf aggregator (docs/FAULT_TOLERANCE.md "Async +
    tiered worlds"): rank 0 of its own leaf world toward its clients
    (``--ip_config``), member rank ``dep.rank`` of the root world
    toward the root (``--uplink_ip_config``). The leaf waits for its
    OWN clients' readiness barrier first, then announces JOIN upstream
    — so the root's barrier completes exactly when every leaf's
    subtree is servable."""
    from fedml_tpu.algorithms.async_actors import TierAggregatorActor
    from fedml_tpu.algorithms.distributed_fedavg import (
        QuorumLostError,
        RoundPolicy,
    )
    from fedml_tpu.core.reputation import QuarantinePolicy
    from fedml_tpu.core.tier import TierSpec
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    tier = TierSpec.parse(dep.tier_spec)
    data = load_dataset(cfg.data)
    model = create_model(cfg.model)
    # downlink: this leaf IS rank 0 of its leaf world. uplink: member
    # rank of the root world — chaos flags stay on the client-facing
    # edge only (a faulted uplink would punish every client at once).
    downlink = _make_transport(dataclasses.replace(dep, rank=0))
    uplink_t = _make_transport(dataclasses.replace(
        dep, ip_config=dep.uplink_ip_config, fault=None,
    ))
    uplink = Manager(dep.rank, tier.root_world_size, uplink_t)
    base = (
        dep.tier_client_base
        if dep.tier_client_base is not None
        else tier.client_base(dep.rank, dep.world_size - 1)
    )
    leaf = TierAggregatorActor(
        dep.world_size, downlink, uplink, model, cfg,
        client_base=base,
        num_clients=cfg.data.num_clients, data=data,
        round_policy=RoundPolicy(
            quorum_fraction=dep.quorum_fraction,
            round_deadline_s=dep.round_deadline_s,
            recovery_extensions=dep.recovery_extensions,
        ),
        quarantine=QuarantinePolicy(
            threshold=dep.quarantine_threshold,
            decay=dep.quarantine_decay,
            evict_after=dep.quarantine_evict_after,
        ),
    )
    up_state: dict = {"got": None, "failures": []}

    def kickoff() -> None:
        # this leaf's subtree is ready: surface upstream. The announce
        # helper re-sends JOIN until the root answers and then arms
        # the uplink liveness watchdog — a dead root stops the uplink,
        # and the bridge below stops the downlink so the leaf fails
        # loudly instead of serving a headless subtree forever.
        uplink_t.start()
        got, failures = _announce_until_first_message(uplink, dep)
        up_state["got"], up_state["failures"] = got, failures
        threading.Thread(target=uplink.run, daemon=True,
                         name=f"leaf{dep.rank}-uplink").start()

        def bridge() -> None:
            uplink_t._stopped.wait()
            if not leaf.done.is_set():
                leaf.transport.stop()

        threading.Thread(target=bridge, daemon=True,
                         name=f"leaf{dep.rank}-uplink-bridge").start()

    _serve_with_ready_barrier(leaf, dep, kickoff)
    if leaf.failure is not None:
        raise QuorumLostError(
            f"leaf {dep.rank} aborted: {leaf.failure}"
        )
    if up_state["failures"]:
        raise RuntimeError(up_state["failures"][0])
    if up_state["got"] is not None:
        _check_contacted(up_state["got"], dep)
    if not leaf.done.is_set():
        raise RuntimeError(
            f"leaf {dep.rank} stopped before the root finished the "
            f"run (version {leaf.round_idx})"
        )
    return {
        "role": "leaf",
        "rank": dep.rank,
        "status": "finished",
        "tier_spec": dep.tier_spec,
        "client_base": base,
        "partials": leaf.partials_sent,
        "membership": leaf.membership,
        "quarantined": leaf.quarantined_ranks,
        "dead_peers": sorted(leaf.dead_peers),
    }


def _run_fedavg_rank(cfg: ExperimentConfig, dep: DeployConfig) -> dict:
    from fedml_tpu.algorithms.distributed_fedavg import (
        FedAvgClientActor,
        FedAvgServerActor,
    )
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models import create_model

    if dep.role == "leaf":
        return _run_tier_leaf_rank(cfg, dep)
    # every rank rebuilds the identical seeded dataset + partition (the
    # reference ships the same data path to every MPI rank too,
    # main_fedavg.py load_data before FedML_FedAvg_distributed)
    data = load_dataset(cfg.data)
    model = create_model(cfg.model)
    transport = _make_transport(dep)

    if dep.role == "server":
        from fedml_tpu.algorithms.distributed_fedavg import (
            QuorumLostError,
            RoundPolicy,
        )

        ckpt = None
        if dep.checkpoint_every > 0:
            from fedml_tpu.utils.checkpoint import RoundCheckpointer

            # <run_dir>/ckpt — the same layout the simulator harness
            # uses, so a deploy run and a sim run of one config share
            # the resume story (docs/FAULT_TOLERANCE.md "Recovery")
            ckpt = RoundCheckpointer(os.path.join(_run_dir(cfg), "ckpt"))
        from fedml_tpu.core.reputation import QuarantinePolicy

        # actor-class selection (docs/FAULT_TOLERANCE.md "Async +
        # tiered worlds"): async and/or tiered servers are strictly
        # opt-in subclasses — with both knobs off this constructs the
        # untouched FedAvgServerActor, byte-identical to every prior
        # release (pinned in tests/test_async.py)
        from fedml_tpu.core.async_agg import AsyncConfig
        from fedml_tpu.core.tier import TierSpec

        acfg = AsyncConfig.from_fed(cfg.fed)
        extra = {}
        if dep.tier_spec is not None:
            from fedml_tpu.algorithms.async_actors import (
                AsyncTierRootActor,
                TierRootActor,
            )

            tier = TierSpec.parse(dep.tier_spec)
            if dep.world_size != tier.root_world_size:
                raise ValueError(
                    f"--tier_spec {dep.tier_spec} implies a root "
                    f"world of {tier.root_world_size} (root + "
                    f"{tier.n_leaves} leaves), got --world_size "
                    f"{dep.world_size}"
                )
            cls = AsyncTierRootActor if acfg.enabled() else TierRootActor
            extra["tier_spec"] = tier
        elif acfg.enabled():
            from fedml_tpu.algorithms.async_actors import (
                AsyncFedAvgServerActor,
            )

            cls = AsyncFedAvgServerActor
        else:
            cls = FedAvgServerActor
        server = cls(
            dep.world_size, transport, model, cfg,
            num_clients=cfg.data.num_clients, data=data,
            round_policy=RoundPolicy(
                quorum_fraction=dep.quorum_fraction,
                round_deadline_s=dep.round_deadline_s,
                recovery_extensions=dep.recovery_extensions,
            ),
            checkpointer=ckpt,
            checkpoint_every=dep.checkpoint_every or 1,
            quarantine=QuarantinePolicy(
                threshold=dep.quarantine_threshold,
                decay=dep.quarantine_decay,
                evict_after=dep.quarantine_evict_after,
            ),
            **extra,
        )
        try:
            if server.resumed_from >= cfg.fed.num_rounds:
                # restored AT the end (crash between the final round
                # closing and the summary): nothing to run, and the
                # clients that finished the run may be gone for good —
                # don't wait on a readiness barrier that can never
                # complete; just finish and emit the summary
                server.done.set()
                server.finish_all()
            else:
                _serve_with_ready_barrier(server, dep, server.kickoff)
        finally:
            if ckpt is not None:
                ckpt.close()
        if server.failure is not None:
            raise QuorumLostError(
                f"run aborted (straggler tolerance exhausted): "
                f"{server.failure}"
            )
        if not server.done.is_set():
            raise RuntimeError(
                f"server stopped before completing {cfg.fed.num_rounds} "
                f"rounds (round_idx={server.round_idx})"
            )
        path = _write_final(cfg, "final_params", server.variables)
        # global test metrics on the final model (reference
        # test_on_server_for_all_clients, FedAVGAggregator.py:110-164)
        from fedml_tpu.algorithms.base import build_evaluator, make_task

        arrays = data.to_arrays(pad_multiple=cfg.data.batch_size)
        ev = build_evaluator(model, make_task(data.task))
        metrics = {
            k: float(v)
            for k, v in ev(server.variables, arrays.test_x,
                           arrays.test_y).items()
        }
        return {
            "role": "server",
            "algorithm": cfg.fed.algorithm,
            "backend": dep.backend,
            "world_size": dep.world_size,
            "rounds": server.round_idx,
            # first round executed by THIS incarnation (0 = fresh start;
            # > 0 = restored from <run_dir>/ckpt after a crash)
            "resumed_from": server.resumed_from,
            "final_params": path,
            "params_digest": _params_digest(server.variables),
            "dead_peers": sorted(server.dead_peers),
            # the Byzantine-defense plane's verdicts (docs/
            # FAULT_TOLERANCE.md "Threat model"): the defense rule in
            # force and which ranks ended the run quarantined
            "defense": cfg.fed.robust_method,
            "quarantined": server.quarantined_ranks,
            # the elastic-membership verdicts (docs/FAULT_TOLERANCE.md
            # "Elastic membership"): who ended the run active / left /
            # evicted — mid-run admissions show up as active ranks
            # beyond the launch world
            "membership": server.membership,
            "elastic": bool(cfg.fed.elastic_buckets),
            # the wire codec + aggregation layout in force
            # (docs/PERFORMANCE.md "Wire compression"): reduction
            # claims must be checkable against what actually ran
            "compress": cfg.fed.compress,
            "shard_aggregation": bool(cfg.fed.shard_aggregation),
            # the async/tier plane in force (docs/FAULT_TOLERANCE.md
            # "Async + tiered worlds"): 0 / None == the synchronous
            # flat path ran, byte-identical to prior releases
            "async_buffer_k": cfg.fed.async_buffer_k,
            "async_restored_folds": getattr(server, "restored_folds",
                                            0),
            "tier_spec": dep.tier_spec,
            # the live-observability plane in force (docs/
            # OBSERVABILITY.md "Live export and SLOs"): the SLO specs
            # evaluated this run (verdicts in slo_rank<r>.json) and
            # the exporter's bound port (None = no listener)
            "slos": list(cfg.fed.slos),
            "metrics_port": getattr(telemetry.exporter(), "port",
                                    None),
            **metrics,
        }

    client = FedAvgClientActor(
        dep.rank, dep.world_size, transport, model, data, cfg,
        leave_after_round=dep.leave_after_round,
    )
    _run_client(client, dep)
    return {
        "role": "client",
        "rank": dep.rank,
        # "left": announced a graceful LEAVE; "evicted": the server
        # FINISHed it out of the world permanently; either way the
        # Supervisor must never respawn or reactivate this rank
        "status": (
            "left" if client.left.is_set()
            else "evicted" if client.finish_reason == "evicted"
            else "finished"
        ),
    }


def _run_splitnn_rank(cfg: ExperimentConfig, dep: DeployConfig) -> dict:
    from fedml_tpu.algorithms.split import SplitNNSim
    from fedml_tpu.algorithms.split_actors import (
        SplitNNClientActor,
        SplitNNServerActor,
    )
    from fedml_tpu.data.loaders import load_dataset
    from fedml_tpu.models.gkt import SplitClientNet, SplitServerNet

    if dep.world_size != cfg.data.num_clients + 1:
        raise ValueError(
            "splitnn deployment: world_size must be num_clients+1 "
            f"(got {dep.world_size} vs {cfg.data.num_clients}+1)"
        )
    if dep.checkpoint_every:
        import sys as _sys

        # only the fedavg-family server checkpoints rounds; saying so
        # loudly beats letting the user believe a splitnn run is
        # durable (it restarts from round 0 after a crash)
        print(
            "warning: --checkpoint_every is ignored for splitnn "
            "deployments (round checkpointing covers the fedavg "
            "family only)",
            file=_sys.stderr,
        )
    if cfg.adversary.enabled():
        import sys as _sys

        print(
            "warning: --adversary_* flags are ignored by splitnn "
            "ranks (adversary injection covers the fedavg-family "
            "client actor only)",
            file=_sys.stderr,
        )
    if cfg.fed.compress != "none" or cfg.fed.shard_aggregation:
        import sys as _sys

        print(
            "warning: --compress / --shard_aggregation are ignored by "
            "splitnn ranks (the compressed + sharded weight-update "
            "path covers the fedavg family only)",
            file=_sys.stderr,
        )
    data = load_dataset(cfg.data)
    client_model = SplitClientNet()
    server_model = SplitServerNet(num_classes=cfg.model.num_classes)
    # the seeded sim init is the shared starting point: each rank takes
    # only its own piece (the reference distributes initial weights by
    # broadcast; here init is deterministic so no round-0 broadcast of
    # the lower stacks is needed)
    sim = SplitNNSim(client_model, server_model, data, cfg)
    state0 = sim.init()
    transport = _make_transport(dep)

    if dep.role == "server":
        server = SplitNNServerActor(
            dep.world_size, transport, server_model,
            state0.server_vars, cfg,
        )
        _serve_with_ready_barrier(server, dep, server.start_round)
        if not server.done.is_set():
            liveness = getattr(server, "_liveness_failure", None)
            raise RuntimeError(
                liveness
                if liveness is not None
                else f"splitnn server stopped before completing "
                     f"{cfg.fed.num_rounds} rounds (round_idx="
                     f"{server.round_idx})"
            )
        path = _write_final(cfg, "final_server_params", server.server_vars)
        return {
            "role": "server",
            "algorithm": "splitnn",
            "backend": dep.backend,
            "world_size": dep.world_size,
            "rounds": len(server.metrics_history),
            "final_params": path,
            "params_digest": _params_digest(server.server_vars),
            "metrics_history": server.metrics_history,
        }

    client = SplitNNClientActor(
        dep.rank, dep.world_size, transport, client_model,
        jax.tree.map(lambda s: s[dep.rank - 1], state0.client_stack),
        data, cfg,
    )
    _run_client(client, dep)
    path = _write_final(
        cfg, f"final_client{dep.rank}_params", client.c_vars
    )
    return {
        "role": "client",
        "rank": dep.rank,
        "status": "finished",
        "final_params": path,
        "params_digest": _params_digest(client.c_vars),
    }


# ---------------------------------------------------------------------------
# supervised deployment: spawn, watch, restart
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RankSpec:
    """One rank's launch recipe for the :class:`Supervisor`.

    ``restart_argv`` (default: ``argv``) is what a RESTARTED incarnation
    runs — the CLI supervise path strips ``--fault_*`` chaos flags here,
    so an injected ``--fault_crash_round`` kills the first incarnation
    exactly once and the replacement runs clean (otherwise the restart
    would re-crash on the same round's sync, forever)."""

    rank: int
    argv: list[str]
    restart_argv: list[str] | None = None


class SupervisorError(RuntimeError):
    """A rank exhausted its restart budget (or the run timed out); the
    message carries the rank, exit code, and last log path."""


class Supervisor:
    """Process supervisor for a deployment world: spawns every rank,
    watches exit codes, and restarts crashed ranks with capped
    exponential backoff (the same :class:`RetryPolicy` schedule the
    transports use), turning a SIGKILL of any rank into a
    kill -> restart -> rejoin -> converge loop instead of a dead run
    (docs/FAULT_TOLERANCE.md "Recovery").

    Exit-code semantics: nonzero — including signal deaths (negative
    returncodes) and chaos's
    :data:`~fedml_tpu.core.transport.chaos.CHAOS_EXIT_CODE` — is a
    crash, restarted until ``max_restarts`` per rank is spent. The run
    succeeds when the SERVER (rank 0) exits 0; its last stdout line is
    the run summary. A CLIENT exiting 0 is a genuine end-of-run
    wind-down when the server is alive and has never crashed (the
    normal case — the server exits moments later); but when the server
    has crashed or is mid-restart, a clean client exit means it obeyed
    a doomed incarnation's FINISH broadcast, so it is respawned after
    ``finish_grace_s`` — and a server crash likewise *reactivates*
    clients that were already marked finished. These respawns spend
    their own ``respawns`` cap, never the crash budget. Each attempt's
    output goes to ``<log_dir>/rank<r>_try<n>.log`` (a crashed rank's
    log is named in the failure diagnostic)."""

    def __init__(
        self,
        specs: list[RankSpec],
        *,
        max_restarts: int = 3,
        backoff=None,
        env: dict | None = None,
        cwd: str | None = None,
        log_dir: str | None = None,
        poll_interval_s: float = 0.1,
        # delay before respawning a client whose clean exit was judged
        # premature (server crashed / mid-restart); a genuine
        # end-of-run never schedules one
        finish_grace_s: float = 5.0,
    ):
        import tempfile

        from fedml_tpu.core.transport.retry import RetryPolicy

        self.specs = {s.rank: s for s in specs}
        assert 0 in self.specs, "the supervisor needs a server (rank 0)"
        self.max_restarts = max_restarts
        self.backoff = backoff or RetryPolicy(
            max_attempts=max_restarts + 1, base_delay_s=0.5,
            max_delay_s=10.0, jitter=0.25, deadline_s=float("inf"),
        )
        self.env = env
        self.cwd = cwd
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="fedml_sup_")
        os.makedirs(self.log_dir, exist_ok=True)
        self.poll_interval_s = poll_interval_s
        self.finish_grace_s = finish_grace_s
        self.procs: dict[int, "subprocess.Popen"] = {}
        self.restarts: dict[int, int] = {r: 0 for r in self.specs}
        self.respawns: dict[int, int] = {r: 0 for r in self.specs}
        self.exited: dict[int, int] = {}  # rank -> rc for clean exits
        # ranks whose clean exit was a graceful LEAVE (summary status
        # "left"): departed BY DESIGN, never respawned or reactivated —
        # the ledger keeps the departure across server restarts and the
        # restored barrier will not wait for them
        self.departed: set[int] = set()
        # the subset of departed whose status was "evicted": a restarted
        # server must re-EVICT them (not mark them LEFT) so the ban
        # survives a checkpoint that predates it
        self.evicted: set[int] = set()
        self.log_paths: dict[int, list[str]] = {r: [] for r in self.specs}
        self._fhs: list = []
        self._pending: dict[int, float] = {}  # rank -> respawn-at time
        import random as _random

        self._rng = _random.Random(0)

    def _spawn(self, rank: int, argv: list[str]) -> None:
        import subprocess

        from fedml_tpu.analysis.flags import check_rank_argv

        # one registration contract across run.py/bench.py/this
        # supervisor (fedml_tpu/analysis/flags.py): a client argv
        # carrying a rank-0-only bind flag (--metrics_port) means the
        # caller built its RankSpecs without run.py's strip — fail at
        # spawn, not at N clients fighting over one port
        check_rank_argv(argv, rank)
        n = len(self.log_paths[rank])
        path = os.path.join(self.log_dir, f"rank{rank}_try{n}.log")
        fh = open(path, "w")
        self._fhs.append(fh)
        self.log_paths[rank].append(path)
        self.procs[rank] = subprocess.Popen(
            argv, env=self.env, cwd=self.cwd, stdout=fh,
            stderr=subprocess.STDOUT,
        )

    def _terminate_all(self) -> None:
        for p in self.procs.values():
            try:
                p.terminate()
            except Exception:
                pass
        deadline = time.monotonic() + 5
        for p in self.procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                try:
                    p.kill()
                except Exception:
                    pass
        self.procs.clear()
        self._pending.clear()
        for fh in self._fhs:
            try:
                fh.close()
            except Exception:
                pass

    def _server_healthy(self) -> bool:
        """True while rank 0 is alive RIGHT NOW (not crashed, not
        awaiting respawn). Prior crashes don't matter: a client exiting
        0 under a live server incarnation is a genuine wind-down even
        after a recovery (the restarted server's own post-run work can
        take tens of seconds), and the one mis-classification this
        allows — a doomed server broadcasting FINISH moments before its
        own death — is repaired by the rank-0 crash handler, which
        reactivates every already-finished client."""
        proc = self.procs.get(0)
        return (
            0 not in self._pending
            and proc is not None
            and proc.poll() is None
        )

    def _client_departed(self, rank: int) -> str | None:
        """The rank's departure status if its last incarnation reported
        a departure BY DESIGN — its final stdout line is the run.py
        summary JSON with ``status: "left"`` (graceful LEAVE) or
        ``"evicted"`` (the server permanently banned it and FINISHed it
        out of the world); either way the rank must stay gone
        (docs/FAULT_TOLERANCE.md "Elastic membership"). None for an
        ordinary finish (or no readable summary)."""
        try:
            with open(self.log_paths[rank][-1], "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - 4096))
                tail = f.read().decode("utf-8", "replace")
        except Exception:
            return None
        for line in reversed(tail.strip().splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            # stderr rides the same stream (_spawn merges it): a
            # '{'-prefixed fragment AFTER the summary (interpreter-
            # shutdown noise, dict reprs) must not mask the summary —
            # keep scanning earlier lines past anything that is not a
            # status-carrying JSON object
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            status = (
                obj.get("status") if isinstance(obj, dict) else None
            )
            if status is not None:
                return (
                    status if status in ("left", "evicted") else None
                )
        return None

    def _respawn_finished_client(self, rank: int) -> None:
        """Schedule a respawn for a client whose clean exit was judged
        premature (it obeyed a doomed server incarnation's FINISH).
        Spends the respawn cap, not the crash budget."""
        if self.respawns[rank] >= max(3, self.max_restarts):
            self._terminate_all()
            raise SupervisorError(
                f"rank {rank} kept finishing prematurely "
                f"({self.respawns[rank]} respawns) while the "
                f"server never completed; last log: "
                f"{self.log_paths[rank][-1]}"
            )
        self.respawns[rank] += 1
        telemetry.RECORDER.record(
            "premature_finish", rank=rank,
            respawn=self.respawns[rank],
        )
        self._pending[rank] = time.monotonic() + self.finish_grace_s

    def _on_exit(self, rank: int, rc: int) -> None:
        if rc == 0:
            status = (
                self._client_departed(rank) if rank != 0 else None
            )
            if status is not None:
                # graceful LEAVE or eviction: this clean exit is a
                # mid-run departure BY DESIGN, not an obeyed FINISH —
                # stays gone even if the server is mid-restart (the
                # rank-0 respawn argv carries the departure so the
                # restored barrier will not wait for it)
                self.departed.add(rank)
                if status == "evicted":
                    self.evicted.add(rank)
                self.exited[rank] = 0
                return
            if rank == 0 or self._server_healthy():
                # the server completing, or a client winding down while
                # a never-crashed server finishes its post-run work
                # (eval + summary can take tens of seconds cold) — a
                # genuine finish, not a failure
                self.exited[rank] = 0
                return
            # server crashed / mid-restart: this client's FINISH came
            # from a doomed incarnation — bring it back so the
            # restarted server's barrier can complete
            self._respawn_finished_client(rank)
            return
        if self.restarts[rank] >= self.max_restarts:
            self._terminate_all()
            raise SupervisorError(
                f"rank {rank} exited rc={rc} with its restart budget "
                f"({self.max_restarts}) spent; last log: "
                f"{self.log_paths[rank][-1]}"
            )
        pause = self.backoff.delay(self.restarts[rank], self._rng)
        self.restarts[rank] += 1
        telemetry.METRICS.inc("recovery.restarts")
        # every restart is a flight-recorder trigger: the artifact names
        # the rank, the exit code, and the backoff it sat out
        telemetry.flight_dump(
            "restart", rank=rank, code=rc,
            attempt=self.restarts[rank], delay_s=pause,
        )
        self._pending[rank] = time.monotonic() + pause
        if rank == 0:
            # the dying server may have FINISHed clients into clean
            # exits moments before it crashed — reactivate them; its
            # restarted incarnation needs them back at the barrier.
            # Gracefully-LEFT ranks stay gone: the ledger says so.
            for r in [r for r in self.exited
                      if r != 0 and r not in self.departed]:
                del self.exited[r]
                self._respawn_finished_client(r)

    def run(self, timeout: float | None = None) -> dict:
        """Supervise until the server completes (returns the run
        summary parsed from its stdout) or a budget is exhausted
        (raises :class:`SupervisorError`)."""
        import json as _json

        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        try:
            for rank in sorted(self.specs, reverse=True):  # clients 1st
                self._spawn(rank, self.specs[rank].argv)
            while True:
                now = time.monotonic()
                if deadline is not None and now > deadline:
                    raise SupervisorError(
                        f"run exceeded its {timeout}s budget "
                        f"(restarts so far: {self.restarts})"
                    )
                for rank, at in list(self._pending.items()):
                    if now >= at:
                        del self._pending[rank]
                        spec = self.specs[rank]
                        argv = list(spec.restart_argv or spec.argv)
                        if rank == 0 and self.departed:
                            # the restored checkpoint may predate a
                            # departure: tell the restarted server
                            # which ranks are gone BY DESIGN so its
                            # barrier does not wait forever for ranks
                            # this supervisor will never respawn —
                            # evictions separately, so the ledger
                            # re-bans instead of marking merely LEFT
                            left = sorted(self.departed - self.evicted)
                            if left:
                                argv += ["--presumed_left",
                                         *(str(r) for r in left)]
                            if self.evicted:
                                argv += ["--presumed_evicted", *(
                                    str(r) for r in sorted(self.evicted)
                                )]
                        self._spawn(rank, argv)
                for rank, proc in list(self.procs.items()):
                    rc = proc.poll()
                    if rc is None:
                        continue
                    del self.procs[rank]
                    self._on_exit(rank, rc)
                if self.exited.get(0) == 0:
                    break
                if not self.procs and not self._pending:
                    raise SupervisorError(
                        "every rank exited but the server never "
                        f"completed (clean exits: {self.exited})"
                    )
                time.sleep(self.poll_interval_s)
            # server done: clients received FINISH — give them a grace
            # window to unwind, then stop any leftovers
            grace = time.monotonic() + 15
            for p in self.procs.values():
                try:
                    p.wait(timeout=max(0.1, grace - time.monotonic()))
                except Exception:
                    pass
        finally:
            self._terminate_all()
        with open(self.log_paths[0][-1]) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        summary = None
        for ln in reversed(lines):  # stderr shares the file: take the
            try:                    # last line that IS the summary JSON
                cand = _json.loads(ln)
            except ValueError:
                continue
            # json.loads also accepts bare scalars ('1.0', 'true',
            # quoted strings) that a trailing library/log line can
            # produce — the rank summary is always an object
            if isinstance(cand, dict):
                summary = cand
                break
        if summary is None:
            raise SupervisorError(
                f"server completed but its log carries no summary "
                f"JSON ({self.log_paths[0][-1]})"
            )
        return {
            "summary": summary,
            "restarts": dict(self.restarts),
            "respawns": dict(self.respawns),
            "logs": {r: list(p) for r, p in self.log_paths.items()},
        }


def run_role(cfg: ExperimentConfig, dep: DeployConfig) -> dict:
    """Run THIS process's rank to completion; returns the rank summary."""
    if (dep.telemetry_dir or dep.trace or dep.trace_jax
            or dep.metrics_interval or dep.metrics_port is not None
            or cfg.fed.slos or cfg.fed.anatomy
            or cfg.fed.profile_on_breach):
        telemetry.configure(
            # --trace without a dir still gets dumps, in the run dir
            telemetry_dir=dep.telemetry_dir
            or telemetry.default_dir(cfg.out_dir, cfg.run_name),
            rank=dep.rank,
            jax_profiler=dep.trace_jax,
            metrics_interval=dep.metrics_interval,
            metrics_port=dep.metrics_port,
            metrics_host=dep.metrics_host,
            slos=cfg.fed.slos,
            slo_scope=cfg.run_name,
        )
        if cfg.fed.anatomy or cfg.fed.profile_on_breach:
            # the round-anatomy plane (core/anatomy.py) rides the
            # telemetry dir configured above; the knobs travel in
            # FedConfig so every rank of a world shares ONE config —
            # the supervisor strips --profile_on_breach from client
            # argv (rank-0-only), explicit --role launches honor what
            # each rank's own command line says
            from fedml_tpu.core import anatomy

            anatomy.configure(
                anatomy=cfg.fed.anatomy,
                profile_on_breach=cfg.fed.profile_on_breach,
                profile_window_s=cfg.fed.profile_window_s,
                profile_max_captures=cfg.fed.profile_max_captures,
            )
    algo = cfg.fed.algorithm
    if algo in FEDAVG_FAMILY:
        return _run_fedavg_rank(cfg, dep)
    if dep.role == "leaf":
        raise ValueError(
            f"--role leaf covers the fedavg family only (tier "
            f"aggregation has no {algo!r} path)"
        )
    if algo == "splitnn":
        return _run_splitnn_rank(cfg, dep)
    raise ValueError(
        f"algorithm {algo!r} has no deployment path; deployable: "
        f"{DEPLOY_ALGORITHMS} (every other algorithm runs via the "
        "compiled simulator, python -m fedml_tpu.experiments.run without "
        "--role)"
    )

"""Experiment harness (reference ``fedml_experiments``)."""

from fedml_tpu.experiments.harness import (  # noqa: F401
    ALGORITHMS,
    Experiment,
    build_sim,
)

"""Device-resident bulk-client execution: scan-chunked streaming cohorts.

Every simulated client in the stacked round is a row of a ``[C, ...]``
operand inside one compiled program, so HBM grows linearly with cohort
size — the O(C) law ``bench.py --mem-bench`` pinned
(``peak_round_hbm_mb_c{8,64,256}``: 0.62 → 4.5 → 18.0 MB) and the
reason the 10k-client acceptance previously ran in a discrete-event
model instead of real training. This module is the FedJAX
``for_each_client`` idiom (PAPERS.md), ROADMAP item 2: stream the
sampled cohort through the device in fixed-size **blocks** of ``B``
clients. Each block runs the existing vmapped local update and is
immediately reduced to an O(model) partial —

    delta_wsum += Σ_r n_r · (clipped, tau-normalized) delta_r
    n_sum      += Σ_r n_r
    metric sums, non-param collections alike

— the same ``[weighted-delta-sum, mass, n, metric-sums]`` vocabulary
the :class:`~fedml_tpu.core.async_agg.AsyncBuffer` fold and the tier
machinery's ``[sum, n, count]`` partials already speak. The partials
fold through a ``lax.scan`` carry, so peak round memory is
**O(B + model)**, independent of C; only the final server step
(:func:`fedml_tpu.algorithms.fedavg.server_update_from_partials`)
touches model-sized state.

Contract honesty, stated like :mod:`fedml_tpu.core.elastic` states its
padding tiers:

- **Exact rules**: clip (per-row) + ``mean`` reduce and FedNova
  tau-normalized averaging decompose into partial sums exactly — bulk
  agrees with the stacked round within the reduce-reassociation ulp
  band (blockwise sums then a combine, vs one reduction over C; the
  same equality class as bucket padding / sharded psum, pinned in
  ``tests/test_bulk.py``).
- **Rejected rules**: selection/gather defenses (``median`` /
  ``trimmed_mean`` / ``krum`` / ``multikrum`` / ``fltrust``) score the
  full ``[C, D]`` stacked-delta matrix, which the streaming reduce
  never materializes. They are rejected LOUDLY at construction
  (:func:`check_bulk_compat`), never silently approximated.
- **Rejected composition**: wire compression's error-feedback residual
  is a dense ``[cohort, ...]`` carry — itself the O(C) buffer the
  block scan exists to eliminate — so ``compress + bulk`` is rejected
  at construction (a sharded/host-resident residual bank is the future
  fix; rejection is the honest present). The ``gauss`` adversary mode
  draws its noise over the full stacked shape and would repeat the
  draw per block; every other adversary mode is per-row and composes.

Elasticity applies to the block COUNT: the scan length is the
power-of-two bucket of ``ceil(C / B)`` blocks, the live cohort count
rides as a traced operand, and a partial final block is healed by the
existing :func:`fedml_tpu.core.elastic.mask_padded` — cohort churn
within the block bucket costs a compile-cache hit, not a recompile.

Telemetry (docs/OBSERVABILITY.md): ``bulk.block_size``,
``bulk.blocks_per_round``, ``bulk.padded_slots`` gauges and the
``bulk.rounds`` counter, written host-side at dispatch (never inside
the compiled program).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from fedml_tpu.core import telemetry
from fedml_tpu.core.elastic import bucket_for

Pytree = Any

#: reduce rules whose aggregate decomposes into streaming partial sums
#: (fednova is an ALGORITHM, not a robust_method, and composes because
#: its tau normalization is per-row before the weighted sum)
BULK_REDUCE_RULES = ("mean",)


@dataclasses.dataclass(frozen=True)
class BulkSpec:
    """Frozen description of the block-streaming mode (rides
    ``FedConfig.client_block_size``; 0 = off, the stacked ``[C, ...]``
    round stays byte-identical)."""

    block_size: int = 0

    def __post_init__(self):
        if self.block_size < 0:
            raise ValueError(
                f"client_block_size must be >= 0 (0 = stacked mode), "
                f"got {self.block_size}"
            )

    @staticmethod
    def from_fed(fed) -> "BulkSpec":
        return BulkSpec(
            block_size=getattr(fed, "client_block_size", 0) or 0
        )

    def enabled(self) -> bool:
        return self.block_size > 0


def check_bulk_compat(fed, adversary=None) -> None:
    """Reject configurations the streaming partial-sum reduce cannot
    express EXACTLY — raised at construction (and at run.py parse
    time), never silently approximated mid-run."""
    method = getattr(fed, "robust_method", "mean") or "mean"
    if method not in BULK_REDUCE_RULES:
        raise ValueError(
            f"robust_method={method!r} is incompatible with bulk "
            "(client_block_size) execution: selection/gather defenses "
            "(median/trimmed_mean/krum/multikrum/fltrust) score the "
            "full [C, D] stacked-delta matrix, which the O(block) "
            "streaming reduce never materializes. Run the defended "
            "cohort on the stacked path (client_block_size=0); "
            "robust_norm_clip and robust_noise_stddev DO compose "
            "(per-row clip, aggregate noise)."
        )
    if getattr(fed, "compress", "none") not in ("none", "", None):
        raise ValueError(
            "compress is incompatible with bulk (client_block_size) "
            "execution: the error-feedback residual is a dense "
            "[cohort, ...] carry — exactly the O(C) buffer the block "
            "scan exists to eliminate (core/bulk.py). Use the stacked "
            "path (client_block_size=0) for compressed experiments."
        )
    if adversary is not None and adversary.enabled() \
            and adversary.mode == "gauss":
        raise ValueError(
            "adversary mode 'gauss' is incompatible with bulk "
            "(client_block_size) execution: its noise is drawn over "
            "the full stacked [C, ...] shape, so a per-block "
            "application would repeat the same draw every block. Use "
            "the stacked path, or a per-row mode (sign_flip/"
            "scale_boost/zero/constant/collude — all compose with "
            "bulk)."
        )


def plan_blocks(cohort: int, block_size: int, elastic: bool) -> int:
    """Number of scan blocks for a ``cohort`` streamed in blocks of
    ``block_size``. Under ``elastic`` the count is bucketed to the next
    power of two — the compiled scan length depends only on the bucket,
    so cohort churn within it is a compile-cache hit (headroom blocks
    are fully masked)."""
    if cohort < 1:
        raise ValueError(f"cohort must be >= 1, got {cohort}")
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    nb = -(-cohort // block_size)
    return bucket_for(nb) if elastic else nb


class RoundPartials(NamedTuple):
    """The O(model) streaming-aggregation vocabulary: what one block of
    local updates reduces to, and what the whole round's scan carry
    accumulates — the same ``[weighted-delta-sum, mass, n,
    metric-sums]`` shape the :class:`~fedml_tpu.core.async_agg.
    AsyncBuffer` fold speaks (its ``sum``/``mass``/``count``), so the
    bulk round, the async server, and the tier partial uplinks all
    aggregate in one algebra. Built per block by
    :func:`fedml_tpu.algorithms.fedavg.fold_block_partials` and
    finalized by ``server_update_from_partials``."""

    delta_wsum: Pytree  # Σ n_r · (clipped[, /tau_r]) delta_r, f32 leaves
    other_wsum: dict  # Σ n_r · non-param collections (batch_stats)
    n_sum: jax.Array  # Σ n_r (the mass)
    tau_wsum: jax.Array  # Σ n_r · tau_r (fednova; 0 otherwise)
    msums: dict  # additive metric sums (scalar leaves)
    rejected: jax.Array  # non-finite rows screened (scalar f32)


def stream_blocks(
    fold_block: Callable[..., Pytree],
    ids: jax.Array,
    live: jax.Array | None,
    block_size: int,
) -> Pytree:
    """Fold ``ids`` (``[S]`` client ids, ``S`` a multiple of
    ``block_size``) through ``fold_block(block_ids[, block_live])`` in
    fixed-size blocks, summing the returned partials through a
    ``lax.scan`` carry — the O(B + model) round body. ``live`` (``[S]``
    bool or None = all live) rides the scan as a per-block operand so a
    traced live count never retraces the program. A single-block cohort
    skips the scan entirely (no loop-carry layout copies for the
    B >= C case)."""
    n_slots = ids.shape[0]
    if n_slots % block_size != 0:
        raise ValueError(
            f"slot count {n_slots} is not a multiple of block size "
            f"{block_size}"
        )
    nb = n_slots // block_size
    ids_b = ids.reshape(nb, block_size)
    if live is None:
        fold = lambda bids, _unused: fold_block(bids, None)
        xs = (ids_b, jnp.zeros((nb,), jnp.int32))
    else:
        fold = fold_block
        xs = (ids_b, live.reshape(nb, block_size))
    if nb == 1:
        return fold(*jax.tree.map(lambda a: a[0], xs))
    shapes = jax.eval_shape(fold, *jax.tree.map(lambda a: a[0], xs))
    zero = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    def body(carry, x):
        p = fold(*x)
        return jax.tree.map(jnp.add, carry, p), None

    out, _ = jax.lax.scan(body, zero, xs)
    return out


def note_round(block_size: int, n_blocks: int, padded_slots: int,
               rounds: int = 1) -> None:
    """Host-side per-dispatch telemetry for the bulk engine
    (docs/OBSERVABILITY.md vocabulary) — called by the drivers'
    ``run_round``/``run_block``, never from inside a compiled
    program. ``rounds`` is the round count this dispatch executes (a
    fused block passes its K, so ``bulk.rounds`` stays per-ROUND like
    every fused metric — the perf.* wall/K discipline). One attribute
    check when the metrics plane is off."""
    m = telemetry.METRICS
    if not m.enabled:
        return
    m.gauge("bulk.block_size", float(block_size))
    m.gauge("bulk.blocks_per_round", float(n_blocks))
    m.gauge("bulk.padded_slots", float(padded_slots))
    m.inc("bulk.rounds", float(rounds))

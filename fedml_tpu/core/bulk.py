"""Device-resident bulk-client execution: scan-chunked streaming cohorts.

Every simulated client in the stacked round is a row of a ``[C, ...]``
operand inside one compiled program, so HBM grows linearly with cohort
size — the O(C) law ``bench.py --mem-bench`` pinned
(``peak_round_hbm_mb_c{8,64,256}``: 0.62 → 4.5 → 18.0 MB) and the
reason the 10k-client acceptance previously ran in a discrete-event
model instead of real training. This module is the FedJAX
``for_each_client`` idiom (PAPERS.md), ROADMAP item 2: stream the
sampled cohort through the device in fixed-size **blocks** of ``B``
clients. Each block runs the existing vmapped local update and is
immediately reduced to an O(model) partial —

    delta_wsum += Σ_r n_r · (clipped, tau-normalized) delta_r
    n_sum      += Σ_r n_r
    metric sums, non-param collections alike

— the same ``[weighted-delta-sum, mass, n, metric-sums]`` vocabulary
the :class:`~fedml_tpu.core.async_agg.AsyncBuffer` fold and the tier
machinery's ``[sum, n, count]`` partials already speak. The partials
fold through a ``lax.scan`` carry, so peak round memory is
**O(B + model)**, independent of C; only the final server step
(:func:`fedml_tpu.algorithms.fedavg.server_update_from_partials`)
touches model-sized state.

Contract honesty, stated like :mod:`fedml_tpu.core.elastic` states its
padding tiers:

- **Exact rules**: clip (per-row) + ``mean`` reduce and FedNova
  tau-normalized averaging decompose into partial sums exactly — bulk
  agrees with the stacked round within the reduce-reassociation ulp
  band (blockwise sums then a combine, vs one reduction over C; the
  same equality class as bucket padding / sharded psum, pinned in
  ``tests/test_bulk.py``).
- **Streamed rules**: the selection/gather defenses (``median`` /
  ``trimmed_mean`` / ``krum`` / ``multikrum`` / ``fltrust``) run as
  TWO-PASS streaming computations over this same block scan
  (:mod:`fedml_tpu.core.streamdef`): pass 1 folds an O(sketch) summary
  (coordinate moments, or seeded random projections), the selection is
  decided from the sketch, pass 2 folds the decided aggregate — the
  full ``[C, D]`` stacked-delta matrix is never materialized, and the
  accuracy contract of each sketch is stated honestly in streamdef's
  module doc (and pinned in ``tests/test_streamdef.py``).
- **Banked composition**: per-client O(C) states — the wire codec's
  error-feedback residual, the PEFT private adapter bank — live in a
  :class:`~fedml_tpu.core.statebank.ClientStateBank` keyed by CLIENT
  ID: each block gathers its sampled rows, updates them, and scatters
  them back through the scan carry (``stream_blocks(banks=...)``), so
  ``compress + bulk`` and ``personalize + bulk`` compose at O(block)
  round memory. The ``gauss`` adversary draws per-row noise keyed on
  (round, client id) (:func:`fedml_tpu.core.adversary.
  corrupt_stacked_deltas`), so it composes with the block scan too —
  bitwise-equal to the stacked path at matched seeds.

Elasticity applies to the block COUNT: the scan length is the
power-of-two bucket of ``ceil(C / B)`` blocks, the live cohort count
rides as a traced operand, and a partial final block is healed by the
existing :func:`fedml_tpu.core.elastic.mask_padded` — cohort churn
within the block bucket costs a compile-cache hit, not a recompile.

Telemetry (docs/OBSERVABILITY.md): ``bulk.block_size``,
``bulk.blocks_per_round``, ``bulk.padded_slots`` gauges and the
``bulk.rounds`` counter, written host-side at dispatch (never inside
the compiled program).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from fedml_tpu.core import telemetry
from fedml_tpu.core.elastic import bucket_for

Pytree = Any

#: reduce rules whose aggregate decomposes into streaming partial sums
#: (fednova is an ALGORITHM, not a robust_method, and composes because
#: its tau normalization is per-row before the weighted sum)
BULK_REDUCE_RULES = ("mean",)


@dataclasses.dataclass(frozen=True)
class BulkSpec:
    """Frozen description of the block-streaming mode (rides
    ``FedConfig.client_block_size``; 0 = off, the stacked ``[C, ...]``
    round stays byte-identical)."""

    block_size: int = 0

    def __post_init__(self):
        if self.block_size < 0:
            raise ValueError(
                f"client_block_size must be >= 0 (0 = stacked mode), "
                f"got {self.block_size}"
            )

    @staticmethod
    def from_fed(fed) -> "BulkSpec":
        return BulkSpec(
            block_size=getattr(fed, "client_block_size", 0) or 0
        )

    def enabled(self) -> bool:
        return self.block_size > 0


def check_bulk_compat(fed, adversary=None) -> None:
    """Validate a bulk configuration at construction (and at run.py
    parse time). The PR 14 composition walls have all fallen:

    - selection defenses stream through the two-pass sketches of
      :mod:`fedml_tpu.core.streamdef` (every
      :attr:`~fedml_tpu.core.robust.DefensePipeline.METHODS` rule);
    - ``compress`` keeps its error-feedback residual in a client-id-
      keyed :class:`~fedml_tpu.core.statebank.ClientStateBank` that
      rides the block scan carry;
    - the ``gauss`` adversary draws per-row noise keyed on (round,
      client id), bitwise-equal to the stacked path at matched seeds.

    The method name itself is validated by
    :class:`~fedml_tpu.core.robust.DefensePipeline`; what remains here
    is the fednova×defense wall (owned by
    :func:`~fedml_tpu.core.robust.check_fednova_compat`), enforced by
    the callers. The function stays as the single parse-time/
    construction seam so a future wall fails loudly in one place."""
    del fed, adversary  # everything composes — see docstring


def plan_blocks(cohort: int, block_size: int, elastic: bool) -> int:
    """Number of scan blocks for a ``cohort`` streamed in blocks of
    ``block_size``. Under ``elastic`` the count is bucketed to the next
    power of two — the compiled scan length depends only on the bucket,
    so cohort churn within it is a compile-cache hit (headroom blocks
    are fully masked)."""
    if cohort < 1:
        raise ValueError(f"cohort must be >= 1, got {cohort}")
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    nb = -(-cohort // block_size)
    return bucket_for(nb) if elastic else nb


class RoundPartials(NamedTuple):
    """The O(model) streaming-aggregation vocabulary: what one block of
    local updates reduces to, and what the whole round's scan carry
    accumulates — the same ``[weighted-delta-sum, mass, n,
    metric-sums]`` shape the :class:`~fedml_tpu.core.async_agg.
    AsyncBuffer` fold speaks (its ``sum``/``mass``/``count``), so the
    bulk round, the async server, and the tier partial uplinks all
    aggregate in one algebra. Built per block by
    :func:`fedml_tpu.algorithms.fedavg.fold_block_partials` and
    finalized by ``server_update_from_partials``."""

    delta_wsum: Pytree  # Σ n_r · (clipped[, /tau_r]) delta_r, f32 leaves
    other_wsum: dict  # Σ n_r · non-param collections (batch_stats)
    n_sum: jax.Array  # Σ n_r (the mass)
    tau_wsum: jax.Array  # Σ n_r · tau_r (fednova; 0 otherwise)
    msums: dict  # additive metric sums (scalar leaves)
    rejected: jax.Array  # non-finite rows screened (scalar f32)


def stream_blocks(
    fold_block: Callable[..., Pytree],
    ids: jax.Array,
    live: jax.Array | None,
    block_size: int,
    banks: Pytree | None = None,
    positions: bool = False,
) -> Pytree:
    """Fold ``ids`` (``[S]`` client ids, ``S`` a multiple of
    ``block_size``) through ``fold_block(block_ids[, block_live])`` in
    fixed-size blocks, summing the returned partials through a
    ``lax.scan`` carry — the O(B + model) round body. ``live`` (``[S]``
    bool or None = all live) rides the scan as a per-block operand so a
    traced live count never retraces the program. A single-block cohort
    skips the scan entirely (no loop-carry layout copies for the
    B >= C case).

    ``positions=True`` additionally passes each block's global slot
    indices (``block_pos``, the block's slice of ``arange(S)``) — the
    streaming defenses scatter per-slot sketch rows by position
    (:mod:`fedml_tpu.core.streamdef`).

    ``banks`` (a pytree — typically one or more
    :class:`~fedml_tpu.core.statebank.ClientStateBank`) threads
    client-keyed state through the scan carry with REPLACE semantics:
    ``fold_block`` takes the banks as its last argument, returns
    ``(partials, banks)``, and the partials sum while the banks flow
    through updated in place (gather/scatter per block, donation-
    friendly). The call then returns ``(partials, banks)``."""
    n_slots = ids.shape[0]
    if n_slots % block_size != 0:
        raise ValueError(
            f"slot count {n_slots} is not a multiple of block size "
            f"{block_size}"
        )
    nb = n_slots // block_size
    ids_b = ids.reshape(nb, block_size)
    xs = [ids_b]
    if live is None:
        xs.append(jnp.zeros((nb,), jnp.int32))  # placeholder operand
    else:
        xs.append(live.reshape(nb, block_size))
    if positions:
        xs.append(
            jnp.arange(n_slots, dtype=jnp.int32).reshape(nb, block_size)
        )
    xs = tuple(xs)

    def call(x, bk):
        args = list(x)
        if live is None:
            args[1] = None
        if banks is None:
            return fold_block(*args)
        return fold_block(*args, bk)

    x0 = jax.tree.map(lambda a: a[0], xs)
    if nb == 1:
        return call(x0, banks)
    if banks is None:
        shapes = jax.eval_shape(lambda x: call(x, None), x0)
        zero = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )

        def body(carry, x):
            return jax.tree.map(jnp.add, carry, call(x, None)), None

        out, _ = jax.lax.scan(body, zero, xs)
        return out
    p_shapes, _ = jax.eval_shape(call, x0, banks)
    zero = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), p_shapes)

    def body_banked(carry, x):
        psum, bk = carry
        p, bk = call(x, bk)
        return (jax.tree.map(jnp.add, psum, p), bk), None

    (out, banks), _ = jax.lax.scan(body_banked, (zero, banks), xs)
    return out, banks


def note_round(block_size: int, n_blocks: int, padded_slots: int,
               rounds: int = 1) -> None:
    """Host-side per-dispatch telemetry for the bulk engine
    (docs/OBSERVABILITY.md vocabulary) — called by the drivers'
    ``run_round``/``run_block``, never from inside a compiled
    program. ``rounds`` is the round count this dispatch executes (a
    fused block passes its K, so ``bulk.rounds`` stays per-ROUND like
    every fused metric — the perf.* wall/K discipline). One attribute
    check when the metrics plane is off."""
    m = telemetry.METRICS
    if not m.enabled:
        return
    m.gauge("bulk.block_size", float(block_size))
    m.gauge("bulk.blocks_per_round", float(n_blocks))
    m.gauge("bulk.padded_slots", float(padded_slots))
    m.inc("bulk.rounds", float(rounds))

"""MLOps status reporting + system telemetry.

Reference: ``fedml_core/mlops_logger.py:15-117`` (singleton publishing
run/client status, training metrics, and system telemetry JSON to fixed
MQTT topics ``fl_client/mlops/...`` / ``fl_server/mlops/...``) and
``fedavg_cross_silo/SysStats.py:13`` (psutil + pynvml sampling).

TPU-native shape: the logger writes the same topic->payload records to any
sink — a transport (for a live MQTT-like control plane), a JSONL file, or
an in-memory list for tests. ``SysStats`` samples psutil host metrics plus
jax device memory stats (the TPU analog of pynvml GPU telemetry).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable

TOPIC_CLIENT_STATUS = "fl_client/mlops/status"
TOPIC_SERVER_STATUS = "fl_server/mlops/status"
TOPIC_TRAINING_PROGRESS = "fl_server/mlops/training_progress_and_eval"
TOPIC_SYSTEM = "fl_client/mlops/system_performance"


class MLOpsLogger:
    """Publishes status/metric records (reference ``MLOpsLogger``; the
    singleton pattern is dropped — pass one instance around instead)."""

    def __init__(self, sink: Callable[[str, dict], None] | None = None,
                 jsonl_path: str | None = None):
        self.records: list[tuple[str, dict]] = []
        self._sink = sink
        self._jsonl = None
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._jsonl = open(jsonl_path, "a")
        self.run_id: str | None = None
        self.edge_id: int | None = None

    @classmethod
    def over_bus(cls, bus, jsonl_path: str | None = None) -> "MLOpsLogger":
        """Transport-backed status channel: publish every record onto a
        pub-sub bus under its MQTT-style topic (the reference's production
        wiring — ``MLOpsLogger`` over ``MqttS3StatusManager``,
        ``mlops_logger.py:24-29``). Any subscriber (e.g. a platform
        bridge) receives JSON payloads per topic."""
        return cls(
            sink=lambda topic, payload: bus.publish(
                topic, json.dumps(payload).encode("utf-8")
            ),
            jsonl_path=jsonl_path,
        )

    def set_context(self, run_id: str, edge_id: int = 0):
        self.run_id = run_id
        self.edge_id = edge_id

    def _publish(self, topic: str, payload: dict):
        payload = {
            **payload,
            "run_id": self.run_id,
            "edge_id": self.edge_id,
            "timestamp": time.time(),
        }
        self.records.append((topic, payload))
        if self._sink is not None:
            self._sink(topic, payload)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps({"topic": topic, **payload}) + "\n")
            self._jsonl.flush()

    # -- reference API (mlops_logger.py:31-112) ----------------------------
    def report_client_training_status(self, edge_id: int, status: str):
        self._publish(
            TOPIC_CLIENT_STATUS, {"edge_id": edge_id, "status": status}
        )

    def report_server_training_status(self, status: str):
        self._publish(TOPIC_SERVER_STATUS, {"status": status})

    def report_training_progress(self, round_idx: int, metrics: dict):
        self._publish(
            TOPIC_TRAINING_PROGRESS, {"round": round_idx, **metrics}
        )

    def report_system_metric(self, metric: dict | None = None):
        self._publish(TOPIC_SYSTEM, metric or SysStats().sample())

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()


class SysStats:
    """System telemetry sampler (reference ``SysStats.py:13``: psutil CPU /
    memory / disk / network + pynvml GPU; here the accelerator side reads
    jax device memory stats)."""

    def __init__(self):
        import psutil

        self._ps = psutil
        self._proc = psutil.Process()

    def sample(self) -> dict[str, Any]:
        ps = self._ps
        vm = ps.virtual_memory()
        disk = ps.disk_io_counters()
        net = ps.net_io_counters()
        out = {
            "cpu_utilization": ps.cpu_percent(),
            "process_cpu_threads_in_use": self._proc.num_threads(),
            "process_memory_in_use": self._proc.memory_info().rss,
            "process_memory_available": vm.available,
            "system_memory_utilization": vm.percent,
            "disk_utilization": (disk.read_bytes + disk.write_bytes)
            if disk else 0,
            "network_traffic": (net.bytes_sent + net.bytes_recv)
            if net else 0,
        }
        try:
            # ONE memory path (core/memscope.py; docs/OBSERVABILITY.md
            # "Memory & compilation"): the same reader the
            # DeviceMemoryMonitor samples — every device (not just the
            # first), documented mem.* names, and the RSS fallback on
            # backends without memory_stats (marked by mem.source)
            from fedml_tpu.core import memscope

            source, readings = memscope.read_device_memory()
            if readings:
                out["mem.source"] = source
                out["mem.bytes_in_use"] = sum(
                    r["bytes_in_use"] for r in readings
                )
                peaks = [r["peak_bytes"] for r in readings
                         if r["peak_bytes"]]
                if peaks:
                    out["mem.peak_bytes"] = max(peaks)
                caps = [r["capacity_bytes"] for r in readings
                        if r["capacity_bytes"]]
                if caps:
                    out["mem.capacity_bytes"] = sum(caps)
        except Exception:  # noqa: BLE001 — telemetry must never crash a run
            pass
        return out

"""Memory & compilation observability: per-program HBM accounting,
live device-memory monitoring, and the runtime donation audit.

The observability plane (core/perf.py, core/export.py, core/slo.py)
covers the TIME domain — device-time breakdowns, MFU, SLOs, live
OpenMetrics export — but until now the memory and compilation domain
was blind: the only memory signal in the tree was one ad-hoc
``bytes_in_use`` probe in ``core/mlops.py``, and the donation claims
the compressed/fused paths stake correctness and footprint on were
verified only in tests, never at runtime. This module is the memory
spine (docs/OBSERVABILITY.md "Memory & compilation"):

- **static per-program accounting** (:func:`note_program`): every
  compile site — :class:`~fedml_tpu.core.elastic.CompiledRoundCache`
  (the deploy server's bucket executables, the sharded aggregator),
  and the sims' round / fused-block programs via :class:`ProgramSite`
  — records ``compiled.memory_analysis()`` (temp, argument, output,
  alias, generated-code bytes) as ``mem.program.<slug>.*`` gauges
  keyed by a stable program slug ``<family>.<key parts>`` (family plus
  bucket / fuse length), and the compile wall time as a
  ``mem.compile_s.<family>`` histogram — an eviction-thrash world now
  shows SECONDS burning, not just a flat miss counter;
- **live device-memory monitoring** (:class:`DeviceMemoryMonitor`):
  ``device.memory_stats()`` sampled at round/block boundaries into
  per-device ``mem.bytes_in_use`` / ``mem.peak_bytes`` gauges with a
  run high-water mark and a used-fraction computed against the HBM
  capacity column of :data:`fedml_tpu.core.perf.PEAKS` (or the
  device's own ``bytes_limit`` when it reports one); ONE
  flight-recorder event fires at the first crossing of the headroom
  threshold (``--mem_headroom_warn``, default 0.9). Backends without
  ``memory_stats`` (the CPU backend CI runs) fall back to process RSS
  (``/proc/self/statm``), marked ``source: rss`` and measured against
  total system memory, so the same code path is exercised everywhere;
- **runtime donation audit** (:func:`audit_donation`): after the FIRST
  execution of each donating program, the donated input buffers are
  checked ``is_deleted()`` — a program whose donation silently failed
  (a 2x-footprint regression) counts ``mem.donation_misses`` and
  leaves one flight event naming the program. The test-only donation
  pins (tests/test_fuse.py) are now a standing production invariant.

Everything gates on ``telemetry.METRICS.enabled`` — the off path costs
one attribute check per sample and nothing per metric write. All
``mem.*`` gauges ride ``/metrics`` (core/export.py) unchanged, and
``/statusz`` gains a ``memory`` section (per-device live/peak/headroom,
the per-program table, donation-miss count) via a weak-registered
status source like every actor's.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from fedml_tpu.core import telemetry

#: per-program table cap: program slugs are bounded by design (elastic
#: buckets are powers of two, block lengths a small set), but a
#: misbehaving caller keying executables by something unbounded must
#: not grow every /statusz response and scrape forever — beyond the
#: cap new programs fold into one ``mem.program_overflow`` counter.
MAX_PROGRAMS = 64

#: memory_analysis() fields recorded per program (bytes each).
_ANALYSIS_FIELDS = (
    ("temp_bytes", "temp_size_in_bytes"),
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)

_LOCK = threading.Lock()
# slug -> program record (family, key, *_bytes, compile_s, donation)
_PROGRAMS: dict[str, dict[str, Any]] = {}
_STATUS_REGISTERED = False


def program_slug(family: str, key) -> str:
    """Stable dotted slug for one compiled program: the site family
    plus the cache key's parts (bucket, fuse length, ...)."""
    parts = key if isinstance(key, tuple) else (key,)
    return ".".join([str(family)] + [str(p) for p in parts])


class _MemoryStatus:
    """The ``/statusz`` ``memory`` section (one module-held instance —
    export keeps only a weakref)."""

    def status(self) -> dict[str, Any]:
        with _LOCK:
            programs = {k: dict(v) for k, v in _PROGRAMS.items()}
        m = telemetry.METRICS
        return {
            "source": MONITOR.last_source,
            "devices": MONITOR.last_readings,
            "high_water_bytes": MONITOR.high_water,
            "headroom_warn": MONITOR.headroom_warn,
            "donation_audits": m.counter("mem.donation_audits"),
            "donation_misses": m.counter("mem.donation_misses"),
            "programs": programs,
        }


_STATUS = _MemoryStatus()


def _register_status() -> None:
    """Idempotently (re-)register the statusz memory section. Called on
    every record/sample — ``export.reset_status_sources()`` (test
    isolation, telemetry shutdown) clears weak registrations behind our
    back, so a flag alone would go stale."""
    from fedml_tpu.core import export

    export.register_status_source("memory", _STATUS)


# ---------------------------------------------------------------------------
# static per-program accounting
# ---------------------------------------------------------------------------


def note_program(family: str, key, compiled,
                 compile_s: float | None = None) -> dict | None:
    """Record one freshly-compiled executable: its XLA memory analysis
    as ``mem.program.<slug>.*`` gauges and its compile wall time into
    the ``mem.compile_s.<family>`` histogram. Returns the program
    record (None while the metrics plane is off or when the backend
    cannot produce an analysis — accounting must never fail a
    compile)."""
    m = telemetry.METRICS
    if not m.enabled:
        return None
    slug = program_slug(family, key)
    rec: dict[str, Any] = {"family": family, "key": repr(key),
                           "ts": time.time()}
    try:
        ma = compiled.memory_analysis()
        if isinstance(ma, list):  # one analysis per partition
            ma = ma[0]
        for name, attr in _ANALYSIS_FIELDS:
            rec[name] = int(getattr(ma, attr, 0) or 0)
    except Exception:
        m.inc("mem.program_analysis_failures")
        for name, _ in _ANALYSIS_FIELDS:
            rec[name] = 0
        rec["analysis_failed"] = True
    if compile_s is not None:
        rec["compile_s"] = float(compile_s)
        m.observe(f"mem.compile_s.{family}", float(compile_s))
    m.inc("mem.compiles")
    with _LOCK:
        if slug not in _PROGRAMS and len(_PROGRAMS) >= MAX_PROGRAMS:
            overflow = True
        else:
            overflow = False
            _PROGRAMS[slug] = rec
    if overflow:
        m.inc("mem.program_overflow")
        return rec
    if not rec.get("analysis_failed"):
        for name, _ in _ANALYSIS_FIELDS:
            m.gauge(f"mem.program.{slug}.{name}", rec[name])
    _register_status()
    telemetry.RECORDER.record(
        "mem_program", program=slug,
        temp_mb=round(rec["temp_bytes"] / 1e6, 3),
        argument_mb=round(rec["argument_bytes"] / 1e6, 3),
        compile_s=round(compile_s, 3) if compile_s is not None else None,
    )
    return rec


def program_record(family: str, key) -> dict | None:
    """Read one recorded program's accounting (bench's ``--mem-bench``
    stage and the smoke assertions)."""
    with _LOCK:
        rec = _PROGRAMS.get(program_slug(family, key))
        return dict(rec) if rec is not None else None


def program_table() -> dict[str, dict]:
    with _LOCK:
        return {k: dict(v) for k, v in _PROGRAMS.items()}


# ---------------------------------------------------------------------------
# runtime donation audit
# ---------------------------------------------------------------------------


def audit_donation(family: str, key, donated_leaves) -> bool:
    """Verify a donating program's donated input buffers were actually
    consumed (``is_deleted``) after its first execution. A live donated
    buffer means XLA could not alias it — the program is silently
    paying the 2x footprint its donation was supposed to eliminate.
    Counts ``mem.donation_audits`` / ``mem.donation_misses`` and leaves
    ONE flight event naming the program per miss. Returns True when the
    donation held (also True for an empty leaf list — nothing was
    donated, nothing can miss)."""
    m = telemetry.METRICS
    if not m.enabled:
        return True
    leaves = [lf for lf in donated_leaves if hasattr(lf, "is_deleted")]
    m.inc("mem.donation_audits")
    alive = 0
    for lf in leaves:
        try:
            if not lf.is_deleted():
                alive += 1
        except Exception:
            pass
    slug = program_slug(family, key)
    ok = alive == 0
    with _LOCK:
        rec = _PROGRAMS.get(slug)
        if rec is not None:
            rec["donation"] = "ok" if ok else "missed"
    if not ok:
        m.inc("mem.donation_misses")
        telemetry.RECORDER.record(
            "mem_donation_miss", program=slug, live_buffers=alive,
            note="donated inputs were not deleted — XLA did not alias "
                 "them; the program pays double its claimed footprint",
        )
    _register_status()
    return ok


# ---------------------------------------------------------------------------
# ProgramSite: the sims' jit sites, AOT-compiled + accounted
# ---------------------------------------------------------------------------


class ProgramSite:
    """An instrumented ``jax.jit`` call site: executables are compiled
    ahead-of-time (``.lower().compile()`` — the exact artifacts a
    first jit call would build, byte-identical lowering) and held per
    stable program key, so every compile is TIMED (``mem.compile_s``),
    memory-ACCOUNTED (``mem.program.*``), and — when the site donates —
    donation-AUDITED on its first execution.

    Call as ``site(key, *args)``; ``key`` is the program identity
    (bucket, or ``(bucket, block_length)``) — one executable per key,
    exactly the signature-stability contract the sims already hold (a
    given sim instance's shapes vary only on the key). Exposes
    ``_cache_size`` so :func:`fedml_tpu.core.elastic.mirror_jit_cache`
    keeps feeding the ``elastic.compile_cache_*`` counters unchanged.
    ``static_argnums``/``donate_argnums`` index into ``*args`` (the
    wrapped function's own positions, key excluded)."""

    def __init__(self, fn: Callable, family: str,
                 static_argnums=(), donate_argnums=()):
        import jax

        self.family = family
        self._static = tuple(static_argnums)
        self._donate = tuple(donate_argnums)
        self._jit = jax.jit(fn, static_argnums=self._static,
                            donate_argnums=self._donate)
        self._exes: dict[Any, Any] = {}
        self._audited: set = set()
        self._lock = threading.Lock()

    def _cache_size(self) -> int:
        with self._lock:
            return len(self._exes)

    def __call__(self, key, *args):
        import jax

        with self._lock:
            exe = self._exes.get(key)
        if exe is None:
            t0 = time.perf_counter()
            exe = self._jit.lower(*args).compile()
            wall = time.perf_counter() - t0
            with self._lock:
                self._exes[key] = exe
            note_program(self.family, key, exe, compile_s=wall)
        audit = bool(self._donate) and key not in self._audited
        donated = (
            [leaf
             for i in self._donate if i < len(args)
             for leaf in jax.tree.leaves(args[i])]
            if audit else None
        )
        if self._static:
            dynamic = tuple(a for i, a in enumerate(args)
                            if i not in self._static)
        else:
            dynamic = args
        out = exe(*dynamic)
        if audit:
            self._audited.add(key)
            audit_donation(self.family, key, donated)
        return out


# ---------------------------------------------------------------------------
# live device-memory monitoring
# ---------------------------------------------------------------------------


def read_device_memory() -> tuple[str, list[dict]]:
    """Raw memory readings with NO registry interaction (shared by the
    monitor and the mlops ``SysStats`` sampler — one memory path, not
    two): ``("device", [...])`` from ``device.memory_stats()`` when the
    backend reports it, else ``("rss", [...])`` from
    ``/proc/self/statm`` against total system memory, else
    ``("none", [])``. Each reading carries ``bytes_in_use``,
    ``peak_bytes`` (None when the source has no allocator peak) and
    ``capacity_bytes`` (the device's ``bytes_limit``, the
    :data:`fedml_tpu.core.perf.PEAKS` HBM column, or total RAM)."""
    from fedml_tpu.core import perf

    readings: list[dict] = []
    try:
        import jax

        devices = jax.devices()
    except Exception:
        devices = []
    for i, d in enumerate(devices):
        fn = getattr(d, "memory_stats", None)
        stats = None
        if fn is not None:
            try:
                stats = fn()
            except Exception:
                stats = None
        if not stats or "bytes_in_use" not in stats:
            continue
        kind = getattr(d, "device_kind", "")
        cap = (
            stats.get("bytes_limit")
            or stats.get("bytes_reservable_limit")
            or perf.device_hbm_capacity(kind)
        )
        readings.append({
            "device": f"d{i}",
            "kind": kind,
            "bytes_in_use": int(stats["bytes_in_use"]),
            "peak_bytes": (
                int(stats["peak_bytes_in_use"])
                if "peak_bytes_in_use" in stats else None
            ),
            "capacity_bytes": int(cap) if cap else None,
        })
    if readings:
        return "device", readings
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        page = os.sysconf("SC_PAGE_SIZE")
        rss = rss_pages * page
        total = os.sysconf("SC_PHYS_PAGES") * page
    except (OSError, ValueError, IndexError):
        return "none", []
    return "rss", [{
        "device": "rss",
        "kind": "host_rss",
        "bytes_in_use": rss,
        "peak_bytes": None,
        "capacity_bytes": total,
    }]


class DeviceMemoryMonitor:
    """Round/block-boundary device-memory sampler.

    ``sample()`` reads every device's ``memory_stats()`` (or the RSS
    fallback) into per-device ``mem.bytes_in_use.<d>`` /
    ``mem.peak_bytes.<d>`` gauges plus the aggregates
    ``mem.bytes_in_use`` (sum), ``mem.peak_bytes`` (max),
    ``mem.high_water_bytes`` (run high-water mark of the sum),
    ``mem.used_frac`` / ``mem.headroom_frac`` (worst device against
    its HBM capacity) and ``mem.source_rss`` (1 on the fallback). The
    FIRST sample whose used fraction crosses ``headroom_warn`` leaves
    exactly one ``mem_headroom`` flight-recorder event for the run —
    an alert trigger, not a per-round log. The off path
    (``METRICS.enabled`` False) is one attribute check."""

    def __init__(self, headroom_warn: float = 0.9):
        self.headroom_warn = float(headroom_warn)
        self.high_water = 0
        self.last_source = "none"
        self.last_readings: list[dict] = []
        self._flagged = False
        self._peak_seen: dict[str, int] = {}

    def reset(self) -> None:
        self.high_water = 0
        self.last_source = "none"
        self.last_readings = []
        self._flagged = False
        self._peak_seen.clear()

    def sample(self, tag: str | None = None) -> dict | None:
        m = telemetry.METRICS
        if not m.enabled:
            return None
        source, readings = read_device_memory()
        if not readings:
            return None
        total = 0
        peak_max = 0
        worst_frac = 0.0
        resolved: list[dict] = []
        for r in readings:
            label = r["device"]
            used = r["bytes_in_use"]
            total += used
            # allocator peak when the source reports one; otherwise the
            # run-max of our own samples (the RSS path, marked as such)
            peak = r["peak_bytes"]
            if peak is None:
                peak = max(self._peak_seen.get(label, 0), used)
            self._peak_seen[label] = peak
            resolved.append(dict(r, peak_bytes=peak))
            peak_max = max(peak_max, peak)
            m.gauge_labeled("mem.bytes_in_use", label, used)
            m.gauge_labeled("mem.peak_bytes", label, peak)
            cap = r["capacity_bytes"]
            if cap:
                worst_frac = max(worst_frac, used / cap)
        self.high_water = max(self.high_water, total)
        m.gauge("mem.bytes_in_use", total)
        m.gauge("mem.peak_bytes", peak_max)
        m.gauge("mem.high_water_bytes", self.high_water)
        m.gauge("mem.source_rss", 1.0 if source == "rss" else 0.0)
        if worst_frac:
            m.gauge("mem.used_frac", worst_frac)
            m.gauge("mem.headroom_frac", max(0.0, 1.0 - worst_frac))
        summary = {
            "source": source,
            "bytes_in_use": total,
            "peak_bytes": peak_max,
            "high_water_bytes": self.high_water,
            "used_frac": worst_frac,
            "readings": resolved,
        }
        self.last_source = source
        self.last_readings = summary["readings"]
        if worst_frac >= self.headroom_warn and not self._flagged:
            self._flagged = True
            telemetry.RECORDER.record(
                "mem_headroom", source=source, tag=tag,
                used_frac=round(worst_frac, 4),
                threshold=self.headroom_warn,
                bytes_in_use=total,
                note="device memory crossed the headroom threshold — "
                     "the next bucket/cohort growth may OOM",
            )
            # the crossing is the second breach-profile trigger
            # (core/anatomy.py) — lazily, like telemetry.shutdown's
            # reset: this module must not pull anatomy in
            import sys as _sys

            _an = _sys.modules.get("fedml_tpu.core.anatomy")
            if _an is not None:
                try:
                    _an.notify_mem_headroom(
                        source=source, used_frac=round(worst_frac, 4),
                        threshold=self.headroom_warn,
                    )
                except Exception:
                    pass  # a profiler failure must not fail sampling
        _register_status()
        return summary


#: Process-global monitor — the round loops and the deploy actor
#: sample it; ``--mem_headroom_warn`` retunes its threshold.
MONITOR = DeviceMemoryMonitor()


def reset() -> None:
    """Return the module to its pristine state (test isolation; called
    by :func:`fedml_tpu.core.telemetry.shutdown`)."""
    global _PROGRAMS
    with _LOCK:
        _PROGRAMS.clear()
    MONITOR.reset()
    MONITOR.headroom_warn = 0.9

"""Core runtime: pytree math, RNG discipline, messaging, topology, robustness.

TPU-native replacement for the reference's ``fedml_core`` package.
"""

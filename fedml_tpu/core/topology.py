"""Topology managers for decentralized FL.

Re-designs the reference's topology layer
(``fedml_core/distributed/topology/{base,symmetric,asymmetric}_topology_manager.py``):
a ring + random-link ("Watts-Strogatz-like") neighbor graph with
row-normalized mixing weights. The TPU-native addition: the topology is
exported as a dense ``[N, N]`` mixing matrix so a full gossip round is one
matmul over the client axis (MXU work), instead of per-neighbor message
sends.
"""

from __future__ import annotations

import numpy as np


class SymmetricTopologyManager:
    """Undirected ring with `neighbor_num` nearest neighbors plus optional
    random extra links; row-normalized symmetric mixing weights (reference
    ``symmetric_topology_manager.py:21-52``)."""

    def __init__(self, n: int, neighbor_num: int = 2, extra_links: int = 0,
                 seed: int = 0):
        self.n = n
        self.neighbor_num = min(neighbor_num, n - 1)
        self.extra_links = extra_links
        self.seed = seed
        self.topology = self._generate()

    def _generate(self) -> np.ndarray:
        n, k = self.n, self.neighbor_num
        adj = np.eye(n, dtype=np.float64)
        for i in range(n):
            for d in range(1, k // 2 + 1):
                adj[i, (i + d) % n] = 1.0
                adj[i, (i - d) % n] = 1.0
        rng = np.random.default_rng(self.seed)
        for _ in range(self.extra_links):
            i, j = rng.integers(0, n, 2)
            if i != j:
                adj[i, j] = adj[j, i] = 1.0
        # symmetrize then row-normalize (equal weights over neighbors+self)
        adj = np.maximum(adj, adj.T)
        return adj / adj.sum(axis=1, keepdims=True)

    def get_in_neighbor_idx_list(self, node: int) -> list[int]:
        return [
            j for j in range(self.n) if self.topology[j, node] > 0 and j != node
        ]

    def get_out_neighbor_idx_list(self, node: int) -> list[int]:
        return [
            j for j in range(self.n) if self.topology[node, j] > 0 and j != node
        ]

    def get_in_neighbor_weights(self, node: int) -> np.ndarray:
        return self.topology[:, node]

    def get_out_neighbor_weights(self, node: int) -> np.ndarray:
        return self.topology[node]

    def mixing_matrix(self) -> np.ndarray:
        """Dense row-stochastic [N, N] matrix W; gossip mixing is
        ``stacked_params' = W @ stacked_params``."""
        return self.topology


class AsymmetricTopologyManager(SymmetricTopologyManager):
    """Directed variant: each node drops a random subset of out-links, so
    in/out neighborhoods differ (reference
    ``asymmetric_topology_manager.py:7``)."""

    def __init__(self, n: int, neighbor_num: int = 4, out_drop: int = 1,
                 seed: int = 0):
        self.out_drop = out_drop
        super().__init__(n, neighbor_num, 0, seed)

    def _generate(self) -> np.ndarray:
        base = super()._generate()
        rng = np.random.default_rng(self.seed + 1)
        adj = (base > 0).astype(np.float64)
        for i in range(self.n):
            outs = [j for j in range(self.n) if adj[i, j] > 0 and j != i]
            rng.shuffle(outs)
            for j in outs[: self.out_drop]:
                adj[i, j] = 0.0
        return adj / adj.sum(axis=1, keepdims=True)

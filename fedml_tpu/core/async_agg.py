"""Asynchronous (FedBuff-style) aggregation: staleness-weighted buffer.

Every aggregation path before this module is a synchronous round funneled
into one rank-0 aggregator — the ceiling for the ROADMAP north-star
("millions of users") is the server's inbox. This module removes the
round barrier itself (ROADMAP item 1): the server folds each arriving
(decompressed, screened, defense-preprocessed) client delta into a
staleness-weighted buffer tagged with the model VERSION the client
trained against, and emits a new model every K arrivals. Clients are
re-synced individually the moment their result lands, so a slow client
never blocks a fast one; its late result is folded with a reduced
staleness weight instead of being dropped.

Grounding: "Server Averaging for Federated Learning" (arxiv 2103.11619
— staleness-weighted server-side folding of whatever updates actually
arrive) and the FedBuff buffered-async scheme (buffer K arrivals, one
server step per emission). The polynomial staleness discount
``(1 + lag)^-alpha`` is the standard FedAsync/FedBuff family weighting.

The buffer is a plain pytree accumulator::

    sum   += w(lag) * n_k * delta_k        # weighted delta mass
    mass  += w(lag) * n_k                  # total weight
    count += 1                             # arrivals since last emit

and an emission hands ``sum / mass`` (one weighted-mean delta row) to
the SAME ``server_update`` body every synchronous path uses, so the
server rule (FedOpt optimizers, clip/noise postprocessing) cannot
drift between the sync and async worlds. State is checkpointable
(:meth:`AsyncBuffer.state_arrays` / :meth:`AsyncBuffer.load_arrays`)
and rides the server's :class:`~fedml_tpu.utils.checkpoint.
RoundCheckpointer` composite payload under the ``"async"`` key — a
SIGKILLed async server resumes its buffer, not just its params
(docs/FAULT_TOLERANCE.md "Async + tiered worlds").
"""

from __future__ import annotations

import dataclasses
import heapq
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

STALENESS_FNS = ("poly", "const")


@dataclasses.dataclass(frozen=True)
class AsyncConfig:
    """Knobs for the buffered-async server (rides ``FedConfig``).

    - ``buffer_k``: emit a new model every K folded arrivals; 0 (the
      default) disables the async path entirely — the synchronous
      round machinery stays byte-identical.
    - ``staleness_fn``: ``"poly"`` discounts a result that trained
      against a model ``lag`` versions old by ``(1 + lag)^-alpha``;
      ``"const"`` folds every arrival at full weight (plain FedBuff).
    - ``staleness_alpha``: the poly exponent (0.5 is the FedAsync
      default; higher forgets stale work faster).
    """

    buffer_k: int = 0
    staleness_fn: str = "poly"
    staleness_alpha: float = 0.5

    def __post_init__(self):
        if self.buffer_k < 0:
            raise ValueError(
                f"async_buffer_k must be >= 0, got {self.buffer_k}"
            )
        if self.staleness_fn not in STALENESS_FNS:
            raise ValueError(
                f"staleness_fn must be one of {STALENESS_FNS}, "
                f"got {self.staleness_fn!r}"
            )
        if self.staleness_alpha < 0:
            raise ValueError(
                f"staleness_alpha must be >= 0, "
                f"got {self.staleness_alpha}"
            )

    @staticmethod
    def from_fed(fed) -> "AsyncConfig":
        return AsyncConfig(
            buffer_k=getattr(fed, "async_buffer_k", 0),
            staleness_fn=getattr(fed, "staleness_fn", "poly"),
            staleness_alpha=getattr(fed, "staleness_alpha", 0.5),
        )

    def enabled(self) -> bool:
        return self.buffer_k > 0

    def weight(self, lag: int | float) -> float:
        """The staleness discount for a result that trained against a
        model ``lag`` versions behind the current one. ``lag`` is the
        version-lag (current emit counter minus the version tag the
        result carries); a fresh result (lag 0) always weighs 1.0."""
        lag = float(lag)
        if lag < 0:
            raise ValueError(f"version lag must be >= 0, got {lag}")
        if self.staleness_fn == "const":
            return 1.0
        return (1.0 + lag) ** (-self.staleness_alpha)


class AsyncBuffer:
    """The staleness-weighted fold buffer.

    NOT thread-safe by itself — the owning actor serializes folds under
    its own lock (results arrive on the transport's single dispatch
    thread anyway). All arithmetic is plain jax ops over the delta
    pytree, so the fold runs on whatever backend the server state lives
    on and a fold is O(model size), never O(cohort)."""

    def __init__(self, cfg: AsyncConfig, template_vars: Pytree):
        self.cfg = cfg
        self._template = template_vars
        self.sum = jax.tree.map(jnp.zeros_like, template_vars)
        self.mass = 0.0
        self.count = 0
        self.version = 0  # emit counter == the model version clients see

    # -- fold / emit -------------------------------------------------------

    def fold(self, delta: Pytree, n_k: float, lag: int) -> float:
        """Fold one screened delta (trained ``lag`` versions ago) into
        the buffer. Returns the staleness weight applied, so the caller
        can gauge it without recomputing."""
        w = self.cfg.weight(lag)
        wn = w * float(n_k)
        self.sum = jax.tree.map(
            lambda s, d: s + wn * d.astype(s.dtype), self.sum, delta
        )
        self.mass += wn
        self.count += 1
        return w

    def ready(self) -> bool:
        return self.count >= self.cfg.buffer_k > 0

    def emit(self) -> tuple[Pytree, float]:
        """Drain the buffer: returns ``(weighted-mean delta, mass)``
        and resets the accumulator. Advances ``version`` — the caller
        applies the delta through ``server_update`` and re-syncs
        clients with the new version."""
        if self.count == 0:
            raise RuntimeError("emit() on an empty async buffer")
        inv = 1.0 / self.mass
        mean_delta = jax.tree.map(lambda s: s * inv, self.sum)
        mass = self.mass
        self.sum = jax.tree.map(jnp.zeros_like, self._template)
        self.mass = 0.0
        self.count = 0
        self.version += 1
        return mean_delta, mass

    # -- checkpoint persistence (utils/checkpoint.py) ----------------------

    def state_arrays(self) -> dict:
        """Checkpoint payload: the accumulated sum tree plus the three
        scalars, all as host arrays (rides the server's composite
        checkpoint under the ``"async"`` key)."""
        return {
            "sum": jax.tree.map(np.asarray, self.sum),
            "mass": np.asarray(self.mass, np.float64),
            "count": np.asarray(self.count, np.int64),
            "version": np.asarray(self.version, np.int64),
        }

    def load_arrays(self, blob: dict) -> None:
        """Restore a SIGKILLed server's pending folds: the buffer
        resumes mid-accumulation, so the arrivals folded before the
        crash still count toward the next emission."""
        self.sum = jax.tree.map(
            lambda t, b: jnp.asarray(np.asarray(b), dtype=t.dtype),
            self._template, blob["sum"],
        )
        self.mass = float(np.asarray(blob["mass"]))
        self.count = int(np.asarray(blob["count"]))
        self.version = int(np.asarray(blob["version"]))


# ---------------------------------------------------------------------------
# open-loop world simulation (the --async-bench stage + its test pin)
# ---------------------------------------------------------------------------


def _serial_completion(arrivals: np.ndarray, t_free: float,
                       service_s: float) -> tuple[np.ndarray, float]:
    """Completion times of jobs served one-at-a-time in arrival order
    by a server free at ``t_free`` (the leaf/root aggregator model:
    folds are serialized on the aggregator's dispatch thread)."""
    out = np.empty_like(arrivals)
    for i, a in enumerate(arrivals):
        t_free = max(float(a), t_free) + service_s
        out[i] = t_free
    return out, t_free


def simulate_open_loop(
    *,
    n_clients: int = 10_000,
    n_leaves: int = 1,
    buffer_k: int = 32,
    flush_every: int | None = None,
    horizon_s: float = 20.0,
    seed: int = 0,
    fold_cost_s: float = 4e-4,
    emit_cost_s: float = 2e-3,
    mean_latency_s: float = 1.0,
    sigma: float = 0.8,
    sync: bool = False,
) -> dict:
    """Deterministic discrete-event simulation of an open-loop
    federated world: ``n_clients`` clients each cycle train->report->
    re-sync forever, with seeded lognormal per-result latencies
    (``sigma`` controls the straggler tail). Aggregators are SERIAL
    resources — a fold occupies the aggregator for ``fold_cost_s``
    (the real per-arrival cost the bench measures on the live
    AsyncBuffer code) and an emission for ``emit_cost_s``.

    Topology: clients are dealt round-robin over ``n_leaves`` leaf
    aggregators; each leaf forwards one partial upstream every
    ``flush_every`` folds (default 8 — the wire-reduction factor the
    leaf buys the root), and the root folds partials and emits every
    ``buffer_k`` partials. One emission therefore costs
    ``flush_every * buffer_k`` client arrivals in EVERY configuration,
    so emits/sec across fan-ins compares like-for-like and scales with
    the world's total fold throughput — which is the leaf tier's
    aggregate capacity once the single aggregator saturates.

    ``sync=True`` models the synchronous FedAvg baseline on the SAME
    world: a round closes only when every client's result has been
    folded (the barrier), so the round rate is pinned by the straggler
    maximum of ``n_clients`` latency draws plus the serial fold
    backlog — which is why it saturates flat as fan-in grows while
    async emit throughput keeps scaling (the acceptance shape of
    ROADMAP item 1).

    This is a MODEL of the control plane, not a wall-clock
    measurement: the aggregation costs are real (measured), the
    client latencies are a seeded synthetic population, and virtual
    time makes the result exactly reproducible — the bench records the
    scaling SHAPE (emits/sec vs fan-in), never absolute device time.
    """
    if n_clients < 1 or n_leaves < 1 or buffer_k < 1:
        raise ValueError("n_clients, n_leaves, buffer_k must be >= 1")
    rng = np.random.default_rng(seed)
    mu = math.log(mean_latency_s) - sigma * sigma / 2.0  # mean-preserving
    per_leaf = [n_clients // n_leaves + (1 if l < n_clients % n_leaves
                                         else 0)
                for l in range(n_leaves)]

    if sync:
        # round-at-a-time: all clients draw a latency, every result is
        # folded serially at its leaf, the round closes at the LAST
        # fold (the barrier), then partials hit the root and the model
        # emits. No overlap across rounds — that is the point.
        t = 0.0
        rounds = 0
        # at least 3 rounds regardless of horizon: one synchronous
        # round of a heavy-tailed 10k-client world can outlast any
        # sensible horizon by itself — which is exactly the point the
        # record makes, but a rate of 0/anything carries no shape
        while t < horizon_s or rounds < 3:
            close = t
            for c in per_leaf:
                lat = rng.lognormal(mu, sigma, size=c)
                arrivals = np.sort(t + lat)
                done, _ = _serial_completion(arrivals, t, fold_cost_s)
                close = max(close, float(done[-1]))
            # root: one partial per leaf, then the emission
            close += n_leaves * fold_cost_s + emit_cost_s
            t = close
            rounds += 1
        return {
            "mode": "sync",
            "n_clients": n_clients,
            "n_leaves": n_leaves,
            "rounds": rounds,
            "sim_wall_s": round(t, 6),
            "rounds_per_sec": rounds / t,
        }

    flush = flush_every if flush_every is not None else 8
    if flush < 1:
        raise ValueError(f"flush_every must be >= 1, got {flush}")
    # event heap of (result_ready_time, seq, client_id); each client's
    # next cycle is scheduled when its previous fold completes (the
    # immediate individual re-sync — open loop, no barrier)
    heap: list[tuple[float, int, int]] = []
    seq = 0
    for cid in range(n_clients):
        heapq.heappush(
            heap, (float(rng.lognormal(mu, sigma)), seq, cid)
        )
        seq += 1
    leaf_free = [0.0] * n_leaves
    leaf_folds = [0] * n_leaves
    root_free = 0.0
    partials = 0
    partials_arrived = 0
    emits = 0
    folds = 0
    last_emit_t = 0.0
    while heap:
        t, _, cid = heapq.heappop(heap)
        if t >= horizon_s:
            continue  # drain without scheduling successors
        leaf = cid % n_leaves
        start = max(t, leaf_free[leaf])
        done = start + fold_cost_s
        leaf_free[leaf] = done
        leaf_folds[leaf] += 1
        # only work that COMPLETES inside the horizon counts: a
        # saturated aggregator's backlog drains long after the window
        # and crediting it would overstate the steady-state rate
        if done <= horizon_s:
            folds += 1
        if leaf_folds[leaf] % flush == 0:
            # one partial frame upstream per flush; the root is its
            # own serial resource
            r_start = max(done, root_free)
            root_free = r_start + fold_cost_s
            partials_arrived += 1
            if root_free <= horizon_s:
                partials += 1
            if partials_arrived % buffer_k == 0:
                root_free += emit_cost_s
                if root_free <= horizon_s:
                    emits += 1
                    last_emit_t = root_free
        # the client re-syncs the moment its fold lands and starts the
        # next local update — nobody waits for anybody
        heapq.heappush(
            heap,
            (done + float(rng.lognormal(mu, sigma)), seq, cid),
        )
        seq += 1
    return {
        "mode": "async",
        "n_clients": n_clients,
        "n_leaves": n_leaves,
        "buffer_k": buffer_k,
        "flush_every": flush,
        "folds": folds,
        "partials": partials,
        "emits": emits,
        "emits_per_sec": emits / horizon_s,
        "folds_per_sec": folds / horizon_s,
        "last_emit_t": round(last_emit_t, 6),
    }

"""Delta compression for the client->server weight-update wire.

Every client<->server exchange used to ship the model delta as a dense
float pytree through the pickle/tensor-frame codec. This module shrinks
the RESULT payload — the per-round ``C x model`` term that dominates
wire bytes at scale (the sync broadcast stays dense: the server has no
residual channel to a client, and a lossy global model would corrupt
every client's starting point):

- **int8 quantization** (``int8``): per-leaf absmax scale, values
  rounded to [-127, 127] — 4x fewer bytes than f32, optionally with
  seeded *stochastic* rounding so the quantizer is unbiased
  (``E[Q(x)] = x``), the standard pairing with error feedback.
- **top-k sparsification** (``topk``): per-leaf, keep the ``k =
  max(1, topk_frac * size)`` largest-magnitude entries as (int32 index,
  f32 value) pairs — ~``8/4 * topk_frac`` of the dense bytes.
- **both** (``topk_int8``): sparsify, then int8-quantize the survivors
  — ~``5/4 * topk_frac`` of dense (the ratio the >=4x acceptance bar
  rides on at the default ``topk_frac``).

**Error feedback** (Seide et al. 2014 / Karimireddy et al. 2019): the
client carries the compression residual ``r_t = (d_t + r_{t-1}) -
deQ(Q(d_t + r_{t-1}))`` across rounds and folds it into the next delta
before compressing. The transmitted sequence then telescopes —
``sum_t transmitted_t = sum_t d_t - r_T`` exactly — so compression
error is bounded carry, not accumulating bias (pinned in
``tests/test_compress.py``).

The codec is pure jax end to end, so the SAME arithmetic runs in three
places without drift:

- the deploy client compresses its delta before the send and the
  server decompresses the stacked payloads inside a compiled (and
  optionally mesh-sharded) program;
- the in-process sim round applies ``roundtrip_stacked`` — compress
  then decompress, fused by XLA — inside the jitted round, so the sim
  measures the exact arithmetic the wire would see;
- padded rows of an elastic bucket (:mod:`fedml_tpu.core.elastic`)
  are all-zero payloads that decompress to a delta of exactly zero —
  compression composes with bucket padding by construction.

``method="none"`` is the default and leaves every path byte-identical
to the dense codec: no payload key is added, no jit operand changes,
no residual is allocated.

Telemetry (docs/OBSERVABILITY.md): ``compress.ratio`` (dense/wire
bytes, analytic), ``compress.residual_norm`` (client-side carry),
``compress.decode_errors`` (malformed/mismatched payloads the server
dropped).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

METHODS = ("none", "int8", "topk", "topk_int8")

#: fold_in salt separating the quantizer's rng stream from every other
#: consumer of the round key
_KEY_SALT = 0x43505253  # "CPRS"


@dataclasses.dataclass(frozen=True)
class CompressionSpec:
    """Frozen, seeded description of the wire codec — hashable, so it
    can ride jit closures, and shared verbatim by the client (compress)
    and server (decompress) ends of the wire."""

    method: str = "none"
    #: fraction of each leaf's entries the topk family keeps (>= 1 entry)
    topk_frac: float = 0.01
    #: seeded stochastic rounding for the int8 family (unbiased
    #: quantizer; False = deterministic round-to-nearest)
    stochastic: bool = True
    #: carry the compression residual across rounds (see module doc)
    error_feedback: bool = True
    seed: int = 0

    def __post_init__(self):
        if self.method not in METHODS:
            raise ValueError(
                f"compress method must be one of {METHODS}, "
                f"got {self.method!r}"
            )
        if not (0.0 < self.topk_frac <= 1.0):
            raise ValueError(
                f"compress_topk_frac must be in (0, 1], "
                f"got {self.topk_frac}"
            )

    def enabled(self) -> bool:
        return self.method != "none"

    @staticmethod
    def from_fed(fed, seed: int = 0) -> "CompressionSpec":
        """Build from :class:`~fedml_tpu.config.FedConfig` compress_*
        fields (the single CLI/config surface; ``seed`` is the
        experiment seed, so the stochastic-rounding stream is as
        reproducible as every other draw)."""
        return CompressionSpec(
            method=fed.compress or "none",
            topk_frac=fed.compress_topk_frac,
            seed=seed,
        )

    def leaf_k(self, size: int) -> int:
        """Top-k keep count for a leaf of ``size`` entries."""
        return min(max(1, int(size * self.topk_frac)), size)


# ---------------------------------------------------------------------------
# per-leaf codec (single client; vmap over the client axis for stacks)
# ---------------------------------------------------------------------------


def _round(y: jax.Array, key: jax.Array | None) -> jax.Array:
    """Round-to-nearest, or seeded stochastic rounding when a key is
    given: ``floor(y + u)`` with ``u ~ U[0, 1)`` has ``E = y`` — the
    quantizer itself is unbiased, independent of error feedback."""
    if key is None:
        return jnp.round(y)
    return jnp.floor(y + jax.random.uniform(key, y.shape, y.dtype))


def _quant_int8(x: jax.Array, key: jax.Array | None):
    """``(q int8, scale f32)`` with per-tensor absmax scaling. An
    all-zero tensor gets scale 0 and dequantizes to exact zeros."""
    x = x.astype(jnp.float32)
    a = jnp.max(jnp.abs(x)) if x.size else jnp.zeros((), jnp.float32)
    scale = a / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(_round(x / safe, key), -127, 127).astype(jnp.int8)
    return q, scale


def compress_leaf(spec: CompressionSpec, x: jax.Array,
                  key: jax.Array | None) -> dict[str, jax.Array]:
    """One leaf -> its typed wire payload (a small dict of arrays; the
    bulk parts ride the native tensor-frame codec like any array)."""
    if x.size == 0:
        # degenerate leaf: nothing to compress, nothing to index
        return {"dense": x}
    if spec.method == "int8":
        q, scale = _quant_int8(x, key)
        return {"q": q, "scale": scale}
    flat = jnp.ravel(x).astype(jnp.float32)
    k = spec.leaf_k(flat.size)
    # top-k by magnitude; lax.top_k's deterministic tie-break (lowest
    # index wins) keeps the payload seeded-reproducible
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    idx = idx.astype(jnp.int32)
    vals = flat[idx]
    if spec.method == "topk":
        return {"idx": idx, "vals": vals}
    q, scale = _quant_int8(vals, key)  # topk_int8
    return {"idx": idx, "q": q, "scale": scale}


def decompress_leaf(spec: CompressionSpec, payload: dict,
                    like: jax.Array) -> jax.Array:
    """Inverse of :meth:`compress_leaf`, shaped/typed by ``like``."""
    if "dense" in payload:
        return payload["dense"].astype(like.dtype)
    if spec.method == "int8":
        out = payload["q"].astype(jnp.float32) * payload["scale"]
        return out.reshape(like.shape).astype(like.dtype)
    vals = (
        payload["vals"]
        if spec.method == "topk"
        else payload["q"].astype(jnp.float32) * payload["scale"]
    )
    flat = jnp.zeros((like.size,), jnp.float32).at[payload["idx"]].set(
        vals
    )
    return flat.reshape(like.shape).astype(like.dtype)


# ---------------------------------------------------------------------------
# pytree codec
# ---------------------------------------------------------------------------


def _leaf_keys(tree: Pytree, key: jax.Array | None):
    leaves, treedef = jax.tree.flatten(tree)
    if key is None:
        return leaves, treedef, [None] * len(leaves)
    return leaves, treedef, list(jax.random.split(key, len(leaves)))


def compress_tree(spec: CompressionSpec, delta: Pytree,
                  key: jax.Array | None) -> Pytree:
    """Delta pytree -> payload pytree (each leaf becomes its payload
    dict; structure otherwise preserved, so the payload pickles/stacks
    like any pytree)."""
    leaves, treedef, keys = _leaf_keys(
        delta, key if spec.stochastic else None
    )
    return jax.tree.unflatten(
        treedef,
        [compress_leaf(spec, l, k) for l, k in zip(leaves, keys)],
    )


def decompress_tree(spec: CompressionSpec, payload: Pytree,
                    template: Pytree) -> Pytree:
    """Payload pytree -> delta pytree shaped like ``template``."""
    t_leaves, treedef = jax.tree.flatten(template)
    p_leaves = treedef.flatten_up_to(payload)
    return jax.tree.unflatten(
        treedef,
        [decompress_leaf(spec, p, t)
         for p, t in zip(p_leaves, t_leaves)],
    )


def roundtrip_tree(spec: CompressionSpec, delta: Pytree,
                   key: jax.Array | None) -> Pytree:
    """``decompress(compress(delta))`` — the exact wire arithmetic,
    fused by XLA when traced (no payload materializes)."""
    return decompress_tree(spec, compress_tree(spec, delta, key), delta)


def apply_with_feedback(
    spec: CompressionSpec, delta: Pytree, residual: Pytree | None,
    key: jax.Array | None,
) -> tuple[Pytree, Pytree, Pytree]:
    """One client-side step of the compressed update: fold the carried
    residual into the delta, compress, and compute the new residual.
    Returns ``(payload, decompressed delta, new residual)`` — the
    decompressed delta is what the server will aggregate, so callers
    that only need the roundtrip (the sim) discard the payload and XLA
    never materializes it."""
    if residual is not None:
        delta = jax.tree.map(
            lambda d, r: d + r.astype(d.dtype), delta, residual
        )
    payload = compress_tree(spec, delta, key)
    deq = decompress_tree(spec, payload, delta)
    if spec.error_feedback:
        # a non-finite delta (lr spike, bad batch) yields a non-finite
        # payload the server's screen DROPS for this round — exactly
        # the dense path's behavior. The carry must not memorize the
        # poison: ``delta - deq`` would be NaN forever after, turning
        # one bad round into permanent exclusion. Reset the whole
        # carry for a non-finite round instead; the client recovers
        # next round like its dense twin (pinned in
        # tests/test_compress.py).
        ok = jnp.asarray(True)
        for x in jax.tree.leaves(delta):
            if jnp.issubdtype(x.dtype, jnp.floating):
                ok = ok & jnp.all(jnp.isfinite(x))
        new_residual = jax.tree.map(
            lambda d, q: jnp.where(ok, d - q, jnp.zeros((), d.dtype)),
            delta, deq,
        )
    else:
        new_residual = jax.tree.map(jnp.zeros_like, delta)
    return payload, deq, new_residual


# ---------------------------------------------------------------------------
# stacked [C, ...] forms (the server / sim sides)
# ---------------------------------------------------------------------------


def slot_key(spec: CompressionSpec, rkey: jax.Array,
             slot) -> jax.Array:
    """One slot's quantizer key for one round, folded off the round
    key under the codec's own salt (deterministic; disjoint from the
    sampling/noise streams). The deploy client calls it with its
    cohort slot (``rank - 1``); the sim vmaps it over the bucket."""
    base = jax.random.fold_in(
        jax.random.fold_in(rkey, _KEY_SALT), spec.seed
    )
    return jax.random.fold_in(base, slot)


def round_keys(spec: CompressionSpec, rkey: jax.Array,
               n: int) -> jax.Array:
    """Per-slot quantizer keys for one round (:func:`slot_key` over
    the bucket)."""
    return jax.vmap(lambda i: slot_key(spec, rkey, i))(jnp.arange(n))


def roundtrip_stacked(
    spec: CompressionSpec, stacked_delta: Pytree,
    residual: Pytree | None, rkey: jax.Array,
) -> tuple[Pytree, Pytree]:
    """The sim-side wire model: per-slot compress->decompress with
    error feedback, vmapped over the client axis inside the compiled
    round. Returns ``(decompressed stacked delta, new stacked
    residual)`` — the same arithmetic the deploy path's per-client
    sends see, at stacked layout."""
    n = jax.tree.leaves(stacked_delta)[0].shape[0]
    keys = round_keys(spec, rkey, n)

    def one(delta, res, key):
        _, deq, new_res = apply_with_feedback(spec, delta, res, key)
        return deq, new_res

    if residual is None:
        return jax.vmap(lambda d, k: one(d, None, k))(
            stacked_delta, keys
        )
    return jax.vmap(one)(stacked_delta, residual, keys)


def roundtrip_rows(
    spec: CompressionSpec, stacked_delta: Pytree, residual_rows: Pytree,
    rkey: jax.Array, ids: jax.Array,
) -> tuple[Pytree, Pytree]:
    """:func:`roundtrip_stacked` with the quantizer keyed by CLIENT ID
    instead of cohort slot — the bulk engine's form, where the
    error-feedback residual lives in a client-id-keyed
    :class:`~fedml_tpu.core.statebank.ClientStateBank` and each block's
    gathered rows roundtrip against their own ids. The keying (and so
    the stochastic rounding stream) deliberately differs from the
    stacked path's slot keying: a client's quantizer noise follows the
    client across rounds, not the slot it happened to land in —
    trajectories are compared by convergence/telescoping pins, not
    bitwise (``tests/test_statebank.py``)."""
    keys = jax.vmap(lambda i: slot_key(spec, rkey, i))(ids)

    def one(delta, res, key):
        _, deq, new_res = apply_with_feedback(spec, delta, res, key)
        return deq, new_res

    return jax.vmap(one)(stacked_delta, residual_rows, keys)


def decompress_stacked(spec: CompressionSpec, stacked_payload: Pytree,
                       template: Pytree) -> Pytree:
    """Server side: stacked payload tree (leaves ``[C, ...]``) ->
    stacked dense delta ``[C, ...]`` shaped like ``template`` (the
    global variables). Pure jax — runs inside the compiled (and
    optionally client-axis-sharded) aggregation program."""
    return jax.vmap(
        lambda p: decompress_tree(spec, p, template)
    )(stacked_payload)


def zero_residual(template: Pytree, n: int) -> Pytree:
    """Fresh ``[n, ...]`` error-feedback carry for ``n`` slots."""
    return jax.tree.map(
        lambda g: jnp.zeros((n,) + np.shape(g), g.dtype), template
    )


def pad_stacked_payload(stacked_payload: Pytree, bucket: int) -> Pytree:
    """Pad every payload leaf to ``bucket`` rows with zeros. A zero
    payload row (indices 0, values 0, scale 0) decompresses to a delta
    of exactly zero — the healed-row convention of
    :func:`fedml_tpu.core.elastic.pad_stacked`, so bucket padding and
    compression compose."""

    def leaf(x):
        x = jnp.asarray(x)
        c = x.shape[0]
        if c > bucket:
            raise ValueError(f"cohort {c} does not fit bucket {bucket}")
        if c == bucket:
            return x
        pad = jnp.zeros((bucket - c,) + x.shape[1:], x.dtype)
        return jnp.concatenate([x, pad], axis=0)

    return jax.tree.map(leaf, stacked_payload)


# ---------------------------------------------------------------------------
# host-side wire accounting + validation (the server's receive edge)
# ---------------------------------------------------------------------------


def _leaf_dtype(leaf) -> np.dtype:
    """Leaf dtype without materializing device arrays host-side."""
    dt = getattr(leaf, "dtype", None)
    return np.dtype(dt) if dt is not None else np.asarray(leaf).dtype


def _leaf_payload_bytes(spec: CompressionSpec, leaf) -> int:
    size = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
    if size == 0 or not spec.enabled():
        return size * _leaf_dtype(leaf).itemsize
    if spec.method == "int8":
        return size * 1 + 4
    k = spec.leaf_k(size)
    if spec.method == "topk":
        return k * (4 + 4)
    return k * (4 + 1) + 4  # topk_int8


def wire_ratio(spec: CompressionSpec, template: Pytree) -> float:
    """Analytic dense/compressed byte ratio for a variables tree —
    the ``compress.ratio`` gauge (payload tensors only; envelope
    overhead is shared by both paths and excluded)."""
    leaves = jax.tree.leaves(template)
    dense = sum(
        int(np.prod(np.shape(l))) * _leaf_dtype(l).itemsize
        for l in leaves
    )
    compressed = sum(_leaf_payload_bytes(spec, l) for l in leaves)
    return dense / max(1, compressed)


@dataclasses.dataclass(frozen=True)
class _LeafSpec:
    """Expected payload parts for one leaf: ``{part: (shape, dtype)}``
    plus the dense extent top-k indices scatter into. A distinct class
    (not a bare dict) so template flattening can tell a payload leaf
    from the variables tree's own dict structure."""

    parts: dict
    dense_size: int | None = None


def payload_template(spec: CompressionSpec, variables: Pytree) -> Pytree:
    """The expected payload structure for a variables tree: leaf ->
    :class:`_LeafSpec` — what :func:`validate_payload` checks inbound
    results against."""

    def leaf(g):
        size = int(np.prod(np.shape(g)))
        if size == 0:
            return _LeafSpec({"dense": (np.shape(g), _leaf_dtype(g))})
        if spec.method == "int8":
            return _LeafSpec({
                "q": (np.shape(g), np.dtype(np.int8)),
                "scale": ((), np.dtype(np.float32)),
            })
        k = spec.leaf_k(size)
        parts = {"idx": ((k,), np.dtype(np.int32))}
        if spec.method == "topk":
            parts["vals"] = ((k,), np.dtype(np.float32))
        else:
            parts["q"] = ((k,), np.dtype(np.int8))
            parts["scale"] = ((), np.dtype(np.float32))
        return _LeafSpec(parts, dense_size=size)

    return jax.tree.map(leaf, variables)


def validate_payload(template: Pytree, payload: Pytree) -> str | None:
    """Structural + finiteness screen for one inbound compressed
    result, host-side at the receive edge (the compressed twin of the
    dense path's ``_result_is_finite``). Returns a diagnostic string
    for a payload that must be DROPPED (counted
    ``compress.decode_errors``), else None.

    Checks: tree structure matches the spec's expected payload shape,
    every part has the expected shape/dtype, float parts are finite,
    and top-k indices are in range (an out-of-range index would make
    the compiled scatter silently drop updates)."""
    t_leaves, treedef = jax.tree.flatten(
        template, is_leaf=lambda x: isinstance(x, _LeafSpec)
    )
    try:
        p_leaves = treedef.flatten_up_to(payload)
    except (ValueError, TypeError) as err:
        return f"payload tree mismatch: {err}"
    for t, p in zip(t_leaves, p_leaves):
        dense_size = t.dense_size
        expected = t.parts
        if not isinstance(p, dict) or set(p) != set(expected):
            got = sorted(p) if isinstance(p, dict) else type(p).__name__
            return f"payload keys {got} != expected {sorted(expected)}"
        for name, (shape, dtype) in expected.items():
            arr = np.asarray(p[name])
            if tuple(arr.shape) != tuple(shape):
                return (
                    f"part {name!r} shape {arr.shape} != {tuple(shape)}"
                )
            if arr.dtype != dtype:
                return f"part {name!r} dtype {arr.dtype} != {dtype}"
            if np.issubdtype(arr.dtype, np.floating) and not np.all(
                np.isfinite(arr)
            ):
                return f"part {name!r} carries non-finite values"
        if "idx" in expected and dense_size is not None:
            idx = np.asarray(p["idx"])
            if idx.size and (
                idx.min() < 0 or idx.max() >= dense_size
            ):
                # an out-of-range index would make the compiled
                # scatter silently drop (or alias) updates
                return (
                    f"idx out of range for dense size {dense_size}"
                )
        if "scale" in expected:
            # the DEQUANTIZED values must stay finite too: a finite
            # scale near f32 max overflows q * scale to inf, and the
            # norm-clip then turns inf * 0 into NaN inside the
            # aggregate — the exact single-result poisoning the dense
            # path's receive screen rejects. Scales are absmax/127 by
            # construction, so negative is equally malformed.
            s = np.float32(np.asarray(p["scale"]))
            with np.errstate(over="ignore"):
                # the product must be taken in f32 — in python floats
                # 3e38 * 127 is still finite and the overflow hides
                biggest = s * np.float32(127.0)
            if s < 0.0 or not np.isfinite(biggest):
                return (
                    f"scale {float(s)!r} dequantizes out of f32 range"
                )
    return None

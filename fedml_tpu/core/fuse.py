"""Round fusion: block planning + pipelined host metric consumption.

The headline MFU problem (ROADMAP item 5, docs/PERFORMANCE.md "Round
fusion") is a host-round-trip problem: the per-round loop dispatches one
compiled round, then immediately blocks converting that round's metric
leaves to host floats before it may dispatch the next — the device idles
for the whole host turnaround, every round. Fusion attacks both halves:

- **fewer dispatches**: with ``FedConfig.fuse_rounds = K`` the sims run
  K complete rounds as ONE compiled program (``lax.scan`` over the round
  body — see ``FedAvgSim._fused_block``), so the per-round host
  turnaround is paid once per block;
- **pipelined consumption**: the round loop keeps block k+1's dispatch
  in flight while the host converts block k's stacked metrics
  (:class:`BlockPipeline` — ONE batched ``jax.device_get`` per block
  instead of one transfer per metric leaf per round), blocking only at
  eval / checkpoint / profiler-capture boundaries.

This module owns the driver-side machinery shared by the two round-loop
drivers (``FedAvgSim.run`` and the experiment harness — the same
mutually-exclusive-drivers pairing that shares ``perf.build_sim_perf``):
:func:`plan_blocks` cuts the round range into blocks that never cross an
eval/checkpoint boundary (so ``eval_every % K != 0`` flushes correctly —
the block shortens to end exactly on the boundary round),
:class:`BlockPipeline` holds the one in-flight block's device metrics,
and :func:`drive` is the loop itself, parameterized by the per-driver
hooks (record shaping, logging, the eval/checkpoint boundary action) so
the two drivers cannot drift.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Iterable, Iterator

import numpy as np


def plan_blocks(
    start: int,
    total: int,
    fuse: int,
    eval_every: int,
    checkpoint_every: int = 0,
) -> Iterator[tuple[int, int, bool]]:
    """Cut rounds ``[start, total)`` into fused blocks of at most
    ``fuse`` rounds, never crossing a boundary round. Yields
    ``(block_start, length, boundary)`` where ``boundary`` is True when
    the block's LAST round is an eval round (``(r+1) % eval_every ==
    0``), a checkpoint round, or the final round — the driver must
    flush the metric pipeline and sync there (the state it evaluates /
    checkpoints is exactly the boundary round's, same as the unfused
    loop). With ``fuse == 1`` every round is its own block, which is
    the unfused schedule."""
    if fuse < 1:
        raise ValueError(f"fuse must be >= 1, got {fuse}")

    def is_boundary(r: int) -> bool:
        return (
            (r + 1) % eval_every == 0
            or (checkpoint_every > 0 and (r + 1) % checkpoint_every == 0)
            or r == total - 1
        )

    r = start
    while r < total:
        n = 0
        while n < fuse and r + n < total:
            n += 1
            if is_boundary(r + n - 1):
                break
        yield r, n, is_boundary(r + n - 1)
        r += n


class BlockPipeline:
    """One-deep pipeline of a fused block's device-resident metrics.

    ``push`` stores the just-dispatched block's stacked metrics and
    returns the PREVIOUS block, flushed — since dispatch is async, the
    previous block's ``device_get`` (and the host-side row conversion
    the caller does with it) overlaps the current block's device
    execution. ``flush`` drains the pending block synchronously (eval /
    checkpoint / profiler boundaries, end of run).

    Flushed blocks come back as ``(start, length, rows, wall_s,
    compiled, get_wait_s)``: ``rows`` is one host dict per round (sliced
    out of the ``[K, ...]`` stacked leaves — one batched transfer for
    the whole block), ``wall_s`` spans dispatch -> metrics-on-host, i.e.
    the block's execution in the steady state (the next block was
    already enqueued when the flush started waiting), ``compiled``
    echoes the flag the dispatcher pushed (True when this dispatch
    traced a fresh block program — its wall is compile-dominated and
    must stay out of the per-round SLO surface), and ``get_wait_s`` is
    the seconds the ``device_get`` blocked — the anatomy plane's
    ``local`` attribution (core/anatomy.py), timed at a sync the
    pipeline already pays."""

    def __init__(self) -> None:
        self._pending: tuple[int, int, Any, float, bool] | None = None

    def push(
        self, start: int, length: int, device_metrics: Any, t0: float,
        compiled: bool = False,
    ) -> tuple[int, int, list[dict], float, bool, float] | None:
        prev = self.flush()
        self._pending = (start, length, device_metrics, t0, compiled)
        return prev

    def flush(
        self,
    ) -> tuple[int, int, list[dict], float, bool, float] | None:
        if self._pending is None:
            return None
        import jax

        start, n, dm, t0, compiled = self._pending
        self._pending = None
        t_get = time.perf_counter()
        host = jax.device_get(dm)  # one batched D2H for the block
        wall = time.perf_counter() - t0
        get_wait = time.perf_counter() - t_get
        rows = [
            {k: np.asarray(v)[i] for k, v in host.items()}
            for i in range(n)
        ]
        return start, n, rows, wall, compiled, get_wait


def drive(
    run_block: Callable[[int], Any],
    blocks: Iterable[tuple[int, int, bool]],
    *,
    profiler=None,
    monitor=None,
    make_records: Callable[[int, list[dict]], list[dict]],
    log: Callable[[dict], None],
    boundary_hook: Callable[[int, dict], None],
    span: Callable[[int, int], Any] | None = None,
) -> None:
    """The fused round loop, shared by ``FedAvgSim._run_fused`` and the
    harness ``Experiment._fused_loop`` so the two drivers cannot drift.

    - ``run_block(length)`` dispatches one block and returns its
      device-resident stacked metrics (the caller owns the state);
    - ``blocks`` is a :func:`plan_blocks` schedule;
    - ``make_records(start, rows)`` shapes one host row per round into
      the driver's record dicts (consuming device counters);
    - ``log(record)`` emits a finished record;
    - ``boundary_hook(r_last, last_record)`` runs at every boundary
      block with the held last record — the driver evaluates /
      checkpoints there and must log ``last_record`` itself;
    - ``span(start, length)`` optionally wraps each dispatch in a
      context manager (tracer spans).

    Pipelining: block k+1's dispatch goes out before block k's metrics
    are fetched, so the host-side conversion overlaps device execution;
    the pipeline drains at boundaries and around profiler captures.
    The FIRST dispatch of each distinct block length traces a fresh
    scan program — that block's wall is compile-dominated, so it is
    flagged to :meth:`PerfMonitor.note_block` as ``compiled`` and
    excluded from the per-round SLO surface like the warmup round
    (otherwise the remainder lengths an eval/checkpoint cadence forces
    would put an XLA compile into the p99)."""
    from fedml_tpu.core.anatomy import ANATOMY

    pipeline = BlockPipeline()
    seen_lengths: set[int] = set()

    def emit(flushed, hold_last=False):
        start, blen, rows, wall, compiled, get_wait = flushed
        if monitor is not None:
            monitor.note_block(wall, blen, compiled=compiled)
        if ANATOMY.enabled:
            # one anatomy entry per fused block: `local` is the
            # device_get wait the flush already paid (remaining device
            # execution in the steady state); dispatch + host row
            # conversion land in host_gap. The driver's boundary hook
            # amends eval/checkpoint onto this entry afterwards.
            ANATOMY.begin_round(start, path="fused", rounds=blen)
            ANATOMY.phase("local", get_wait)
            ANATOMY.end_round(wall_s=wall)
        records = make_records(start, rows)
        last = records.pop() if hold_last else None
        for rec in records:
            log(rec)
        return last

    for bstart, blen, boundary in blocks:
        capturing = profiler is not None and profiler.wants_capture
        if capturing:
            # a capture window must contain exactly this block's
            # device work: drain the pipeline first
            prev = pipeline.flush()
            if prev:
                emit(prev)
            profiler.start_round(bstart)
        compiled = blen not in seen_lengths
        seen_lengths.add(blen)
        t0 = time.perf_counter()
        cm = (span(bstart, blen) if span is not None
              else contextlib.nullcontext())
        with cm:
            dm = run_block(blen)
        prev = pipeline.push(bstart, blen, dm, t0, compiled)
        if prev:
            emit(prev)
        if boundary or capturing:
            last = emit(pipeline.flush(), hold_last=boundary)
            if capturing:
                profiler.end_round(bstart, rounds=blen)
            if boundary:
                boundary_hook(bstart + blen - 1, last)
    final = pipeline.flush()
    if final:
        emit(final)

"""Byzantine adversary injection: seeded, deterministic malicious deltas.

The fault layer (:mod:`fedml_tpu.core.transport.chaos`) models *benign*
unreliability — drops, delays, crashes. This module models *malice*: a
selected subset of clients emits corrupted model deltas instead of the
honest local-update result. Following FedJAX's simulation-fidelity
argument (arxiv 2108.02117), attacks are pure, seeded functions over
client deltas, so an adversarial round is exactly reproducible given
``(policy, round)`` — on the compiled simulator (vectorized over the
stacked ``[C, ...]`` cohort) and on the deployment path (each malicious
rank corrupts its own delta before sending) alike.

Attack modes (``d`` = the honest delta ``new_params - global_params``):

- **sign_flip**   — ``d' = -scale * d`` (gradient-ascent steering).
- **scale_boost** — ``d' = scale * d`` (honest direction, boosted to
  dominate the weighted mean).
- **gauss**       — ``d' = d + noise_stddev * N(0, 1)`` (stochastic
  poisoning / unusable-update attack).
- **zero**        — ``d' = 0`` (free-riding: claims sample mass while
  contributing nothing).
- **constant**    — ``d' = scale * ones`` (coordinate-bias attack).
- **collude**     — every adversary emits the SAME pseudo-delta, drawn
  from the policy seed folded with the round (global L2 norm
  ``scale``). Colluders are indistinguishable from each other by
  construction — the attack that defeats distance-based selection
  (Krum picks the tight colluding cluster) and is instead caught by
  the near-duplicate anomaly signal (:func:`fedml_tpu.core.robust.
  anomaly_scores`).

Reproducibility contract: the corruption stream is a deterministic
function of ``(seed, mode, round, member identity)``. The simulator and
the deployment path each replay byte-identically under a fixed seed;
the two paths are not bit-equal to each other (they stack and fold keys
differently), matching the chaos layer's per-path replayability.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core import tree as T

Pytree = Any

#: fold_in salt separating the adversary key stream from training keys
_ADV_SALT = 0x41D5

MODES = ("sign_flip", "scale_boost", "gauss", "zero", "constant",
         "collude")


@dataclasses.dataclass(frozen=True)
class AdversaryPolicy:
    """Which clients are malicious and how (sibling of
    :class:`~fedml_tpu.core.transport.chaos.FaultPolicy` — frozen and
    hashable, so it rides :class:`~fedml_tpu.config.ExperimentConfig`
    as a jit-static field).

    - ``mode``: one of :data:`MODES`, or ``"none"`` (disabled).
    - ``ranks``: explicit adversary identities — CLIENT ids on the
      simulator path, worker RANKS (>= 1) on the deployment path.
    - ``num_adversaries``: when ``ranks`` is empty, a seeded choice of
      this many members from the population.
    - ``scale``: magnitude knob (sign_flip/scale_boost multiplier,
      constant fill value, collude pseudo-delta norm).
    - ``noise_stddev``: gauss-mode perturbation stddev.
    """

    seed: int = 0
    mode: str = "none"
    ranks: tuple[int, ...] = ()
    num_adversaries: int = 0
    scale: float = 10.0
    noise_stddev: float = 1.0

    def __post_init__(self):
        if self.mode not in ("none", *MODES):
            raise ValueError(
                f"adversary mode must be one of {('none', *MODES)}, "
                f"got {self.mode!r}"
            )
        if self.num_adversaries < 0:
            raise ValueError(
                f"num_adversaries must be >= 0, "
                f"got {self.num_adversaries}"
            )

    def enabled(self) -> bool:
        return self.mode != "none" and bool(
            self.ranks or self.num_adversaries
        )

    def member_ids(self, population: int, base: int = 0) -> np.ndarray:
        """The adversarial identities among ``[base, base+population)``:
        the explicit ``ranks`` when given (validated in range), else a
        seeded without-replacement choice of ``num_adversaries``. Host-
        side and deterministic — callers close over the result as a
        constant under jit."""
        if not self.enabled():
            return np.zeros((0,), np.int32)
        if self.ranks:
            ids = np.asarray(sorted(set(self.ranks)), np.int32)
            lo, hi = base, base + population
            if ids.size and (ids[0] < lo or ids[-1] >= hi):
                raise ValueError(
                    f"adversary ranks {sorted(set(self.ranks))} outside "
                    f"the population [{lo}, {hi})"
                )
            return ids
        n = min(self.num_adversaries, population)
        rng = np.random.default_rng(self.seed)
        ids = rng.choice(population, size=n, replace=False) + base
        return np.sort(ids).astype(np.int32)

    def is_member(self, ident: int, population: int,
                  base: int = 0) -> bool:
        return bool(np.isin(ident, self.member_ids(population, base)))


def _round_key(policy: AdversaryPolicy, round_idx) -> jax.Array:
    """The shared per-round adversary key — every colluder can derive
    it independently (it depends only on the policy seed + round)."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.key(policy.seed), _ADV_SALT),
        round_idx,
    )


def _leaf_keys(key: jax.Array, tree_: Pytree) -> Pytree:
    leaves, treedef = jax.tree.flatten(tree_)
    return jax.tree.unflatten(
        treedef, list(jax.random.split(key, max(1, len(leaves))))
    )


def collusion_delta(policy: AdversaryPolicy, like: Pytree,
                    round_idx) -> Pytree:
    """The shared colluding pseudo-delta: a seeded gaussian direction
    normalized to global L2 norm ``policy.scale``, shaped like one
    client's delta (``like``). Identical for every colluder in a round
    because it derives only from ``(seed, round, shapes)``."""
    keys = _leaf_keys(_round_key(policy, round_idx), like)
    raw = jax.tree.map(
        lambda l, k: jax.random.normal(k, l.shape, jnp.float32),
        like, keys,
    )
    norm = T.tree_l2_norm(raw)
    s = jnp.asarray(policy.scale, jnp.float32) / jnp.maximum(norm, 1e-12)
    return jax.tree.map(
        lambda r, l: (r * s).astype(l.dtype), raw, like
    )


def _attack_one(policy: AdversaryPolicy, delta: Pytree, key: jax.Array,
                round_idx) -> Pytree:
    """Apply ``policy.mode`` to ONE client's delta. Pure; preserves
    leaf dtypes."""
    mode = policy.mode
    if mode == "sign_flip":
        return jax.tree.map(
            lambda d: d * jnp.asarray(-policy.scale, d.dtype), delta
        )
    if mode == "scale_boost":
        return jax.tree.map(
            lambda d: d * jnp.asarray(policy.scale, d.dtype), delta
        )
    if mode == "zero":
        return jax.tree.map(jnp.zeros_like, delta)
    if mode == "constant":
        return jax.tree.map(
            lambda d: jnp.full_like(d, policy.scale), delta
        )
    if mode == "gauss":
        keys = _leaf_keys(key, delta)
        return jax.tree.map(
            lambda d, k: d + (
                policy.noise_stddev
                * jax.random.normal(k, d.shape, jnp.float32)
            ).astype(d.dtype),
            delta, keys,
        )
    if mode == "collude":
        return collusion_delta(policy, delta, round_idx)
    raise ValueError(f"unknown adversary mode: {mode!r}")


def corrupt_stacked_deltas(policy: AdversaryPolicy, stacked: Pytree,
                           round_idx,
                           cohort: jax.Array | None = None) -> Pytree:
    """Simulator path: return the ATTACKED version of every row of a
    stacked ``[C, ...]`` delta pytree (one vectorized op — the caller
    selects adversarial rows with its cohort mask, so honest rows stay
    byte-identical to the untouched input). Jit-traceable; ``round_idx``
    may be a traced scalar.

    ``cohort`` (``[C]`` sampled client ids) keys the ``gauss`` draw per
    ROW on (round, client id) instead of one full-stack-shaped draw —
    the draw is then independent of how the cohort is chunked, so the
    bulk engine's per-block application is bitwise-equal to the stacked
    path at matched seeds (pinned in ``tests/test_streamdef.py``). The
    stacked simulator passes its cohort too, so both paths share one
    keying. Every other mode is row-local (or, for collude, depends
    only on (seed, round)) and ignores ``cohort``."""
    if policy.mode == "collude":
        # one shared pseudo-delta, broadcast over the cohort axis
        like = jax.tree.map(lambda x: x[0], stacked)
        base = collusion_delta(policy, like, round_idx)
        return jax.tree.map(
            lambda x, b: jnp.broadcast_to(b[None], x.shape), stacked,
            base,
        )
    rk = _round_key(policy, round_idx)
    if policy.mode == "gauss" and cohort is not None:
        return jax.vmap(
            lambda d, c: _attack_one(
                policy, d, jax.random.fold_in(rk, c), round_idx
            )
        )(stacked, cohort)
    return _attack_one(policy, stacked, rk, round_idx)


def cohort_mask(policy: AdversaryPolicy, cohort: jax.Array,
                num_clients: int) -> jax.Array:
    """``[C]`` bool: which sampled cohort slots belong to adversarial
    CLIENT ids. ``cohort`` may be traced; the member-id set is a static
    host-side constant."""
    ids = policy.member_ids(num_clients)
    if ids.size == 0:
        return jnp.zeros(cohort.shape, bool)
    return jnp.isin(cohort, jnp.asarray(ids))


def corrupt_client_vars(policy: AdversaryPolicy, global_vars: dict,
                        new_vars: dict, round_idx: int,
                        member: int) -> dict:
    """Deployment path: a malicious rank corrupts its OWN result before
    sending — ``params' = global + attack(params - global)``. Non-param
    collections (batch_stats) ride unmodified, mirroring the simulator
    injection. ``member`` (the rank) decorrelates gauss draws between
    adversaries; collude ignores it by design."""
    g = global_vars["params"]
    delta = jax.tree.map(jnp.subtract, new_vars["params"], g)
    key = jax.random.fold_in(_round_key(policy, round_idx), member)
    attacked = _attack_one(policy, delta, key, round_idx)
    params = jax.tree.map(
        lambda gg, d: (gg + d).astype(gg.dtype), g, attacked
    )
    return {**new_vars, "params": params}

"""Heterogeneous-workload scheduler (branch-and-bound).

Re-design of the reference DP workload scheduler
(``fedml_core/distributed/schedule/scheduler.py:3-177``,
``DP_schedule(mode):109``): assign per-client workloads to heterogeneous
resources, minimizing the makespan (max per-resource cost) subject to
per-resource memory caps. The reference explores cases recursively by
popping the current-cheapest partial map; here the same best-first search
runs on a heap (no recursion-depth hazard, same expansion order), with the
reference's two modes:

- ``serial``: a resource runs its assigned workloads back-to-back; cost is
  additive (``assign_a_workload_serial``).
- ``parallel``: each resource has a concurrency budget; the reference's
  parallel mode tracks per-resource occupancy (``assign_a_workload``).

Workloads are pre-sorted descending (largest-first), matching
``self.x = np.sort(workloads)[::-1]``.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools

import numpy as np


@dataclasses.dataclass
class Assignment:
    """Result: ``mapping[i]`` = resource for workload i (original order),
    ``costs[r]`` = total cost on resource r, ``makespan`` = max cost."""

    mapping: np.ndarray
    costs: np.ndarray
    makespan: float


class WorkloadScheduler:
    def __init__(self, workloads, speeds, memory):
        """``workloads``: per-client work (e.g. n_k x epochs);
        ``speeds``: per-resource cost factor (reference ``constraints`` y);
        ``memory``: per-resource cost cap."""
        self.workloads = np.asarray(workloads, float)
        self.order = np.argsort(self.workloads)[::-1]
        self.sorted_w = self.workloads[self.order]
        self.speeds = np.asarray(speeds, float)
        self.memory = np.asarray(memory, float)

    def schedule(self, mode: str = "serial") -> Assignment | None:
        """Best-first branch-and-bound (reference ``DP_schedule``,
        ``scheduler.py:109``). Returns None if no feasible assignment."""
        assert mode in ("serial", "parallel")
        n, r = len(self.sorted_w), len(self.speeds)
        counter = itertools.count()
        # heap entries: (makespan, tiebreak, next_workload_idx, costs, map)
        heap = [(0.0, next(counter), 0, tuple(0.0 for _ in range(r)), ())]
        while heap:
            makespan, _, i, costs, mapping = heapq.heappop(heap)
            if i == n:
                full_map = np.empty(n, int)
                full_map[self.order] = np.asarray(mapping, int)
                return Assignment(
                    mapping=full_map,
                    costs=np.asarray(costs),
                    makespan=makespan,
                )
            w = self.sorted_w[i]
            for res in range(r):
                cost = self.speeds[res] * w
                new_costs = list(costs)
                if mode == "serial":
                    new_costs[res] += cost
                else:
                    # parallel: resource cost is its single largest job
                    # (jobs run concurrently, bounded by memory)
                    new_costs[res] = max(new_costs[res], cost)
                if new_costs[res] > self.memory[res]:
                    continue  # memory violation: prune (reference :47-50)
                heapq.heappush(
                    heap,
                    (
                        max(new_costs),
                        next(counter),
                        i + 1,
                        tuple(new_costs),
                        mapping + (res,),
                    ),
                )
        return None


def dp_schedule(workloads, speeds, memory, mode: str = "serial"):
    """Functional entry mirroring the reference ``DP_schedule``."""
    return WorkloadScheduler(workloads, speeds, memory).schedule(mode)

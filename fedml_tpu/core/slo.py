"""Declarative SLO engine: windowed objectives over the live registry.

ROADMAP item 3 says "heavy traffic" means the server is a long-lived
*service* with "per-job p99 round-latency SLOs from the existing
percentile export". This module turns that sentence into a mechanism
(docs/OBSERVABILITY.md "Live export and SLOs"):

- :class:`SloSpec` — one parsed objective,
  ``SloSpec.parse("perf.round_wall_s:p99<2.0@60s")``: metric, statistic
  (a histogram percentile/mean/max, a gauge ``value``, or a counter
  ``rate``), comparison, threshold, and evaluation window. Specs ride
  ``--slo`` (repeatable) / ``FedConfig.slos`` and carry a ``scope``
  (job id; defaults to the run name) so the multi-tenant service of
  ROADMAP item 3 can evaluate per-job objectives without rework.
- :class:`SloEngine` — the windowed evaluator. It rides the existing
  ``start_metrics_timeseries`` cadence (one ``tick()`` per flush
  interval): each tick snapshots the registry, reconstructs the
  WINDOWED histogram as the delta between the current cumulative
  buckets and the ring entry from ``window_s`` ago (cumulative bucket
  counts are monotone, so the difference is itself a valid histogram),
  and compares the spec's statistic against its threshold.

Burn state per spec is exported as gauges —
``slo.ok.<slug>`` (1/0), ``slo.breach_seconds.<slug>`` (total seconds
spent in breach), ``slo.burn_rate.<slug>`` (fraction of the trailing
window spent in breach) — and every breach TRANSITION (ok→breach,
breach→ok) records exactly ONE flight-recorder event, never one per
tick. At shutdown the engine writes ``slo_rank<r>.json`` verdicts next
to the other telemetry artifacts.

Like the rest of the plane, all of this is strictly opt-in: no specs,
no engine, no per-message or per-round work.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import re
import time
from typing import Any

_STATS = ("p50", "p95", "p99", "mean", "max", "min", "value", "rate")
_SPEC_RE = re.compile(
    r"^(?P<metric>[A-Za-z_][A-Za-z0-9_.]*)"
    r":(?P<stat>[a-z0-9]+)"
    r"(?P<op>[<>])"
    r"(?P<threshold>[-+0-9.eE]+)"
    r"@(?P<window>[0-9.]+)(?P<unit>s|m|h)$"
)
_UNIT_S = {"s": 1.0, "m": 60.0, "h": 3600.0}


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One parsed ``--slo`` objective. ``op`` is the HEALTHY relation:
    ``perf.round_wall_s:p99<2.0@60s`` is healthy while the windowed
    p99 stays BELOW 2 seconds."""

    metric: str
    stat: str  # p50|p95|p99|mean|max|min (histogram), value, rate
    op: str  # "<" | ">"
    threshold: float
    window_s: float
    scope: str = ""

    def __post_init__(self):
        if self.stat not in _STATS:
            raise ValueError(
                f"--slo statistic must be one of {_STATS}, "
                f"got {self.stat!r}"
            )
        if not math.isfinite(self.threshold):
            raise ValueError(
                f"--slo threshold must be finite, got {self.threshold!r}"
            )
        if not (self.window_s > 0):
            raise ValueError(
                f"--slo window must be positive, got {self.window_s!r}"
            )

    @staticmethod
    def parse(spec: str, scope: str = "") -> "SloSpec":
        """``metric:stat<threshold@window`` — e.g.
        ``perf.round_wall_s:p99<2.0@60s``, ``fleet.perf.round_wall_s:
        p95<1.5@5m``, ``round.quorum_lost_aborts:rate<0.01@10m``."""
        m = _SPEC_RE.match(spec.strip())
        if m is None:
            raise ValueError(
                f"malformed --slo {spec!r}: expected "
                f"'metric:stat<threshold@window' (e.g. "
                f"'perf.round_wall_s:p99<2.0@60s'; stats: "
                f"{', '.join(_STATS)}; window units: s/m/h)"
            )
        try:
            threshold = float(m.group("threshold"))
        except ValueError:
            raise ValueError(
                f"malformed --slo threshold {m.group('threshold')!r} "
                f"in {spec!r}"
            )
        return SloSpec(
            metric=m.group("metric"),
            stat=m.group("stat"),
            op=m.group("op"),
            threshold=threshold,
            window_s=float(m.group("window")) * _UNIT_S[m.group("unit")],
            scope=scope,
        )

    @property
    def slug(self) -> str:
        """Registry-safe identifier for the per-spec burn gauges
        (``slo.ok.<slug>``). The FULL spec participates — two SLOs on
        the same metric/stat with different thresholds or windows
        (a tight and a loose latency objective) must not collide on
        one gauge name."""
        op = "lt" if self.op == "<" else "gt"
        raw = (f"{self.metric}_{self.stat}_{op}_{self.threshold}"
               f"_{self.window_s}s")
        return re.sub(r"[^0-9a-zA-Z_]", "_", raw)

    def describe(self) -> str:
        w = self.window_s
        return f"{self.metric}:{self.stat}{self.op}{self.threshold}@{w}s"


def _hist_delta(cur: dict, base: dict | None) -> dict:
    """Windowed histogram = cumulative now minus cumulative at the
    window's start. Bucket counts are monotone, so the difference is a
    valid histogram; min/max keep the CURRENT cumulative values — they
    only clamp estimates derived from the windowed buckets, and a
    loose clamp degrades an estimate, never corrupts it (windowed
    min/max themselves come from :func:`_bucket_extremes`)."""
    if base is None:
        return cur
    buckets = {
        k: cur.get("buckets", {}).get(k, 0) - v
        for k, v in base.get("buckets", {}).items()
    }
    for k, v in cur.get("buckets", {}).items():
        if k not in buckets:
            buckets[k] = v
    return {
        "count": cur.get("count", 0) - base.get("count", 0),
        "sum": cur.get("sum", 0.0) - base.get("sum", 0.0),
        "min": cur.get("min", float("inf")),
        "max": cur.get("max", float("-inf")),
        "buckets": {k: v for k, v in buckets.items() if v > 0},
    }


def _bucket_extremes(delta: dict) -> tuple[float, float]:
    """Windowed (min, max) estimated from the delta's OCCUPIED
    power-of-two buckets: max is the highest occupied bucket's upper
    bound, min the lowest occupied bucket's lower bound, each clamped
    by the cumulative (all-time) extremes. Bounded by the 2x bucket
    width like every other histogram-derived statistic — the crucial
    property is that both are WINDOWED: a max-based SLO recovers once
    the slow observation ages out, instead of breaching forever on the
    all-time extreme."""
    ks = sorted(
        int(k.split("^", 1)[1]) for k in delta.get("buckets", {})
    )
    if not ks:
        return float("inf"), float("-inf")
    lo = 0.0 if ks[0] <= -20 else 2.0 ** (ks[0] - 1)
    hi = 2.0 ** ks[-1]
    cmin = delta.get("min", float("-inf"))
    cmax = delta.get("max", float("inf"))
    return max(lo, cmin), min(hi, cmax)


@dataclasses.dataclass
class _SpecState:
    breaching: bool = False
    transitions: int = 0
    breach_seconds: float = 0.0
    last_value: float | None = None
    # trailing (t0, t1, breached) tick intervals for the burn rate
    intervals: collections.deque = dataclasses.field(
        default_factory=collections.deque
    )


class SloEngine:
    """Windowed evaluator over a :class:`MetricsRegistry`.

    One engine per process; :func:`telemetry.configure` builds it from
    the ``--slo`` specs and hooks :meth:`tick` into the metrics
    time-series cadence. ``clock`` is injectable so transitions are
    testable without wall sleeps."""

    def __init__(self, specs, registry, recorder=None, clock=None):
        self.specs: list[SloSpec] = list(specs)
        self._registry = registry
        self._recorder = recorder
        self._clock = clock or time.monotonic
        # breach-transition listeners (core/anatomy.py's breach
        # profiler): called as cb(spec, breaching, value) exactly once
        # per transition, right where the flight event records — never
        # once per breached tick
        self._listeners: list = []
        self._max_window = max(
            (s.window_s for s in self.specs), default=0.0
        )
        # shared snapshot ring: (ts, histograms, counters) — every spec
        # reads the same registry, so one ring serves them all
        self._ring: collections.deque = collections.deque()
        self._state = {id(s): _SpecState() for s in self.specs}
        self._last_tick: float | None = None

    def add_transition_listener(self, cb) -> None:
        """Subscribe ``cb(spec, breaching, value)`` to breach
        transitions (idempotent per callable)."""
        if cb not in self._listeners:
            self._listeners.append(cb)

    # -- evaluation --------------------------------------------------------

    def _baseline(self, now: float, window_s: float):
        """Newest ring entry at least ``window_s`` old, as
        ``(ts, hists, counters)`` — the timestamp matters: with a tick
        interval coarser than the window the delta actually spans
        ``now - ts`` (> window), and rate-style statistics must
        normalize by the REAL covered span, not the nominal window.
        None while the run is younger than the window — the delta then
        falls back to the full cumulative state, which is exactly the
        window's content."""
        best = None
        for entry in self._ring:
            if now - entry[0] >= window_s:
                best = entry
            else:
                break
        return best

    def _value(self, spec: SloSpec, snap: dict,
               now: float) -> float | None:
        base = self._baseline(now, spec.window_s)
        if spec.stat == "value":
            g = snap["gauges"].get(spec.metric)
            if g is not None:
                return float(g)
            c = snap["counters"].get(spec.metric)
            return None if c is None else float(c)
        if spec.stat == "rate":
            cur = snap["counters"].get(spec.metric)
            if cur is None:
                return None
            prev = 0.0
            span = spec.window_s
            if base is not None:
                prev = base[2].get(spec.metric, 0.0)
                # the delta spans back to the BASELINE's timestamp,
                # which with a coarse tick interval is older than the
                # nominal window — dividing by window_s there would
                # overestimate the rate by interval/window
                span = max(now - base[0], spec.window_s)
            elif self._ring:
                span = max(now - self._ring[0][0], spec.window_s)
            return (float(cur) - float(prev)) / span
        h = snap["histograms"].get(spec.metric)
        if h is None:
            return None
        delta = _hist_delta(
            h, None if base is None else base[1].get(spec.metric)
        )
        count = delta.get("count", 0)
        if count <= 0:
            return None  # nothing observed inside the window
        if spec.stat == "mean":
            return float(delta["sum"]) / count
        if spec.stat in ("max", "min"):
            w_min, w_max = _bucket_extremes(delta)
            return float(w_max if spec.stat == "max" else w_min)
        from fedml_tpu.core.telemetry import percentiles_from_histogram

        q = float(spec.stat[1:]) / 100.0
        out = percentiles_from_histogram(delta, qs=(q,))
        return out.get(f"p{round(q * 100):d}")

    def tick(self, now: float | None = None) -> None:
        """One evaluation pass: compute each spec's windowed statistic,
        update its burn state, export the ``slo.*`` gauges, and record
        ONE flight event per breach transition. Appends the current
        snapshot to the ring afterwards, so the window never includes
        the tick's own baseline."""
        if not self.specs:
            return
        now = self._clock() if now is None else now
        snap = self._registry.snapshot()
        last = self._last_tick
        for spec in self.specs:
            st = self._state[id(spec)]
            value = self._value(spec, snap, now)
            if value is None:
                # no signal inside the window: keep the previous state
                # (an idle server is not breaching its latency SLO)
                breaching = st.breaching
            elif spec.op == "<":
                breaching = not (value < spec.threshold)
            else:
                breaching = not (value > spec.threshold)
            st.last_value = value if value is not None else st.last_value
            if breaching != st.breaching:
                st.breaching = breaching
                st.transitions += 1
                if self._recorder is not None:
                    self._recorder.record(
                        "slo_breach" if breaching else "slo_recovered",
                        slo=spec.describe(), scope=spec.scope,
                        value=value, threshold=spec.threshold,
                    )
                for cb in self._listeners:
                    try:
                        cb(spec, breaching, value)
                    except Exception:
                        pass  # a listener must not kill the evaluator
            if last is not None:
                # the just-elapsed interval is attributed to the state
                # this tick DETECTED (the crossing happened somewhere
                # inside it): a breach starts burning — and a recovery
                # stops burning — at the tick that observed it, not one
                # tick late
                st.intervals.append((last, now, st.breaching))
                if st.breaching:
                    st.breach_seconds += now - last
                while (st.intervals
                       and now - st.intervals[0][1] > spec.window_s):
                    st.intervals.popleft()
            burn_w = min(spec.window_s, (now - st.intervals[0][0])
                         if st.intervals else spec.window_s)
            burn = 0.0
            if burn_w > 0 and st.intervals:
                breached_s = sum(
                    min(t1, now) - max(t0, now - spec.window_s)
                    for t0, t1, b in st.intervals
                    if b and t1 > now - spec.window_s
                )
                burn = min(1.0, breached_s / burn_w)
            m = self._registry
            m.gauge(f"slo.ok.{spec.slug}", 0.0 if st.breaching else 1.0)
            m.gauge(f"slo.breach_seconds.{spec.slug}", st.breach_seconds)
            m.gauge(f"slo.burn_rate.{spec.slug}", burn)
        self._last_tick = now
        self._ring.append((now, snap["histograms"], snap["counters"]))
        while (len(self._ring) > 2
               and now - self._ring[1][0] >= self._max_window):
            self._ring.popleft()

    # -- verdicts ----------------------------------------------------------

    def verdicts(self) -> list[dict[str, Any]]:
        out = []
        for spec in self.specs:
            st = self._state[id(spec)]
            out.append({
                "slo": spec.describe(),
                "metric": spec.metric,
                "stat": spec.stat,
                "op": spec.op,
                "threshold": spec.threshold,
                "window_s": spec.window_s,
                "scope": spec.scope,
                "ok": not st.breaching,
                "transitions": st.transitions,
                "breach_seconds": round(st.breach_seconds, 6),
                "last_value": st.last_value,
            })
        return out

    def write_verdicts(self, path: str, rank: int = 0) -> None:
        """The shutdown artifact: one final evaluation, then the
        per-spec verdicts as ``slo_rank<r>.json`` (atomic — a crash
        mid-write must not leave a torn verdict)."""
        self.tick()
        data = {
            "rank": rank,
            "ts": time.time(),
            "slos": self.verdicts(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, default=repr)
        os.replace(tmp, path)


def parse_specs(specs, scope: str = "") -> list[SloSpec]:
    """Parse a sequence of ``--slo`` strings, deduplicating exact
    repeats (a config-file spec repeated on the CLI must not double its
    gauges)."""
    seen: dict[str, SloSpec] = {}
    for s in specs:
        parsed = SloSpec.parse(s, scope=scope)
        seen.setdefault(parsed.describe(), parsed)
    return list(seen.values())

"""Tracing / profiling: structured timers and benchmark log lines.

Reference: wall-clock timers around aggregation
(``FedAVGAggregator.py:60,86-87``) and grep-able "--Benchmark" lines via
``log_communication_tick/tock`` + ``log_round_start/end``
(``fedml_core/distributed/communication/utils.py:4-18``). Here the same
API feeds a structured in-memory trace (exportable to JSON) and optionally
``jax.profiler`` ranges so device timelines line up with host spans.

Every event carries a wall-clock ``ts`` (epoch seconds at start), the
emitting ``rank`` and thread id — the coordinates
``scripts/merge_trace.py`` needs to fold per-rank dumps into one
Chrome-trace-event timeline (Perfetto-loadable, pid = rank, tid =
thread). Cross-process correlation ids (``trace_id``/``span_id``) ride
in as ordinary attrs from the telemetry layer
(:mod:`fedml_tpu.core.telemetry`).
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time
from typing import Any


class Tracer:
    """Span collector with the reference's tick/tock vocabulary.

    ``events`` is a bounded ring (``max_events``, default 200k): a
    multi-thousand-round deployment with tracing left on keeps the most
    recent window instead of growing RSS without bound; ``dropped``
    counts evictions and is recorded in :meth:`dump`.
    """

    def __init__(self, use_jax_profiler: bool = False,
                 rank: int | None = None, max_events: int = 200_000):
        self.events: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=max_events
        )
        self.dropped = 0
        self._open: dict[str, tuple[float, float]] = {}
        self._jax = use_jax_profiler
        self.rank = rank
        self._lock = threading.Lock()

    def _emit(self, ev: dict[str, Any]) -> None:
        with self._lock:
            if len(self.events) == self.events.maxlen:
                self.dropped += 1
            self.events.append(ev)

    def _base(self, kind: str, ts: float, seconds: float,
              attrs: dict) -> dict[str, Any]:
        ev = {
            "kind": kind,
            "ts": ts,
            "seconds": seconds,
            "rank": self.rank,
            "tid": threading.get_ident() & 0xFFFFFFFF,
        }
        ev.update(attrs)  # attrs may override rank (shared-process worlds)
        return ev

    # -- reference-shaped API (communication/utils.py:4-18) ----------------
    def log_communication_tick(self, sender, receiver, tag: str = ""):
        self._open[f"comm:{sender}->{receiver}:{tag}"] = (
            time.perf_counter(), time.time()
        )
        logging.debug("--Benchmark tick comm %s->%s %s", sender, receiver, tag)

    def log_communication_tock(self, sender, receiver, tag: str = ""):
        key = f"comm:{sender}->{receiver}:{tag}"
        t0 = self._open.pop(key, None)
        if t0 is not None:
            dt = time.perf_counter() - t0[0]
            self._emit(self._base(
                "comm", t0[1], dt,
                {"sender": sender, "receiver": receiver, "tag": tag},
            ))
            logging.debug("--Benchmark tock comm %s %fs", key, dt)

    def log_round_start(self, round_idx: int):
        self._open[f"round:{round_idx}"] = (
            time.perf_counter(), time.time()
        )

    def log_round_end(self, round_idx: int):
        t0 = self._open.pop(f"round:{round_idx}", None)
        if t0 is not None:
            self._emit(self._base(
                "round", t0[1], time.perf_counter() - t0[0],
                {"round": round_idx},
            ))

    # -- generic spans -----------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        ctx = (
            __import__("jax").profiler.TraceAnnotation(name)
            if self._jax
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        ts = time.time()
        err: BaseException | None = None
        try:
            with ctx:
                yield
        except BaseException as e:
            # the span record must survive a raising body: a failing
            # round still leaves its timing (tagged with the error)
            # instead of silently dropping the event
            err = e
            raise
        finally:
            ev = self._base(
                "span", ts, time.perf_counter() - t0,
                {"name": name, **attrs},
            )
            if err is not None:
                ev["error"] = repr(err)
            self._emit(ev)

    def event(self, name: str, **attrs):
        """Instant event (zero duration) — message sends/delivers, fault
        injections, dead-peer marks."""
        self._emit(self._base(
            "event", time.time(), 0.0, {"name": name, **attrs}
        ))

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict[str, dict]:
        agg: dict[str, dict] = {}
        with self._lock:
            events = list(self.events)
        for e in events:
            key = e.get("name") or e["kind"]
            s = agg.setdefault(key, {"count": 0, "total_s": 0.0})
            s["count"] += 1
            s["total_s"] += e["seconds"]
        for s in agg.values():
            s["mean_s"] = s["total_s"] / s["count"]
        return agg

    def dump(self, path: str):
        with self._lock:
            events = list(self.events)
            dropped = self.dropped
        # atomic replace: a crash mid-flush (or a concurrent
        # merge_trace.py read) must never observe a truncated dump —
        # this artifact exists precisely for crash debugging
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"rank": self.rank, "dropped": dropped, "events": events},
                f, indent=2, default=repr,
            )
        os.replace(tmp, path)

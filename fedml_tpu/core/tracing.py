"""Tracing / profiling: structured timers and benchmark log lines.

Reference: wall-clock timers around aggregation
(``FedAVGAggregator.py:60,86-87``) and grep-able "--Benchmark" lines via
``log_communication_tick/tock`` + ``log_round_start/end``
(``fedml_core/distributed/communication/utils.py:4-18``). Here the same
API feeds a structured in-memory trace (exportable to JSON) and optionally
``jax.profiler`` ranges so device timelines line up with host spans.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Any


class Tracer:
    """Span collector with the reference's tick/tock vocabulary."""

    def __init__(self, use_jax_profiler: bool = False):
        self.events: list[dict[str, Any]] = []
        self._open: dict[str, float] = {}
        self._jax = use_jax_profiler

    # -- reference-shaped API (communication/utils.py:4-18) ----------------
    def log_communication_tick(self, sender, receiver, tag: str = ""):
        self._open[f"comm:{sender}->{receiver}:{tag}"] = time.perf_counter()
        logging.debug("--Benchmark tick comm %s->%s %s", sender, receiver, tag)

    def log_communication_tock(self, sender, receiver, tag: str = ""):
        key = f"comm:{sender}->{receiver}:{tag}"
        t0 = self._open.pop(key, None)
        if t0 is not None:
            dt = time.perf_counter() - t0
            self.events.append(
                {"kind": "comm", "sender": sender, "receiver": receiver,
                 "tag": tag, "seconds": dt}
            )
            logging.debug("--Benchmark tock comm %s %fs", key, dt)

    def log_round_start(self, round_idx: int):
        self._open[f"round:{round_idx}"] = time.perf_counter()

    def log_round_end(self, round_idx: int):
        t0 = self._open.pop(f"round:{round_idx}", None)
        if t0 is not None:
            self.events.append(
                {"kind": "round", "round": round_idx,
                 "seconds": time.perf_counter() - t0}
            )

    # -- generic spans -----------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        ctx = (
            __import__("jax").profiler.TraceAnnotation(name)
            if self._jax
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with ctx:
            yield
        self.events.append(
            {"kind": "span", "name": name,
             "seconds": time.perf_counter() - t0, **attrs}
        )

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict[str, dict]:
        agg: dict[str, dict] = {}
        for e in self.events:
            key = e.get("name") or e["kind"]
            s = agg.setdefault(key, {"count": 0, "total_s": 0.0})
            s["count"] += 1
            s["total_s"] += e["seconds"]
        for s in agg.values():
            s["mean_s"] = s["total_s"] / s["count"]
        return agg

    def dump(self, path: str):
        with open(path, "w") as f:
            json.dump(self.events, f, indent=2)

"""Cross-round client reputation: accumulate anomaly scores, quarantine.

Per-round defenses (:mod:`fedml_tpu.core.robust`) look at ONE cohort's
deltas; a patient adversary that poisons a little every round slides
under any single-round threshold. The reputation plane integrates over
time: every reporting client's per-round anomaly score
(:func:`fedml_tpu.core.robust.anomaly_scores`) feeds an EWMA, and a
client whose accumulated score crosses ``threshold`` is QUARANTINED —
excluded from aggregation but still served (it keeps receiving syncs
and its results keep being scored), so a false positive whose behavior
normalizes earns its way back out (``release`` hysteresis below the
trip threshold). A client that goes silent keeps its score frozen:
leaving and rejoining does not launder a reputation — which is exactly
the interplay with the JOIN/WELCOME rejoin protocol
(docs/FAULT_TOLERANCE.md): a quarantined client's JOIN is welcomed,
its results stay excluded.

State is two fixed-shape arrays (``scores[world]``,
``quarantined_at[world]``) so it persists through the server's
:class:`~fedml_tpu.utils.checkpoint.RoundCheckpointer` alongside
``ServerState`` — a SIGKILLed server does not forget who it banned.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np


@dataclasses.dataclass(frozen=True)
class QuarantinePolicy:
    """Reputation knobs. ``threshold <= 0`` disables quarantine (scores
    are still tracked when scoring runs).

    - ``threshold``: EWMA score above which a client is quarantined.
    - ``release_frac``: hysteresis — a quarantined client is released
      once its EWMA drops below ``threshold * release_frac``.
    - ``decay``: EWMA memory (``score = decay * old + (1-decay) *
      new``); higher = slower to trip AND slower to forgive.
    - ``warmup_rounds``: rounds at the start of a run during which
      scores accumulate but nobody trips (round-0 deltas are noisy).
    - ``evict_after``: rounds a rank may sit in quarantine without
      earning release before it is PERMANENTLY evicted from the
      membership ledger (docs/FAULT_TOLERANCE.md "Elastic
      membership"). 0 (default) = never escalate — quarantine stays
      recoverable forever.
    """

    threshold: float = 0.0
    release_frac: float = 0.5
    decay: float = 0.7
    warmup_rounds: int = 1
    evict_after: int = 0

    def __post_init__(self):
        if not (0.0 <= self.release_frac < 1.0):
            raise ValueError(
                f"release_frac must be in [0, 1), "
                f"got {self.release_frac}"
            )
        if not (0.0 <= self.decay < 1.0):
            raise ValueError(f"decay must be in [0, 1), got {self.decay}")
        if self.evict_after < 0:
            raise ValueError(
                f"evict_after must be >= 0, got {self.evict_after}"
            )

    def enabled(self) -> bool:
        return self.threshold > 0


class ReputationTracker:
    """Per-rank reputation for a ``size``-rank world (rank 0, the
    server, never quarantines itself — its slots stay zero).

    Elastic worlds (docs/FAULT_TOLERANCE.md "Elastic membership") grow
    past the launch ``world_size``: :meth:`ensure_size` extends the
    arrays for a newly admitted rank with a clean slate, and
    :meth:`load_arrays` accepts a checkpoint written by a DIFFERENT
    world size — the restored run keeps every score the checkpoint
    carries (leaving and rejoining, or restarting the server into a
    smaller launch world, must never launder a reputation)."""

    def __init__(self, size: int, policy: QuarantinePolicy | None = None):
        self.size = size
        self.policy = policy or QuarantinePolicy()
        self.scores = np.zeros(size, np.float32)
        # round at which the rank was quarantined; -1 = not quarantined
        self.quarantined_at = np.full(size, -1, np.int32)
        # elastic worlds mutate the tracker from more than one thread:
        # an admission's ensure_size arrives on the transport dispatch
        # thread while a round-deadline Timer (or liveness watchdog)
        # drives observe() through the round close — without this lock
        # an in-place observe write can land in an array concat just
        # discarded, silently losing the reputation update
        self._lock = threading.Lock()

    def ensure_size(self, size: int) -> None:
        """Grow the per-rank arrays to cover ``size`` ranks (new slots
        start clean: score 0, not quarantined). Shrinking never happens
        — a departed rank keeps its slot so a later rejoin resumes its
        accumulated reputation."""
        with self._lock:
            self._ensure_size_locked(size)

    def _ensure_size_locked(self, size: int) -> None:
        if size <= self.size:
            return
        pad = size - self.size
        self.scores = np.concatenate(
            [self.scores, np.zeros(pad, np.float32)]
        )
        self.quarantined_at = np.concatenate(
            [self.quarantined_at, np.full(pad, -1, np.int32)]
        )
        self.size = size

    # -- per-round update --------------------------------------------------

    def observe(self, round_idx: int, ranks: list[int],
                round_scores: np.ndarray) -> dict:
        """Fold one round's anomaly scores (``round_scores[i]`` belongs
        to ``ranks[i]``) into the EWMAs and apply the quarantine /
        release thresholds. Returns ``{"quarantined": [...],
        "released": [...], "suspected": [...]}`` — the NEW transitions
        plus the ranks whose instant score exceeded the threshold this
        round."""
        with self._lock:
            return self._observe_locked(round_idx, ranks, round_scores)

    def _observe_locked(self, round_idx, ranks, round_scores) -> dict:
        p = self.policy
        newly_q, released, suspected = [], [], []
        for rank, s in zip(ranks, np.asarray(round_scores, np.float32)):
            s = float(s)
            self.scores[rank] = (
                p.decay * self.scores[rank] + (1.0 - p.decay) * s
            )
            if not p.enabled():
                continue
            if s > p.threshold:
                suspected.append(rank)
            ewma = self.scores[rank]
            if self.quarantined_at[rank] < 0:
                if ewma > p.threshold and round_idx >= p.warmup_rounds:
                    self.quarantined_at[rank] = round_idx
                    newly_q.append(rank)
            elif ewma < p.threshold * p.release_frac:
                self.quarantined_at[rank] = -1
                released.append(rank)
        return {
            "quarantined": newly_q,
            "released": released,
            "suspected": suspected,
        }

    # -- queries -----------------------------------------------------------

    def is_quarantined(self, rank: int) -> bool:
        return bool(self.quarantined_at[rank] >= 0)

    def quarantined(self) -> list[int]:
        return [int(r) for r in np.nonzero(self.quarantined_at >= 0)[0]]

    def score(self, rank: int) -> float:
        return float(self.scores[rank])

    # -- checkpoint persistence (utils/checkpoint.py) ----------------------

    def state_arrays(self) -> dict[str, np.ndarray]:
        """Fixed-shape snapshot for the round checkpointer (rides the
        server's composite checkpoint payload)."""
        with self._lock:
            return {
                "scores": self.scores.copy(),
                "quarantined_at": self.quarantined_at.copy(),
            }

    def load_arrays(self, blob: dict) -> None:
        """Restore, tolerating a checkpoint written by a different
        world size: a larger checkpoint grows this tracker (an elastic
        run admitted ranks past the launch world before the crash); a
        smaller one restores into a clean-slate prefix (the world was
        relaunched bigger). Either way no saved score is dropped."""
        scores = np.asarray(blob["scores"], np.float32).ravel()
        qat = np.asarray(blob["quarantined_at"], np.int32).ravel()
        if scores.shape != qat.shape:
            raise ValueError(
                f"reputation checkpoint arrays disagree: scores "
                f"{scores.shape} vs quarantined_at {qat.shape}"
            )
        saved = scores.shape[0]
        with self._lock:
            self._ensure_size_locked(saved)
            self.scores = np.zeros(self.size, np.float32)
            self.quarantined_at = np.full(self.size, -1, np.int32)
            self.scores[:saved] = scores
            self.quarantined_at[:saved] = qat

"""Per-client state banks: ONE ``[num_clients, row]`` store for every
O(C) client-keyed state the compiled rounds carry.

The bulk engine (core/bulk.py) streams a cohort through the device in
O(block) memory — which is exactly why any per-client state (the
compress error-feedback residual, the PEFT private adapter bank) could
not ride it: both are ``[C, ...]`` buffers keyed by client identity,
and the streaming reduce folds identity away. A
:class:`ClientStateBank` restores the seam:

- the bank is a host- or device-resident pytree whose every leaf has a
  leading ``num_clients`` axis (the "rows");
- each round (or each block of a bulk round) GATHERS the sampled ids'
  rows, updates them, and SCATTERS them back — the bank itself rides
  the round program as a donated operand (and the ``lax.scan`` carry of
  :func:`fedml_tpu.core.bulk.stream_blocks`), so round working memory
  stays O(block) while the bank is updated in place;
- **sentinel padding**: a padded slot carries the out-of-range id
  ``num_clients``. JAX clamps out-of-bounds *gathers* (the garbage row
  is masked by the live mask downstream) and ``mode="drop"`` discards
  out-of-bounds *scatters* — so a pad slot can never collide with a
  real client id the way a 0-filled pad would collide with client 0
  (``.at[ids].set`` leaves duplicate-index write order unspecified);
- **screening preserves rows**: :meth:`ClientStateBank.put` takes a
  ``keep`` mask — a row is written only where ``keep`` holds, and a
  screened (non-finite) or non-live slot writes its GATHERED pre-round
  row back, a value-level no-op (ids are a without-replacement draw,
  so no real id appears twice in a round);
- the bank rides the :class:`~fedml_tpu.utils.checkpoint
  .RoundCheckpointer` composite (``{"server": ..., "bank": {name:
  rows}}``) so a SIGKILLed run restores every client's row bitwise
  (docs/FAULT_TOLERANCE.md "Client-state banks").

Registered as a pytree (``name`` is static aux data), so a bank passes
through ``jax.jit`` operands, donation, and scan carries unchanged.

Telemetry (docs/OBSERVABILITY.md): ``bank.rows`` / ``bank.row_bytes``
/ ``bank.resident_mb`` gauges at bank creation, ``bank.gathers`` /
``bank.scatters`` counters at each round dispatch that touches a bank.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fedml_tpu.core import telemetry

Pytree = Any


@jax.tree_util.register_pytree_node_class
class ClientStateBank:
    """A named ``[num_clients, ...]``-leaved pytree of per-client rows."""

    def __init__(self, name: str, rows: Pytree):
        self.name = name
        self.rows = rows

    def tree_flatten(self):
        return (self.rows,), self.name

    @classmethod
    def tree_unflatten(cls, name, children):
        return cls(name, children[0])

    # -- constructors -------------------------------------------------------

    @classmethod
    def zeros(cls, name: str, template: Pytree,
              num_clients: int) -> "ClientStateBank":
        """Every row a zero of ``template``'s leaf shapes (the EF
        residual's init: round 0 transmits the uncorrected delta)."""
        rows = jax.tree.map(
            lambda v: jnp.zeros((num_clients,) + tuple(v.shape), v.dtype),
            template,
        )
        return cls(name, rows)

    @classmethod
    def broadcast(cls, name: str, template: Pytree,
                  num_clients: int) -> "ClientStateBank":
        """Every row a copy of ``template`` (the adapter bank's init:
        round 0 every client IS the base model)."""
        rows = jax.tree.map(
            lambda v: jnp.broadcast_to(
                v[None], (num_clients,) + tuple(v.shape)
            ).astype(v.dtype),
            template,
        )
        return cls(name, rows)

    # -- geometry -----------------------------------------------------------

    @property
    def num_rows(self) -> int:
        leaves = jax.tree.leaves(self.rows)
        return int(leaves[0].shape[0]) if leaves else 0

    @property
    def sentinel(self) -> int:
        """The pad id: out of range by construction, see module doc."""
        return self.num_rows

    def row_bytes(self) -> int:
        return sum(
            int(jnp.dtype(v.dtype).itemsize)
            * int(max(1, v.size) // max(1, v.shape[0]))
            for v in jax.tree.leaves(self.rows)
        )

    def resident_bytes(self) -> int:
        return sum(
            int(jnp.dtype(v.dtype).itemsize) * int(v.size)
            for v in jax.tree.leaves(self.rows)
        )

    # -- gather / scatter ---------------------------------------------------

    def gather(self, ids: jax.Array) -> Pytree:
        """The sampled ids' rows, stacked ``[B, ...]``. Sentinel ids
        clamp (JAX out-of-bounds gather) to the last real row — callers
        mask pad slots with the live mask before the rows matter."""
        return jax.tree.map(lambda v: v[ids], self.rows)

    def put(self, ids: jax.Array, new_rows: Pytree, keep=None,
            gathered: Pytree | None = None) -> "ClientStateBank":
        """Scatter updated rows back by id. Sentinel (out-of-range) ids
        are DROPPED; where ``keep`` (a ``[B]`` bool mask — live and
        finite) is False the pre-round row is written back unchanged (a
        value-level no-op). ``gathered`` skips the re-gather when the
        caller already holds the pre-round rows."""
        if keep is not None:
            if gathered is None:
                gathered = self.gather(ids)
            new_rows = jax.tree.map(
                lambda n, o: jnp.where(
                    keep.reshape((-1,) + (1,) * (n.ndim - 1)), n,
                    o.astype(n.dtype),
                ),
                new_rows, gathered,
            )
        rows = jax.tree.map(
            lambda b, r: b.at[ids].set(r.astype(b.dtype), mode="drop"),
            self.rows, new_rows,
        )
        return ClientStateBank(self.name, rows)

    # -- checkpoint ride-along ----------------------------------------------

    def savable(self) -> Pytree:
        return self.rows

    @classmethod
    def from_savable(cls, name: str, template_rows: Pytree,
                     blob: Pytree) -> "ClientStateBank":
        from fedml_tpu.utils import checkpoint as CK

        return cls(name, CK.from_savable(template_rows, blob))


def pad_ids(ids: jax.Array, n_slots: int, sentinel: int) -> jax.Array:
    """Pad a ``[draw]`` id vector to ``[n_slots]`` with the sentinel
    (out-of-range) id — see the module doc for why not 0."""
    pad = n_slots - int(ids.shape[0])
    if pad <= 0:
        return ids
    fill = jnp.full((pad,), sentinel, ids.dtype)
    return jnp.concatenate([ids, fill])


# ---------------------------------------------------------------------------
# telemetry (names are docs/OBSERVABILITY.md vocabulary rows)
# ---------------------------------------------------------------------------


def note_bank(bank: ClientStateBank) -> None:
    """Resident-footprint gauges, written once at bank creation (and
    harmless to refresh)."""
    m = telemetry.METRICS
    if not m.enabled:
        return
    m.gauge("bank.rows", float(bank.num_rows))
    m.gauge("bank.row_bytes", float(bank.row_bytes()))
    m.gauge("bank.resident_mb", bank.resident_bytes() / 1e6)


def note_round_io(gathers: int, scatters: int) -> None:
    """Per-dispatch gather/scatter counts (host-side; one per block per
    bank in a bulk round, one per round on the stacked path)."""
    m = telemetry.METRICS
    if not m.enabled:
        return
    if gathers:
        m.inc("bank.gathers", gathers)
    if scatters:
        m.inc("bank.scatters", scatters)

"""Performance observability: device-time breakdowns, MFU gauges, and
the analytic round-cost model shared with ``bench.py``.

ROADMAP item 5 diagnosed the headline problem — ~19 rounds/s at ~5% MFU
— but until now the only device-time evidence lived in one-off scripts
(``scripts/profile_round.py``) that nothing in the runtime ever ran,
and ``bench.py``'s ``mfu < 0.005`` warning fired once into a JSON line
nobody monitors. This module promotes that ad-hoc layer into a
first-class runtime subsystem (docs/OBSERVABILITY.md "Performance
observability"):

- :func:`useful_round_cost` — the analytic USEFUL-FLOPs model of one
  FedAvg round (moved here from ``bench.py:406`` so the bench and the
  runtime MFU gauge share ONE definition and can never drift);
- :class:`RoundProfiler` — programmatic ``jax.profiler`` capture
  windows around the first K compiled rounds (``--profile_rounds K`` /
  ``FedConfig.profile_rounds``), each parsed into a per-round
  **device-time breakdown**: compute vs collective vs host-blocked vs
  idle. Captures land under ``<telemetry_dir>/jax_profile/round<k>/``
  (one window per round, so breakdowns are genuinely per-round and
  ``--trace_jax`` TraceAnnotations fold into the same capture), the
  parsed breakdowns into ``perf_rank<r>.json``;
- :class:`PerfMonitor` — a live ``perf.mfu`` gauge computed from the
  same cost model over a smoothed round rate, plus the
  **dispatch-bound detector**: ``mfu < mfu_floor`` becomes a
  ``perf.dispatch_bound_rounds`` counter, a ``perf.latency_bound``
  gauge, and a flight-recorder event instead of a one-shot bench note;
- trace parsing (:func:`load_trace_events`,
  :func:`device_time_breakdown`) over the ``*.trace.json.gz``
  Chrome-trace files ``jax.profiler`` writes — dependency-free (no
  tensorflow / xplane protobuf needed), and the breakdown computation
  is a pure function over normalized events so tests pin it on
  synthetic captures.

The deploy server actor wires its own ``perf.agg_wall_s`` /
``perf.host_wait_s`` accounting (the server-side time accounting the
Smart-NIC FL serving work optimizes against, arxiv 2307.06561) in
``algorithms/distributed_fedavg.py``; the sims wire this module through
``FedAvgSim.run`` and the experiment harness.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import time
from typing import Any

import numpy as np

from fedml_tpu.core import telemetry

# ---------------------------------------------------------------------------
# chip peaks + the analytic round-cost model (shared with bench.py)
# ---------------------------------------------------------------------------

# (bf16 peak FLOP/s, HBM bandwidth B/s, HBM capacity bytes) per chip.
# v5e (TPU v5 lite): 197 bf16 TFLOP/s, ~819 GB/s, 16 GB HBM. Fallbacks
# for other chips; the point of MFU here is a stable, honest
# denominator, and the capacity column is the headroom denominator the
# memory monitor (core/memscope.py) alarms against.
PEAKS: dict[str, tuple[float, float, float]] = {
    "TPU v5 lite": (197e12, 819e9, 16e9),
    "TPU v4": (275e12, 1228e9, 32e9),
    "TPU v5p": (459e12, 2765e9, 95e9),
    "TPU v6 lite": (918e12, 1640e9, 32e9),
}


def device_peak_flops(kind: str) -> float | None:
    """bf16 MXU peak for a device kind (None for unknown kinds — CPU
    hosts get no MFU gauge rather than a made-up denominator)."""
    return PEAKS.get(kind, (None, None, None))[0]


def device_hbm_capacity(kind: str) -> float | None:
    """Per-chip HBM capacity in bytes (None for unknown kinds — the
    memory monitor then prefers the device's own ``bytes_limit`` and
    otherwise reports no headroom rather than a made-up one)."""
    return PEAKS.get(kind, (None, None, None))[2]


_COST_CACHE: dict = {}


def useful_round_cost(sim) -> float | None:
    """Analytic FLOPs of the USEFUL work in one round: sampled clients
    x their real serial-equivalent optimizer steps x one fwd+bwd batch.
    The compiled round's own XLA cost analysis is not usable directly —
    the step loop has a data-dependent trip count (padding steps are
    skipped at runtime) and HLO cost analysis counts loop bodies once —
    so MFU is reported against the work the *semantics* require, making
    it an honest utilization number: padding waste and grouped-conv
    expansion lower it, exactly as they should. ONE definition, shared
    by ``bench.py``'s record fields and the runtime ``perf.mfu`` gauge
    (:class:`PerfMonitor`), so the two can never drift. (Bytes moved
    are handled separately by ``bench.compulsory_round_bytes``.)"""
    import jax
    import jax.numpy as jnp

    model, B = sim.model, sim.batch_size
    compute_dtype = jnp.dtype(sim.cfg.train.compute_dtype)

    from fedml_tpu.algorithms.base import (
        _static_vars_to_dtype,
        _tree_to_dtype,
    )

    def step_loss(params, static_vars, x, y):
        # the SAME casting policy as the training loss_fn (params ->
        # compute dtype, batch_stats stay f32) and the SAME task loss
        # (classification CE / nwp token CE / tag BCE), imported so the
        # costed program cannot drift from the real one
        variables = {
            **_static_vars_to_dtype(static_vars, compute_dtype),
            "params": _tree_to_dtype(params, compute_dtype),
        }
        xc = (
            x.astype(compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x
        )
        logits, _ = model.apply_train(variables, xc, jax.random.key(0))
        sums = sim.task.metric_sums(
            logits.astype(jnp.float32), y, jnp.ones((B,), jnp.float32)
        )
        return sums["loss_sum"] / jnp.maximum(sums["w_sum"], 1.0)

    x_shape = (B,) + sim.arrays.x.shape[1:]
    y_shape = (B,) + sim.arrays.y.shape[1:]
    cost_key = (sim.cfg.model.name, x_shape, y_shape, str(compute_dtype))
    if cost_key in _COST_CACHE:
        step_flops = _COST_CACHE[cost_key]
    else:
        variables = model.init(jax.random.key(0))
        params = variables["params"]
        static_vars = {k: v for k, v in variables.items() if k != "params"}
        x = jnp.zeros(x_shape, sim.arrays.x.dtype)
        y = jnp.zeros(y_shape, sim.arrays.y.dtype)
        try:
            ca = (
                jax.jit(jax.grad(step_loss))
                .lower(params, static_vars, x, y)
                .compile()
                .cost_analysis()
            )
            if isinstance(ca, list):
                ca = ca[0]
            step_flops = float(ca.get("flops") or 0) or None
        except Exception:
            return None
        _COST_CACHE[cost_key] = step_flops
    counts = np.asarray(sim.arrays.counts)
    mean_steps = float(np.mean(np.ceil(counts / B)))
    k = sim.cfg.fed.clients_per_round * mean_steps * sim.cfg.train.epochs
    return step_flops * k if step_flops else None


# ---------------------------------------------------------------------------
# jax-profiler capture parsing (dependency-free Chrome-trace path)
# ---------------------------------------------------------------------------

#: HLO op-name prefixes that are cross-device collectives.
_COLLECTIVE_RE = re.compile(
    r"^(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)"
)
#: HLO op-name prefixes that are host/data movement the device waits on.
_TRANSFER_RE = re.compile(r"^(copy|infeed|outfeed|send|recv|host)")
#: Host-side events that mean "the host is blocked on device/transfer".
_HOST_BLOCK_RE = re.compile(
    r"(Await|BlockHostUntil|BlockUntilReady|SyncAllActivity|"
    r"TransferLiteral|ExecuteOnStream)"
)


def load_trace_events(profile_dir: str) -> list[dict[str, Any]]:
    """Load every ``*.trace.json.gz`` under a jax-profiler session dir
    (``<dir>/plugins/profile/<ts>/<host>.trace.json.gz``) into
    normalized event dicts ``{name, pid, tid, ts, dur, process, args}``
    (``ts``/``dur`` in microseconds, session-relative). Returns ``[]``
    when no capture exists — callers degrade to a host-only breakdown
    instead of crashing a run whose backend skipped the trace."""
    paths = sorted(
        glob.glob(
            os.path.join(profile_dir, "**", "*.trace.json.gz"),
            recursive=True,
        )
    )
    events: list[dict[str, Any]] = []
    for p in paths:
        try:
            with gzip.open(p, "rt") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError, EOFError):
            continue
        raw = data.get("traceEvents", [])
        procs = {
            e["pid"]: e.get("args", {}).get("name", "")
            for e in raw
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        for e in raw:
            if e.get("ph") != "X":
                continue
            events.append({
                "name": e.get("name", ""),
                "pid": e.get("pid", 0),
                "tid": e.get("tid", 0),
                "ts": float(e.get("ts", 0.0)),
                "dur": float(e.get("dur", 0.0)),
                "process": procs.get(e.get("pid", 0), ""),
                "args": e.get("args", {}) or {},
            })
    return events


def _union_us(intervals: list[tuple[float, float]]) -> float:
    """Total covered microseconds of a set of (start, end) intervals —
    nested/overlapping events (a fusion inside a call, parallel
    threadpool lanes) must not double-count wall time."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def _subtract_us(
    intervals: list[tuple[float, float]],
    cover: list[tuple[float, float]],
) -> float:
    """Microseconds of ``intervals`` NOT covered by ``cover`` (both get
    union-merged first)."""
    both = _union_us(list(intervals) + list(cover))
    return max(0.0, both - _union_us(list(cover)))


def device_time_breakdown(
    events: list[dict[str, Any]], window_s: float | None = None
) -> dict[str, Any]:
    """Fold a capture window's events into the four-way device-time
    breakdown: **compute / collective / host-blocked / idle**.

    Classification:

    - *device op* events are those on a ``/device:*`` plane, or — on
      backends whose XLA thunks run on host threads (the CPU backend;
      what CI exercises) — any event carrying an ``hlo_op`` arg;
    - device ops whose HLO name is a collective prefix (all-reduce /
      all-gather / reduce-scatter / all-to-all / collective-permute /
      collective-broadcast) are **collective**; copy/infeed/outfeed/
      send/recv ops are charged to **host** (data movement the device
      stalls on); everything else is **compute**;
    - host-plane blocking events (buffer awaits, BlockHostUntilReady,
      literal transfers) that do NOT overlap device-busy time are added
      to **host** — the host was stalled while the device did nothing;
    - **idle** is the remainder of the window
      (``window - device_busy - host_blocked``).

    Every duration is the interval-UNION of ITS OWN category's events
    (parallel lanes and nested events never double-count wall time),
    so each ``*_frac`` reads "fraction of the window in which at least
    one op of this kind was running". Categories may OVERLAP in time —
    a collective running concurrently with compute counts fully in
    both, which is the honest view: comm/compute overlap is the
    async-dispatch win, not an accounting error — so the fractions sum
    to 1 only for serial captures. ``window_s`` should be the measured
    wall duration of the capture; when omitted the event span is
    used."""
    device_planes = {
        e["pid"] for e in events if e["process"].startswith("/device:")
    }
    if device_planes:
        dev = [e for e in events if e["pid"] in device_planes
               and e["dur"] > 0]
    else:
        dev = [e for e in events if "hlo_op" in e["args"]
               and e["dur"] > 0]

    def iv(evs):
        return [(e["ts"], e["ts"] + e["dur"]) for e in evs]

    def opname(e):
        return str(e["args"].get("hlo_op") or e["name"])

    coll = [e for e in dev if _COLLECTIVE_RE.match(opname(e))]
    xfer = [e for e in dev if _TRANSFER_RE.match(opname(e))]
    nc = {id(e) for e in coll} | {id(e) for e in xfer}
    comp = [e for e in dev if id(e) not in nc]
    busy_iv = iv(dev)
    busy_us = _union_us(list(busy_iv))
    coll_us = _union_us(iv(coll))
    xfer_us = _union_us(iv(xfer))
    # compute is the union of COMPUTE-classified events, not busy minus
    # the other categories' totals: a collective on a parallel lane
    # must not eat concurrent compute time (per-category unions may
    # overlap; see the docstring)
    compute_us = _union_us(iv(comp))
    host_block = [
        e for e in events
        if e["pid"] not in device_planes and e["dur"] > 0
        and "hlo_op" not in e["args"] and _HOST_BLOCK_RE.search(e["name"])
    ]
    host_block_us = _subtract_us(iv(host_block), busy_iv)

    if window_s is None:
        if events:
            lo = min(e["ts"] for e in events)
            hi = max(e["ts"] + e["dur"] for e in events)
            window_s = (hi - lo) / 1e6
        else:
            window_s = 0.0
    window_us = max(window_s * 1e6, busy_us + host_block_us)
    host_us = xfer_us + host_block_us
    idle_us = max(0.0, window_us - busy_us - host_block_us)

    def frac(us):
        return us / window_us if window_us > 0 else 0.0

    return {
        "window_s": window_us / 1e6,
        "device_busy_s": busy_us / 1e6,
        "compute_s": compute_us / 1e6,
        "collective_s": coll_us / 1e6,
        "host_s": host_us / 1e6,
        "idle_s": idle_us / 1e6,
        "compute_frac": frac(compute_us),
        "collective_frac": frac(coll_us),
        "host_frac": frac(host_us),
        "idle_frac": frac(idle_us),
        "n_device_ops": len(dev),
        "n_events": len(events),
        "device_planes": bool(device_planes),
    }


# ---------------------------------------------------------------------------
# runtime layer: capture windows + live gauges
# ---------------------------------------------------------------------------


class RoundProfiler:
    """Programmatic ``jax.profiler`` windows around the first K rounds.

    Each profiled round gets its OWN capture session under
    ``<out_dir>/jax_profile/round<k>/`` — per-round windows make the
    breakdown genuinely per-round without segmenting one long capture,
    and keep ``--trace_jax``'s TraceAnnotations inside the matching
    round's file. A ``capture.json`` manifest (epoch start + wall
    window) rides next to each capture so ``scripts/merge_trace.py``
    can rebase the session-relative device timestamps onto the host
    span timeline. Parsed breakdowns feed ``perf.profile.*`` gauges and
    are written to ``<out_dir>/perf_<tag>.json`` by :meth:`finish`.

    Profiler failures (an unsupported backend, a second live session)
    disable further captures with a recorded warning — a perf run must
    degrade to wall-clock gauges, never crash the experiment.
    """

    def __init__(self, rounds: int, out_dir: str, tag: str | None = None,
                 flops_per_round: float | None = None,
                 fuse_rounds: int = 1):
        self.rounds = int(rounds)
        self.out_dir = out_dir
        self.tag = tag or telemetry.rank_tag()
        self.flops_per_round = flops_per_round
        # under round fusion (--fuse_rounds K) a capture window spans a
        # whole K-round BLOCK — recorded in the manifest and the
        # breakdown rows so a per-block breakdown is never silently
        # read as per-round (docs/PERFORMANCE.md "Round fusion")
        self.fuse_rounds = max(1, int(fuse_rounds or 1))
        self.capture_dir = os.path.join(out_dir, "jax_profile")
        self.breakdowns: list[dict] = []
        self._active: tuple[int, str, float, float] | None = None
        self._broken = False

    @property
    def wants_capture(self) -> bool:
        """True while another capture window can open (budget left, no
        window active, profiler healthy). The fused round loop checks
        this to drain its metric pipeline around profiled blocks, so a
        capture contains exactly one block's device work."""
        return (not self._broken and self._active is None
                and len(self.breakdowns) < self.rounds)

    def start_round(self, round_idx: int) -> None:
        if (self._broken or self._active is not None
                or len(self.breakdowns) >= self.rounds):
            return
        import jax

        d = os.path.join(self.capture_dir, f"round{round_idx}")
        try:
            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception as err:
            self._broken = True
            telemetry.RECORDER.record("perf_profile_failed",
                                      error=repr(err))
            return
        self._active = (round_idx, d, time.perf_counter(), time.time())

    def end_round(self, round_idx: int, rounds: int = 1) -> None:
        """Close the window opened for ``round_idx`` (call AFTER the
        round's metrics were forced to host, so the capture contains
        the device execution, not just the dispatch). Under round
        fusion the window covers a whole block: pass ``rounds`` so the
        manifest and the breakdown row say how many rounds the window
        actually contains."""
        if self._active is None or self._active[0] != round_idx:
            return
        import jax

        _, d, t0, epoch0 = self._active
        self._active = None
        window_s = time.perf_counter() - t0
        try:
            jax.profiler.stop_trace()
        except Exception as err:
            self._broken = True
            telemetry.RECORDER.record("perf_profile_failed",
                                      error=repr(err))
            return
        manifest = {"round": round_idx, "t_start": epoch0,
                    "window_s": window_s,
                    "fuse_rounds": self.fuse_rounds,
                    "rounds_in_window": int(rounds)}
        try:
            with open(os.path.join(d, "capture.json"), "w") as f:
                json.dump(manifest, f)
        except OSError:
            pass
        bd = device_time_breakdown(load_trace_events(d),
                                   window_s=window_s)
        bd["round"] = round_idx
        bd["rounds_in_window"] = int(rounds)
        self.breakdowns.append(bd)
        m = telemetry.METRICS
        m.inc("perf.profiled_rounds")
        for k in ("compute_frac", "collective_frac", "host_frac",
                  "idle_frac"):
            m.gauge(f"perf.profile.{k}", bd[k])
        m.gauge("perf.profile.window_s", bd["window_s"])
        telemetry.RECORDER.record(
            "perf_profile", round=round_idx,
            compute_frac=round(bd["compute_frac"], 4),
            collective_frac=round(bd["collective_frac"], 4),
            host_frac=round(bd["host_frac"], 4),
            idle_frac=round(bd["idle_frac"], 4),
        )

    def finish(self) -> str | None:
        """Write the per-round breakdown artifact; returns its path."""
        if self._active is not None:  # a raising round left it open
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = None
        if not self.breakdowns:
            return None
        path = os.path.join(self.out_dir, f"perf_{self.tag}.json")
        mean = {
            k: float(np.mean([b[k] for b in self.breakdowns]))
            for k in ("compute_frac", "collective_frac", "host_frac",
                      "idle_frac", "window_s")
        }
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({
                    "tag": self.tag,
                    "flops_per_round": self.flops_per_round,
                    "fuse_rounds": self.fuse_rounds,
                    "rounds": self.breakdowns,
                    "mean": mean,
                }, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            return None
        return path


class PerfMonitor:
    """Live round-rate / MFU gauges + the dispatch-bound detector.

    ``note_round(wall_s)`` per completed round feeds:

    - ``perf.round_wall_s`` histogram (p50/p95/p99 ride the registry's
      percentile estimation — the round-latency SLO surface);
    - ``perf.rounds_per_s`` gauge (EWMA-smoothed);
    - ``perf.mfu`` / ``perf.delivered_flops_per_s`` gauges when the
      analytic round cost and the chip peak are known — the SAME
      :func:`useful_round_cost` model as ``bench.py``, so the live
      gauge and the bench record agree by construction;
    - the detector: ``mfu < mfu_floor`` (bench's one-shot 0.005
      warning, now a runtime signal) increments
      ``perf.dispatch_bound_rounds``, sets ``perf.latency_bound`` and
      leaves ONE flight-recorder event per run — the round is bounded
      by dispatch/lowering latency, not the MXU.

    The first ``warmup_rounds`` rounds (default 1) are EXCLUDED from
    the histogram, the EWMA, and the detector — round 0's wall is
    dominated by the XLA compile (bench.py pays the same discipline
    with its explicit warmup execution), and folding it in would both
    skew the p99 the docs call the SLO surface and spuriously consume
    the per-run dispatch-bound event on a healthy run. The skipped
    wall is still visible as the ``perf.warmup_round_wall_s`` gauge.
    """

    def __init__(self, flops_per_round: float | None = None,
                 peak_flops: float | None = None, path: str = "sim",
                 mfu_floor: float = 0.005, smoothing: float = 0.5,
                 warmup_rounds: int = 1):
        self.flops_per_round = flops_per_round
        self.peak_flops = peak_flops
        self.path = path
        self.mfu_floor = mfu_floor
        self.smoothing = smoothing
        self.warmup_rounds = warmup_rounds
        self._avg_wall: float | None = None
        self._flagged = False
        self.rounds = 0

    @property
    def mfu(self) -> float | None:
        if (not self.flops_per_round or not self.peak_flops
                or not self._avg_wall):
            return None
        return self.flops_per_round / (self._avg_wall * self.peak_flops)

    def note_round(self, wall_s: float) -> None:
        self.note_block(wall_s, 1)

    def note_block(self, wall_s: float, rounds: int,
                   compiled: bool = False) -> None:
        """One completed fused block of ``rounds`` rounds: the wall
        DIVIDES by the round count before feeding the SLO histogram,
        the EWMA, and the MFU gauge, so the per-round surface stays
        honest under ``--fuse_rounds`` (a 4-round block at 2 s is
        0.5 s/round, never a 2 s p99 outlier) and the dispatch-bound
        detector keeps comparing per-round numbers. Excluded whole —
        wall gauged as ``perf.warmup_round_wall_s`` instead — are a
        block containing ANY warmup round AND any block flagged
        ``compiled`` (the fused drivers flag the first dispatch of
        each distinct block length: eval/checkpoint remainders trace a
        fresh scan program post-warmup, and that compile must not
        become the p99 or trip the dispatch-bound detector).
        ``note_round`` is the ``rounds=1`` case."""
        if wall_s <= 0 or rounds <= 0:
            return
        first = self.rounds
        self.rounds += rounds
        per = wall_s / rounds
        if compiled or first < self.warmup_rounds:
            telemetry.METRICS.gauge("perf.warmup_round_wall_s", per)
            return
        self._avg_wall = (
            per if self._avg_wall is None
            else (self.smoothing * per
                  + (1 - self.smoothing) * self._avg_wall)
        )
        m = telemetry.METRICS
        for _ in range(rounds):
            m.observe("perf.round_wall_s", per)
        m.gauge("perf.rounds_per_s", 1.0 / self._avg_wall)
        if self.flops_per_round:
            m.gauge("perf.delivered_flops_per_s",
                    self.flops_per_round / self._avg_wall)
        mfu = self.mfu
        if mfu is None:
            return
        m.gauge("perf.mfu", mfu)
        if mfu < self.mfu_floor:
            m.inc("perf.dispatch_bound_rounds", rounds)
            m.gauge("perf.latency_bound", 1.0)
            if not self._flagged:
                self._flagged = True
                telemetry.RECORDER.record(
                    "perf_dispatch_bound", path=self.path,
                    mfu=float(f"{mfu:.3g}"),
                    flops_per_round=self.flops_per_round,
                    note="round time is dispatch/lowering latency, not "
                         "flops — rounds/sec is the meaningful number",
                )
        else:
            m.gauge("perf.latency_bound", 0.0)


def build_sim_perf(sim) -> tuple[RoundProfiler | None,
                                 PerfMonitor | None]:
    """Perf wiring for a round-loop driver (``FedAvgSim.run`` and the
    experiment harness share this so the two loops cannot drift).
    Returns ``(None, None)`` unless ``cfg.fed.profile_rounds > 0`` —
    the off path costs one attribute read. The analytic round cost is
    resolved best-effort: sims outside the FedAvg family still get
    wall-clock gauges and capture windows, just no MFU."""
    cfg = getattr(sim, "cfg", None)
    k = int(getattr(getattr(cfg, "fed", None), "profile_rounds", 0) or 0)
    if k <= 0:
        return None, None
    import jax

    telemetry.METRICS.enabled = True
    out_dir = telemetry.artifact_dir()
    if out_dir is None:
        out_dir = os.path.join(cfg.out_dir, cfg.run_name, "telemetry")
        os.makedirs(out_dir, exist_ok=True)
    flops = None
    try:
        flops = useful_round_cost(sim)
    except Exception:
        flops = None
    # the sharded runtime spreads the round over its mesh: the honest
    # denominator is every chip it occupies, not one
    mesh = getattr(sim, "mesh", None)
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    peak = device_peak_flops(jax.devices()[0].device_kind)
    fuse = int(getattr(cfg.fed, "fuse_rounds", 1) or 1)
    profiler = RoundProfiler(k, out_dir, flops_per_round=flops,
                             fuse_rounds=fuse)
    monitor = PerfMonitor(
        flops_per_round=flops,
        peak_flops=peak * n_dev if peak else None,
        path=type(sim).__name__,
    )
    return profiler, monitor

"""Message envelope for the cross-process runtime.

Mirrors the reference's ``Message``
(``fedml_core/distributed/communication/message.py:5-81``): a typed envelope
``(msg_type, sender, receiver)`` plus arbitrary params (model pytrees ride
as numpy arrays). The reference pickles messages over MPI
(``mpi_send_thread.py:22-27``) and JSON-encodes them over gRPC/MQTT; here
one codec (pickle protocol 5, zero-copy buffers for large arrays) serves
every transport, and device arrays are converted to numpy at the transport
boundary — device->host transfer happens exactly once, at send.
"""

from __future__ import annotations

import dataclasses
import pickle
from typing import Any

import jax
import numpy as np


# Well-known message types (reference message_define.py files use small int
# enums per algorithm; we reserve a shared space for the built-in flows).
MSG_TYPE_S2C_INIT = 1
MSG_TYPE_S2C_SYNC_MODEL = 2
MSG_TYPE_C2S_RESULT = 3
MSG_TYPE_FINISH = 4

# Well-known payload keys (reference Message.MSG_ARG_KEY_*)
KEY_MODEL_PARAMS = "model_params"
KEY_NUM_SAMPLES = "num_samples"
KEY_CLIENT_INDEX = "client_index"
KEY_ROUND = "round_idx"


@dataclasses.dataclass
class Message:
    msg_type: int
    sender: int
    receiver: int
    payload: dict[str, Any] = dataclasses.field(default_factory=dict)

    def get(self, key: str, default=None):
        return self.payload.get(key, default)

    def add(self, key: str, value: Any) -> "Message":
        self.payload[key] = value
        return self

    def host_copy(self) -> "Message":
        """Convert any device arrays in the payload to numpy (one D2H)."""
        payload = jax.tree.map(
            lambda v: np.asarray(v) if isinstance(v, jax.Array) else v,
            self.payload,
        )
        return Message(self.msg_type, self.sender, self.receiver, payload)

    def encode(self) -> bytes:
        return pickle.dumps(self.host_copy(), protocol=5)

    @staticmethod
    def decode(data: bytes) -> "Message":
        msg = pickle.loads(data)
        assert isinstance(msg, Message)
        return msg

"""Message envelope for the cross-process runtime.

Mirrors the reference's ``Message``
(``fedml_core/distributed/communication/message.py:5-81``): a typed envelope
``(msg_type, sender, receiver)`` plus arbitrary params (model pytrees ride
as numpy arrays). The reference pickles messages over MPI
(``mpi_send_thread.py:22-27``) and JSON-encodes them over gRPC/MQTT; here
one codec (pickle protocol 5, zero-copy buffers for large arrays) serves
every transport, and device arrays are converted to numpy at the transport
boundary — device->host transfer happens exactly once, at send.
"""

from __future__ import annotations

import dataclasses
import pickle
import struct
from typing import Any

import jax
import numpy as np

_WIRE_MAGIC = b"FMG1"
_HDR = struct.Struct("<Q")


@dataclasses.dataclass(frozen=True)
class _TensorRef:
    """Placeholder for a tensor lifted out of a pickled payload into the
    native tensor frame."""

    idx: int


# Well-known message types (reference message_define.py files use small int
# enums per algorithm; we reserve a shared space for the built-in flows).
MSG_TYPE_S2C_INIT = 1
MSG_TYPE_S2C_SYNC_MODEL = 2
MSG_TYPE_C2S_RESULT = 3
MSG_TYPE_FINISH = 4

# Well-known payload keys (reference Message.MSG_ARG_KEY_*)
KEY_MODEL_PARAMS = "model_params"
KEY_NUM_SAMPLES = "num_samples"
KEY_CLIENT_INDEX = "client_index"
KEY_ROUND = "round_idx"


@dataclasses.dataclass
class Message:
    msg_type: int
    sender: int
    receiver: int
    payload: dict[str, Any] = dataclasses.field(default_factory=dict)

    def get(self, key: str, default=None):
        return self.payload.get(key, default)

    def add(self, key: str, value: Any) -> "Message":
        self.payload[key] = value
        return self

    def host_copy(self) -> "Message":
        """Convert any device arrays in the payload to numpy (one D2H)."""
        payload = jax.tree.map(
            lambda v: np.asarray(v) if isinstance(v, jax.Array) else v,
            self.payload,
        )
        return Message(self.msg_type, self.sender, self.receiver, payload)

    def encode(self) -> bytes:
        """Wire format: bulk tensors ride the native C++ tensor-frame codec
        (:mod:`fedml_tpu.native.codec` — multithreaded gather memcpy, CRC);
        everything else (structure + scalars) is pickled. Replaces the
        reference's whole-payload pickle (``mpi_send_thread.py:22-27``).
        """
        from fedml_tpu.native.codec import TensorCodec

        host = self.host_copy()
        arrays: list[np.ndarray] = []

        from fedml_tpu.native.codec import codec_supports

        def strip(v):
            if (
                isinstance(v, np.ndarray)
                and v.nbytes >= 256
                and codec_supports(v.dtype)
            ):
                arrays.append(v)
                return _TensorRef(len(arrays) - 1)
            return v  # small / exotic-dtype values ride the pickle side

        payload = jax.tree.map(strip, host.payload)
        meta = pickle.dumps(
            Message(self.msg_type, self.sender, self.receiver, payload),
            protocol=5,
        )
        frame = TensorCodec().pack(arrays) if arrays else b""
        return _WIRE_MAGIC + _HDR.pack(len(meta)) + meta + frame

    @staticmethod
    def decode(data: bytes) -> "Message":
        if not data.startswith(_WIRE_MAGIC):  # legacy plain-pickle frame
            msg = pickle.loads(data)
            assert isinstance(msg, Message)
            return msg
        off = len(_WIRE_MAGIC)
        (meta_len,) = _HDR.unpack_from(data, off)
        off += _HDR.size
        msg = pickle.loads(data[off:off + meta_len])
        assert isinstance(msg, Message)
        frame = data[off + meta_len:]
        if frame:
            from fedml_tpu.native.codec import TensorCodec

            # copy: consumers own (writable) arrays that don't pin the
            # whole wire frame alive, matching the old pickle semantics
            arrays = [a.copy() for a in TensorCodec().unpack(frame)]
            msg.payload = jax.tree.map(
                lambda v: arrays[v.idx] if isinstance(v, _TensorRef) else v,
                msg.payload,
                is_leaf=lambda v: isinstance(v, _TensorRef),
            )
        return msg

"""Message envelope for the cross-process runtime.

Mirrors the reference's ``Message``
(``fedml_core/distributed/communication/message.py:5-81``): a typed envelope
``(msg_type, sender, receiver)`` plus arbitrary params (model pytrees ride
as numpy arrays). The reference pickles messages over MPI
(``mpi_send_thread.py:22-27``) and JSON-encodes them over gRPC/MQTT; here
one codec (pickle protocol 5, zero-copy buffers for large arrays) serves
every transport, and device arrays are converted to numpy at the transport
boundary — device->host transfer happens exactly once, at send.
"""

from __future__ import annotations

import dataclasses
import pickle
import struct
from typing import Any

import jax
import numpy as np

_WIRE_MAGIC = b"FMG1"
_HDR = struct.Struct("<Q")


@dataclasses.dataclass(frozen=True)
class _TensorRef:
    """Placeholder for a tensor lifted out of a pickled payload into the
    native tensor frame."""

    idx: int


# Well-known message types (reference message_define.py files use small int
# enums per algorithm; we reserve a shared space for the built-in flows).
# Type 1 was MSG_TYPE_S2C_INIT, minted mirroring the reference's init
# broadcast but never sent nor handled by any flow here — the fedlint
# message-edge rule flagged the dead edge and it was removed; the
# integer stays reserved so a future type cannot collide with frames
# from an old build.
MSG_TYPE_S2C_SYNC_MODEL = 2
MSG_TYPE_C2S_RESULT = 3
MSG_TYPE_FINISH = 4
# Deployment readiness handshake (reference analog: the cross-silo client
# managers' register/CONNECTION-ready flow before round 0): a client
# process announces its receive endpoint is live; the server starts round
# 0 once all world_size-1 clients have announced.
MSG_TYPE_C2S_READY = 5
# Server's reply to each READY: proves the control channel is live in BOTH
# directions without waiting for the first work message (a later-rank
# SplitNN client may legitimately sit idle for minutes while predecessors
# train — liveness must not be inferred from work traffic).
MSG_TYPE_S2C_ACK = 6
# Liveness beacon (either direction). Carries no payload; the receiving
# manager's per-peer last-seen table is refreshed by ANY inbound message
# at transport-deliver time, so heartbeats only matter on otherwise-idle
# links. See docs/FAULT_TOLERANCE.md.
MSG_TYPE_HEARTBEAT = 7
# Recovery handshake (docs/FAULT_TOLERANCE.md "Recovery"): a (re)started
# client announces itself with JOIN. Before the run is underway JOIN
# counts toward the readiness barrier exactly like READY; once underway
# it is a REJOIN — the server re-adds the rank to the live set and
# replies WELCOME carrying the current round index + global model +
# client assignment, so the rank resumes work mid-run instead of being
# excluded until the end of the run.
MSG_TYPE_C2S_JOIN = 8
MSG_TYPE_S2C_WELCOME = 9
# Elastic membership (docs/FAULT_TOLERANCE.md "Elastic membership"): a
# client announces a GRACEFUL departure — distinct from a crash (no
# restart budget spent, no dead-peer flight dump, no quarantine
# suspicion). The server's membership ledger marks the rank LEFT; it may
# JOIN again later. JOIN doubles as the mid-run ADMISSION message for
# ranks beyond the launch world_size (the ledger assigns them a stable
# client id and they enter the cohort at the next round boundary).
MSG_TYPE_C2S_LEAVE = 10
# Multi-tier aggregation (core/tier.py, docs/FAULT_TOLERANCE.md "Async +
# tiered worlds"): a LEAF aggregator forwards one partial reduction
# ``[sum, n, count]`` upstream per flush — the root folds one row per
# leaf instead of one per client, so the root's inbox scales with the
# tree's fan-in, not the cohort. Rides the sealed wire frames like every
# other message; validated at the root's receive edge
# (tier.validate_partial).
MSG_TYPE_L2R_PARTIAL = 11

#: symbolic names for the per-type wire-byte counters
#: (``transport.bytes_by_type.<name>``, docs/OBSERVABILITY.md): byte
#: reduction claims must be attributable to the DELTA payloads
#: (``c2s_result``) specifically — heartbeats/ACKs ride the same sealed
#: frames and would otherwise pollute the measurement.
MSG_TYPE_NAMES = {
    MSG_TYPE_S2C_SYNC_MODEL: "s2c_sync_model",
    MSG_TYPE_C2S_RESULT: "c2s_result",
    MSG_TYPE_FINISH: "finish",
    MSG_TYPE_C2S_READY: "c2s_ready",
    MSG_TYPE_S2C_ACK: "s2c_ack",
    MSG_TYPE_HEARTBEAT: "heartbeat",
    MSG_TYPE_C2S_JOIN: "c2s_join",
    MSG_TYPE_S2C_WELCOME: "s2c_welcome",
    MSG_TYPE_C2S_LEAVE: "c2s_leave",
    MSG_TYPE_L2R_PARTIAL: "l2r_partial",
}


def msg_type_name(msg_type: int) -> str:
    """Symbolic name for a message type (algorithm-specific types fall
    back to their integer)."""
    return MSG_TYPE_NAMES.get(msg_type, str(msg_type))


# Well-known payload keys (reference Message.MSG_ARG_KEY_*)
KEY_MODEL_PARAMS = "model_params"
KEY_NUM_SAMPLES = "num_samples"
KEY_CLIENT_INDEX = "client_index"
KEY_ROUND = "round_idx"
# typed compressed-delta payload (core/compress.py): replaces
# KEY_MODEL_PARAMS on C2S_RESULT messages when the wire codec is on —
# {"codec": method, "payload": <payload pytree>}. The dense path never
# adds the key, so --compress none stays byte-identical on the wire.
KEY_COMPRESSED = "compressed_delta"


@dataclasses.dataclass
class Message:
    msg_type: int
    sender: int
    receiver: int
    payload: dict[str, Any] = dataclasses.field(default_factory=dict)
    # cross-process trace context ``(trace_id, span_id)`` — stamped by
    # the sending Manager when tracing is enabled (None otherwise: the
    # disabled path adds no per-message allocation), carried through the
    # wire codec so a send on rank 0 correlates with its deliver on
    # rank 1 (docs/OBSERVABILITY.md)
    trace: tuple[str, str] | None = None

    def get(self, key: str, default=None):
        return self.payload.get(key, default)

    def add(self, key: str, value: Any) -> "Message":
        self.payload[key] = value
        return self

    def host_copy(self) -> "Message":
        """Convert any device arrays in the payload to numpy (one D2H)."""
        payload = jax.tree.map(
            lambda v: np.asarray(v) if isinstance(v, jax.Array) else v,
            self.payload,
        )
        return Message(self.msg_type, self.sender, self.receiver, payload,
                       trace=self.trace)

    def encode_parts(self) -> tuple[bytes, bytes]:
        """Split encoding: ``(meta, tensor_frame)``. Bulk tensors ride the
        native C++ tensor-frame codec (:mod:`fedml_tpu.native.codec` —
        multithreaded gather memcpy, CRC); everything else (structure +
        scalars) is pickled into ``meta`` with :class:`_TensorRef`
        placeholders. Transports choose the region order on the wire:
        :meth:`encode` puts meta first; the TRPC-class transport ships the
        tensor frame first (tensor-native framing)."""
        from fedml_tpu.native.codec import TensorCodec, codec_supports

        host = self.host_copy()
        arrays: list[np.ndarray] = []

        def strip(v):
            if (
                isinstance(v, np.ndarray)
                and v.nbytes >= 256
                and codec_supports(v.dtype)
            ):
                arrays.append(v)
                return _TensorRef(len(arrays) - 1)
            return v  # small / exotic-dtype values ride the pickle side

        payload = jax.tree.map(strip, host.payload)
        meta = pickle.dumps(
            Message(self.msg_type, self.sender, self.receiver, payload,
                    trace=self.trace),
            protocol=5,
        )
        frame = TensorCodec().pack(arrays) if arrays else b""
        return meta, frame

    @staticmethod
    def from_parts(meta: bytes, frame) -> "Message":
        """Inverse of :meth:`encode_parts`. ``frame`` may be any buffer
        (bytes/bytearray/memoryview) — the codec reads it zero-copy and
        the arrays are copied out so they don't pin the wire buffer."""
        msg = pickle.loads(meta)
        assert isinstance(msg, Message)
        if frame:
            from fedml_tpu.native.codec import TensorCodec

            arrays = [a.copy() for a in TensorCodec().unpack(frame)]
            msg.payload = jax.tree.map(
                lambda v: arrays[v.idx] if isinstance(v, _TensorRef) else v,
                msg.payload,
                is_leaf=lambda v: isinstance(v, _TensorRef),
            )
        return msg

    def encode(self) -> bytes:
        """One-buffer wire format: ``MAGIC || meta_len || meta || frame``.
        Replaces the reference's whole-payload pickle
        (``mpi_send_thread.py:22-27``)."""
        meta, frame = self.encode_parts()
        return _WIRE_MAGIC + _HDR.pack(len(meta)) + meta + frame

    @staticmethod
    def decode(data) -> "Message":
        """``data`` may be any buffer (bytes/bytearray/memoryview) —
        the sealed transports hand over a zero-copy payload view."""
        view = memoryview(data)
        if bytes(view[:len(_WIRE_MAGIC)]) != _WIRE_MAGIC:
            # legacy plain-pickle frame
            msg = pickle.loads(data)
            assert isinstance(msg, Message)
            return msg
        off = len(_WIRE_MAGIC)
        (meta_len,) = _HDR.unpack_from(view, off)
        off += _HDR.size
        return Message.from_parts(
            view[off:off + meta_len], view[off + meta_len:]
        )

"""Pytree math for federated aggregation.

The reference aggregates ``state_dict``s in a Python loop over keys
(``fedml_api/distributed/fedavg/FedAVGAggregator.py:59-88``). Here aggregation
is a handful of ``jax.tree_util`` one-liners that XLA fuses into a single
bandwidth-bound pass — the natural TPU formulation (weighted FedAvg ==
``psum(n_k * w_k) / psum(n_k)`` when sharded over a mesh).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_dot(a: Pytree, b: Pytree) -> jax.Array:
    parts = jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b)
    return jax.tree.reduce(jnp.add, parts, jnp.asarray(0.0))


def tree_l2_norm(tree: Pytree) -> jax.Array:
    """Global L2 norm over every leaf (reference ``vectorize_weight`` + norm,
    ``fedml_core/robustness/robust_aggregation.py:4-13,38-49``)."""
    sq = jax.tree.map(lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))), tree)
    return jnp.sqrt(jax.tree.reduce(jnp.add, sq, jnp.asarray(0.0)))


def tree_vectorize(tree: Pytree) -> jax.Array:
    """Flatten a pytree into a single 1-D vector (reference
    ``vectorize_weight``, ``robust_aggregation.py:4-13``)."""
    leaves = jax.tree.leaves(tree)
    return jnp.concatenate([jnp.ravel(l) for l in leaves]) if leaves else jnp.zeros((0,))


def tree_unvectorize(vec: jax.Array, like: Pytree) -> Pytree:
    """Inverse of :func:`tree_vectorize` given a template pytree."""
    leaves, treedef = jax.tree.flatten(like)
    out, off = [], 0
    for l in leaves:
        n = l.size
        out.append(jnp.reshape(vec[off : off + n], l.shape).astype(l.dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


def tree_weighted_mean(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Weighted mean over the leading (client) axis of a stacked pytree.

    ``stacked`` leaves have shape ``[C, ...]``; ``weights`` has shape ``[C]``
    (sample counts ``n_k``). This is the core FedAvg aggregation
    (reference ``FedAVGAggregator.aggregate``, ``FedAVGAggregator.py:59-88``).
    """
    w = weights / jnp.maximum(jnp.sum(weights), 1e-12)

    def leaf_mean(x):
        wb = w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(jnp.float32)
        return jnp.sum(x.astype(jnp.float32) * wb, axis=0).astype(x.dtype)

    return jax.tree.map(leaf_mean, stacked)


def tree_weighted_sum(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Weighted sum over the leading axis (pair with a ``psum`` of the weight
    total for mesh-sharded aggregation)."""

    def leaf_sum(x):
        wb = weights.reshape((-1,) + (1,) * (x.ndim - 1)).astype(jnp.float32)
        return jnp.sum(x.astype(jnp.float32) * wb, axis=0)

    return jax.tree.map(leaf_sum, stacked)


def tree_stack(trees: list[Pytree]) -> Pytree:
    """Stack a python list of identically-shaped pytrees along a new axis 0."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(stacked: Pytree, n: int) -> list[Pytree]:
    return [jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n)]


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_size(tree: Pytree) -> int:
    """Total number of scalar parameters."""
    return sum(l.size for l in jax.tree.leaves(tree))

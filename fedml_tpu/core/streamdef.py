"""Streaming Byzantine defenses: selection/quantile rules for the bulk
engine that never materialize the ``[C, D]`` stacked-delta matrix.

The stacked defenses (core/robust.py) are exact but need the whole
cohort's deltas resident at once — precisely the O(C·model) buffer the
bulk engine (core/bulk.py) exists to avoid. This module re-expresses
each rule as a TWO-PASS streaming computation over the same block scan
that folds :class:`~fedml_tpu.core.bulk.RoundPartials`: pass 1 folds a
low-dimensional SKETCH of the cohort, the defense decision is made
in-program from the sketch, and pass 2 folds the decided aggregate —
both passes recompute the deterministic local updates (the recompute
idiom; the 2x step compute IS the measured
``defense_stream_overhead_ms``), so round working memory stays
O(block + sketch).

Two sketch families, with HONEST accuracy contracts (pinned in
``tests/test_streamdef.py``, documented in docs/PERFORMANCE.md):

- **coordinate-quantile sketch** (``median`` / ``trimmed_mean``):
  pass 1 folds exact per-coordinate moments (sum, sum-of-squares,
  valid count — additive across blocks); pass 2 folds a per-coordinate
  histogram of ``HIST_BINS`` bins spanning ``mu ± HIST_SPAN·sd``; the
  quantile is interpolated from the histogram CDF. Sketch memory is
  O(HIST_BINS · D), independent of the cohort; the estimate is within
  ONE BIN WIDTH (``2·HIST_SPAN·sd / HIST_BINS`` per coordinate) of the
  stacked order statistic, degrading to exact when a coordinate's
  spread is zero.
- **random-projection sketch** (``krum`` / ``multikrum`` /
  ``fltrust``): pass 1 folds each client's seeded random projection
  (``[slots, PROJ_DIM]``, the Johnson–Lindenstrauss sketch — the
  projection matrix is regenerated per block from the round key, so it
  never persists), its TRUE delta norm, and its weight; selection runs
  the PR 7 ``pairwise_sq_dists_rows`` row-blocked-gram idiom on the
  projected rows; pass 2 folds the selected/trust-weighted sum of the
  true full-D deltas. Krum/multi-Krum reproduce the stacked selection
  whenever the projected distance ordering preserves the decision
  margin (near-certain for the large separations an actual attack
  produces; a coin-flip near ties) — and GIVEN the same selection the
  aggregate matches the stacked rule to f32 accumulation order.
  FLTrust's reference is the coordinate-median of the PROJECTED rows
  and its norm-match target is the median cohort norm (the stacked
  rule norm-matches to the full-D median delta's norm); when total
  trust is zero the streamed rule degrades to a ZERO aggregate where
  the stacked rule returns the reference delta itself — there is no
  full-D reference to return at O(sketch) memory.

The sketches fold through the same ``lax.scan`` carry as
``RoundPartials``; eligibility semantics match the stacked reducer
exactly (quantile rules vote over LIVE rows — a screened client votes
its healed zero delta, as ``server_update`` passes ``valid=live``;
selection rules require ``live & weight > 0``).

Telemetry (docs/OBSERVABILITY.md): ``defense.sketch_bins``,
``defense.sketch_proj_dim``, ``defense.sketch_mb`` gauges at bulk
dispatch.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from fedml_tpu.core import robust
from fedml_tpu.core import telemetry
from fedml_tpu.core import tree as T

Pytree = Any

#: random-projection dimension for the krum/multikrum/fltrust sketch.
PROJ_DIM = 256
#: per-coordinate histogram bins for the median/trimmed_mean sketch.
HIST_BINS = 128
#: histogram half-range in per-coordinate standard deviations.
HIST_SPAN = 4.0
_PROJ_SALT = 0x534B5348  # "SKSH"

#: rules served by the coordinate-quantile sketch.
QUANTILE_METHODS = ("median", "trimmed_mean")
#: rules served by the random-projection sketch.
PROJECTION_METHODS = ("krum", "multikrum", "fltrust")
STREAM_METHODS = QUANTILE_METHODS + PROJECTION_METHODS


class CoordMoments(NamedTuple):
    """Pass-1 carry of the quantile sketch: exact per-coordinate
    moments over the live rows (additive across blocks)."""

    sum_x: jax.Array   # [D] f32
    sum_sq: jax.Array  # [D] f32
    count: jax.Array   # scalar f32 — live rows (screened rows included
    #                    with their healed zero delta, like stacked)


class ProjSketch(NamedTuple):
    """Pass-1 carry of the projection sketch: per-SLOT rows, each block
    scattering its own slots into zeros (disjoint, so the scan's
    carry-add unions the blocks)."""

    proj: jax.Array    # [slots, PROJ_DIM] f32 projected deltas
    norm: jax.Array    # [slots] f32 true delta L2 norms
    weight: jax.Array  # [slots] f32 aggregation weights (n_k)
    live: jax.Array    # [slots] f32 live mask


# ---------------------------------------------------------------------------
# shared: flatten a block of stacked deltas
# ---------------------------------------------------------------------------


def flatten_rows(stacked_deltas: Pytree) -> jax.Array:
    """``[B, D]`` f32 block of flattened deltas (one block's slice of
    what :func:`robust.flatten_clients` builds for the whole cohort)."""
    return robust.flatten_clients(stacked_deltas)


# ---------------------------------------------------------------------------
# coordinate-quantile sketch (median / trimmed_mean)
# ---------------------------------------------------------------------------


def fold_moments(flat: jax.Array, live: jax.Array) -> CoordMoments:
    """One block's moment contribution; ``live`` is ``[B]`` f32."""
    v = live[:, None]
    return CoordMoments(
        sum_x=jnp.sum(flat * v, axis=0),
        sum_sq=jnp.sum(flat * flat * v, axis=0),
        count=jnp.sum(live),
    )


def hist_edges(mom: CoordMoments,
               span: float = HIST_SPAN) -> tuple[jax.Array, jax.Array]:
    """Per-coordinate histogram geometry ``(lo, width)`` from the
    pass-1 moments: bins span ``mu ± span·sd``. A zero-spread
    coordinate gets ``width == 0`` — every estimate below then
    collapses exactly to ``lo == mu``."""
    n = jnp.maximum(mom.count, 1.0)
    mu = mom.sum_x / n
    var = jnp.maximum(mom.sum_sq / n - mu * mu, 0.0)
    sd = jnp.sqrt(var)
    lo = mu - span * sd
    width = (2.0 * span * sd) / HIST_BINS
    return lo, width


def fold_hist(flat: jax.Array, live: jax.Array, lo: jax.Array,
              width: jax.Array) -> jax.Array:
    """One block's ``[HIST_BINS, D]`` histogram contribution, built as
    a FLAT scatter-add (``bin·D + coordinate``) — never the
    ``[B, HIST_BINS, D]`` one-hot blowup. Out-of-span values clip into
    the edge bins (they are beyond ``span`` sigmas; the quantile bands
    the defenses read live in the interior)."""
    d = flat.shape[1]
    safe_w = jnp.where(width > 0, width, 1.0)
    b = jnp.clip(
        jnp.floor((flat - lo[None, :]) / safe_w[None, :]),
        0, HIST_BINS - 1,
    ).astype(jnp.int32)
    flat_idx = b * d + jnp.arange(d, dtype=jnp.int32)[None, :]
    hist = jnp.zeros((HIST_BINS * d,), jnp.float32)
    hist = hist.at[flat_idx.ravel()].add(
        jnp.broadcast_to(live[:, None], flat.shape).ravel()
    )
    return hist.reshape(HIST_BINS, d)


def median_from_hist(hist: jax.Array, lo: jax.Array, width: jax.Array,
                     count: jax.Array) -> jax.Array:
    """``[D]`` grouped-median: linear CDF interpolation at ``count/2``
    inside the bin where the cumulative mass crosses it. Within one bin
    width of the stacked order-statistic median; exact (``== mu``) for
    zero-spread coordinates."""
    cum = jnp.cumsum(hist, axis=0)  # [BINS, D]
    target = jnp.maximum(count, 1.0) / 2.0
    b = jnp.argmax(cum >= target, axis=0)  # [D] first crossing bin
    cum_before = jnp.where(
        b > 0,
        jnp.take_along_axis(cum, jnp.maximum(b - 1, 0)[None, :],
                            axis=0)[0],
        0.0,
    )
    mass = jnp.take_along_axis(hist, b[None, :], axis=0)[0]
    frac = (target - cum_before) / jnp.maximum(mass, 1e-12)
    return lo + (b.astype(jnp.float32) + frac) * width


def trim_table(trim_frac: float, c_max: int) -> jax.Array:
    """Host-side trim-count table over every possible live count —
    the SAME Python-float formula as :func:`robust.trimmed_mean` (so
    the streamed and stacked rules trim identical row counts)."""
    return jnp.asarray(
        [max(0, min(int(c * trim_frac), (c - 1) // 2))
         for c in range(c_max + 1)], jnp.int32,
    )


def trimmed_mean_from_hist(hist: jax.Array, lo: jax.Array,
                           width: jax.Array, count: jax.Array,
                           ks: jax.Array) -> jax.Array:
    """``[D]`` trimmed mean from the histogram: per coordinate, the
    mass of the rank band ``[k, n-k)`` — each bin contributes its
    clamped overlap with the band, valued at the bin CENTER — divided
    by ``n - 2k``. Within one bin width of the stacked rule (each
    surviving value is off by at most half a bin from its center, plus
    band-edge attribution of at most one bin)."""
    n = jnp.maximum(count, 1.0)
    k = ks[jnp.clip(count.astype(jnp.int32), 0, ks.shape[0] - 1)]
    lo_rank = k.astype(jnp.float32)
    hi_rank = n - lo_rank
    cum = jnp.cumsum(hist, axis=0)  # [BINS, D]
    cum_prev = jnp.concatenate(
        [jnp.zeros((1,) + cum.shape[1:], cum.dtype), cum[:-1]], axis=0
    )
    band = jnp.clip(
        jnp.minimum(cum, hi_rank) - jnp.maximum(cum_prev, lo_rank),
        0.0, None,
    )
    centers = (
        lo[None, :]
        + (jnp.arange(HIST_BINS, dtype=jnp.float32)[:, None] + 0.5)
        * width[None, :]
    )
    return jnp.sum(band * centers, axis=0) / jnp.maximum(
        hi_rank - lo_rank, 1.0
    )


# ---------------------------------------------------------------------------
# random-projection sketch (krum / multikrum / fltrust)
# ---------------------------------------------------------------------------


def project_rows(stacked_deltas: Pytree, rkey: jax.Array,
                 proj_dim: int = PROJ_DIM) -> jax.Array:
    """``[B, P]`` seeded Johnson–Lindenstrauss projection of each row's
    flattened delta, scaled ``1/sqrt(P)`` so squared distances are
    preserved in expectation. The per-leaf ``[d_leaf, P]`` Gaussian
    blocks derive from ``(round key, salt, leaf index)`` — identical
    across blocks and across the two passes of one round, never stored
    (transient memory O(largest leaf · P))."""
    base = jax.random.fold_in(rkey, _PROJ_SALT)
    leaves = jax.tree.leaves(stacked_deltas)
    b = leaves[0].shape[0]
    acc = jnp.zeros((b, proj_dim), jnp.float32)
    for i, leaf in enumerate(leaves):
        flat = leaf.reshape(b, -1).astype(jnp.float32)
        g = jax.random.normal(
            jax.random.fold_in(base, i),
            (flat.shape[1], proj_dim), jnp.float32,
        )
        acc = acc + flat @ g
    return acc / jnp.sqrt(float(proj_dim))


def fold_proj(stacked_deltas: Pytree, n_k: jax.Array, live: jax.Array,
              positions: jax.Array, n_slots: int,
              rkey: jax.Array) -> ProjSketch:
    """One block's slot-scattered sketch rows: zero everywhere except
    this block's ``positions`` (blocks partition the slot range, so the
    scan's carry-add assembles the full per-slot arrays collision-
    free)."""
    proj = project_rows(stacked_deltas, rkey)
    norms = jax.vmap(T.tree_l2_norm)(stacked_deltas).astype(jnp.float32)

    def scatter(vals, shape):
        return jnp.zeros(shape, jnp.float32).at[positions].set(
            vals.astype(jnp.float32)
        )

    return ProjSketch(
        proj=scatter(proj, (n_slots, proj.shape[1])),
        norm=scatter(norms, (n_slots,)),
        weight=scatter(n_k, (n_slots,)),
        live=scatter(live, (n_slots,)),
    )


def selection_weights(method: str, sk: ProjSketch, num_adversaries: int,
                      multikrum_m: int
                      ) -> tuple[jax.Array, jax.Array]:
    """Per-slot aggregation weights ``(w, den)`` decided from the
    pass-1 sketch; pass 2 folds ``sum_i w_i·delta_i`` and the round
    aggregate is ``wsum / den``. Eligibility is ``live & weight > 0``
    — the stacked reducer's ``gw = where(valid, weights, 0); w > 0``
    semantics."""
    slots = sk.proj.shape[0]
    valid = (sk.live > 0) & (sk.weight > 0)
    n_valid = jnp.sum(valid.astype(jnp.int32))
    if method in ("krum", "multikrum"):
        rows = jnp.arange(slots, dtype=jnp.int32)
        d2 = robust.pairwise_sq_dists_rows(sk.proj, rows, sk.proj)
        scores = robust.krum_scores_rows(
            d2, rows, num_adversaries, valid, n_valid
        )
        if method == "krum":
            # the selected client's delta IS the aggregate: a one-hot
            # weight makes pass 2's weighted sum reproduce it exactly
            # (0·x is exact for the finite, screened-healed rows)
            w = jax.nn.one_hot(jnp.argmin(scores), slots,
                               dtype=jnp.float32)
            return w, jnp.asarray(1.0, jnp.float32)
        m_dyn = (
            jnp.asarray(multikrum_m) if multikrum_m > 0
            else jnp.maximum(1, n_valid - num_adversaries)
        )
        m_dyn = jnp.clip(m_dyn, 1, jnp.maximum(n_valid, 1))
        order = jnp.argsort(scores)
        rank = jnp.zeros((slots,), jnp.int32).at[order].set(
            jnp.arange(slots, dtype=jnp.int32)
        )
        mask = (rank < m_dyn) & valid
        w = jnp.where(mask, sk.weight, 0.0)
        return w, jnp.maximum(jnp.sum(w), 1e-12)
    if method == "fltrust":
        eps = 1e-12
        vf = valid.astype(jnp.float32)
        # reference = coordinate-median of the PROJECTED valid rows;
        # norm-match target = the median TRUE cohort norm (documented
        # divergence from the stacked rule's full-D reference)
        ref = robust.coordinate_median(sk.proj, valid)  # [P]
        rn_p = jnp.sqrt(jnp.sum(ref * ref))
        xn_p = jnp.sqrt(jnp.sum(sk.proj * sk.proj, axis=1))
        cos = (sk.proj @ ref) / jnp.maximum(xn_p * rn_p, eps)
        trust = jax.nn.relu(cos) * vf
        rn = robust.coordinate_median(sk.norm, valid)  # scalar
        norm_match = rn / jnp.maximum(sk.norm, eps)
        tsum = jnp.sum(trust)
        w = (trust / jnp.maximum(tsum, eps)) * norm_match
        # zero total trust degrades to a ZERO aggregate (the stacked
        # rule returns its full-D reference — unavailable at O(sketch))
        w = jnp.where(tsum > 0, w, 0.0)
        return w, jnp.asarray(1.0, jnp.float32)
    raise ValueError(f"not a streaming selection method: {method!r}")


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


def sketch_mb(method: str, flat_dim: int, n_slots: int) -> float:
    """Resident sketch-carry size (the O(sketch) the round pays instead
    of O(C·D))."""
    if method in QUANTILE_METHODS:
        return 4.0 * flat_dim * (HIST_BINS + 2) / 1e6
    return 4.0 * n_slots * (PROJ_DIM + 3) / 1e6


def note_defense(method: str, flat_dim: int, n_slots: int) -> None:
    """Gauges at bulk dispatch (docs/OBSERVABILITY.md vocabulary)."""
    m = telemetry.METRICS
    if not m.enabled:
        return
    m.gauge("defense.sketch_bins",
            float(HIST_BINS if method in QUANTILE_METHODS else 0))
    m.gauge("defense.sketch_proj_dim",
            float(PROJ_DIM if method in PROJECTION_METHODS else 0))
    m.gauge("defense.sketch_mb", sketch_mb(method, flat_dim, n_slots))

"""Version-tolerant jax API shims.

``shard_map`` moved from ``jax.experimental.shard_map`` to the ``jax``
top level, and its replication-check kwarg was renamed ``check_rep`` ->
``check_vma`` en route. Import sites across the repo (parallel runtime,
ops kernels, tests) go through this one shim so the supported jax range
is decided in exactly one place.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, kwarg is check_vma
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental module, kwarg is check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern signature on every supported jax."""
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )

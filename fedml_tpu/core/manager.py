"""Actor base classes: handler-registry managers over a transport.

Reference: ``ClientManager`` / ``ServerManager``
(``fedml_core/distributed/client/client_manager.py:21``,
``server/server_manager.py:15``): construct a backend by name, register as
Observer, dispatch inbound messages by ``msg_type`` to registered handlers.
``finish()`` there is ``MPI.COMM_WORLD.Abort()`` (``client_manager.py:92-93``);
here it's a cooperative FINISH broadcast + transport stop.
"""

from __future__ import annotations

from typing import Callable

from fedml_tpu.core.message import MSG_TYPE_FINISH, Message
from fedml_tpu.core.transport.base import BaseTransport

Handler = Callable[[Message], None]


def create_transport(
    backend: str,
    rank: int,
    *,
    hub=None,
    ip_config: dict[int, tuple[str, int]] | None = None,
    bus=None,
    store=None,
    size: int | None = None,
) -> BaseTransport:
    """Backend dispatch by name (reference ``client_manager.py:28-50``:
    backend in {MPI, MQTT, MQTT_S3, GRPC, TRPC}; here {LOOPBACK, TCP,
    GRPC, TRPC, PUBSUB, PUBSUB_BLOB} — PUBSUB is the MQTT-shaped topic bus,
    PUBSUB_BLOB adds the S3-shaped control/data-plane split)."""
    backend = backend.upper()
    if backend == "LOOPBACK":
        assert hub is not None, "loopback needs a shared LoopbackHub"
        return hub.create(rank)
    if backend == "TCP":
        from fedml_tpu.core.transport.tcp import TcpTransport

        assert ip_config is not None
        return TcpTransport(rank, ip_config)
    if backend == "GRPC":
        from fedml_tpu.core.transport.grpc_transport import GrpcTransport

        assert ip_config is not None
        return GrpcTransport(rank, ip_config)
    if backend in ("TRPC", "TENSOR_RPC"):
        from fedml_tpu.core.transport.tensor_rpc import TensorRpcTransport

        assert ip_config is not None
        return TensorRpcTransport(rank, ip_config)
    if backend in ("PUBSUB", "MQTT"):
        from fedml_tpu.core.transport.pubsub import PubSubTransport

        assert bus is not None and size is not None
        return PubSubTransport(rank, bus, size)
    if backend in ("PUBSUB_BLOB", "MQTT_S3"):
        from fedml_tpu.core.transport.pubsub import PubSubBlobTransport

        assert bus is not None and store is not None and size is not None
        return PubSubBlobTransport(rank, bus, store, size)
    raise ValueError(f"unknown backend: {backend}")


class Manager:
    """Common actor machinery (both sides)."""

    def __init__(self, rank: int, size: int, transport: BaseTransport):
        self.rank = rank
        self.size = size
        self.transport = transport
        self._handlers: dict[int, Handler] = {}
        transport.add_observer(self)
        self.register_message_receive_handler(
            MSG_TYPE_FINISH, lambda msg: self.finish()
        )

    def register_message_receive_handler(
        self, msg_type: int, handler: Handler
    ) -> None:
        self._handlers[msg_type] = handler

    def receive_message(self, msg_type: int, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            raise KeyError(
                f"rank {self.rank}: no handler for msg_type {msg_type}"
            )
        handler(msg)

    def send_message(self, msg: Message) -> None:
        self.transport.send_message(msg)

    def run(self) -> None:
        self.transport.handle_receive_message()

    def finish(self) -> None:
        self.transport.stop()


class ServerManager(Manager):
    """Rank-0 actor (reference ``server_manager.py:15``)."""

    def broadcast(self, msg_type: int, payload_fn) -> None:
        """Send ``Message(msg_type, 0, r, payload_fn(r))`` to every client
        rank 1..size-1."""
        for r in range(1, self.size):
            self.send_message(Message(msg_type, self.rank, r, payload_fn(r)))

    def finish_all(self) -> None:
        for r in range(1, self.size):
            self.send_message(Message(MSG_TYPE_FINISH, self.rank, r, {}))
        self.finish()


class ClientManager(Manager):
    """Rank>=1 actor (reference ``client_manager.py:21``)."""

"""Actor base classes: handler-registry managers over a transport.

Reference: ``ClientManager`` / ``ServerManager``
(``fedml_core/distributed/client/client_manager.py:21``,
``server/server_manager.py:15``): construct a backend by name, register as
Observer, dispatch inbound messages by ``msg_type`` to registered handlers.
``finish()`` there is ``MPI.COMM_WORLD.Abort()`` (``client_manager.py:92-93``);
here it's a cooperative FINISH broadcast + transport stop.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

from fedml_tpu.core import telemetry
from fedml_tpu.core.message import (
    MSG_TYPE_FINISH,
    MSG_TYPE_HEARTBEAT,
    MSG_TYPE_S2C_ACK,
    Message,
)
from fedml_tpu.core.transport.base import BaseTransport

Handler = Callable[[Message], None]


class LivenessMonitor:
    """Per-peer heartbeat sender + staleness detector.

    The reference framework has NO liveness layer: a crashed MPI rank
    aborts the world, and a crashed cross-silo client leaves the server
    blocked in its recv loop forever. Here every manager can arm a
    monitor: a daemon thread beats ``MSG_TYPE_HEARTBEAT`` to each peer
    every ``interval_s`` and declares a peer dead — once — when nothing
    has been DELIVERED from it for ``timeout_s``. Arrival time is
    recorded by a transport deliver-hook, not at dispatch, so a peer busy
    inside a long handler (local training) still observes heartbeats.
    """

    def __init__(
        self,
        mgr: "Manager",
        peers: Iterable[int],
        interval_s: float,
        timeout_s: float,
        on_dead: Callable[[int], None] | None,
    ):
        self.mgr = mgr
        self.peers = list(peers)
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.on_dead = on_dead
        self.dead: set[int] = set()
        now = time.monotonic()
        self.last_seen: dict[int, float] = {p: now for p in self.peers}
        # per-peer watchdog generation: a revive/watch that supersedes
        # a still-sleeping watchdog bumps it, and the old thread exits
        # on its next wake instead of coexisting with its replacement
        # (an unwatch→rejoin inside one interval would otherwise leak
        # a duplicate watchdog per churn cycle)
        self._gen: dict[int, int] = {p: 0 for p in self.peers}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # fleet-federation carry (core/export.py): per-peer record of
        # the metric values already shipped, so each uplink beat sends
        # only DELTAS. Best-effort — a beat lost on a flapping link
        # under-counts the fleet view by one delta, never corrupts it.
        self._fleet_prev: dict[int, dict] = {}
        mgr.transport.add_deliver_hook(self._on_deliver)
        # ONE thread per peer: a beat to a dead peer blocks inside the
        # transport's retry budget, and a shared loop would let a single
        # crashed rank starve every other peer of beats (whose own
        # watchdogs would then fire — a cascade that turns one failure
        # into a world failure)
        self._threads = [
            threading.Thread(
                target=self._run_peer, args=(p, 0), daemon=True,
                name=f"liveness-rank{mgr.rank}-peer{p}",
            )
            for p in self.peers
        ]
        for t in self._threads:
            t.start()

    def _on_deliver(self, msg: Message) -> None:
        with self._lock:
            if msg.sender in self.last_seen:
                self.last_seen[msg.sender] = time.monotonic()

    def _mark_dead_if_stale(self, peer: int) -> bool:
        """Commit a death ONLY if the peer is still stale at commit
        time. A JOIN-triggered :meth:`revive` (or any delivery) that
        refreshed ``last_seen`` between the caller's detection and this
        commit RETRACTS the death — without the re-check, an in-flight
        watchdog could commit a pre-refresh staleness verdict over a
        completed rejoin and strand a live, heartbeating rank outside
        the cohort forever. Returns True when the death was committed
        (the calling watchdog should exit) and False on retraction
        (keep watching)."""
        with self._lock:
            if peer in self.dead:
                return True  # someone else committed; watchdog exits
            if (time.monotonic() - self.last_seen[peer]
                    <= self.timeout_s):
                return False  # heard from since detection — retract
            self.dead.add(peer)
        telemetry.METRICS.inc("manager.dead_peer_events")
        telemetry.RECORDER.record(
            "dead_peer", peer=peer, rank=self.mgr.rank,
            timeout_s=self.timeout_s,
        )
        if self.on_dead is not None:
            self.on_dead(peer)
        return True

    def _run_peer(self, peer: int, gen: int) -> None:
        while not self._stop.wait(self.interval_s):
            if self.mgr.transport._stopped.is_set():
                return  # actor finished without an explicit stop()
            with self._lock:
                if self._gen.get(peer) != gen:
                    return  # superseded by a revive/watch replacement
                if peer in self.dead:
                    return
                stale = (
                    time.monotonic() - self.last_seen[peer]
                    > self.timeout_s
                )
            if stale and self._mark_dead_if_stale(peer):
                return
            try:
                # hb_ts: the peer's manager echoes it back so the next
                # inbound beat closes the loop into an RTT gauge
                payload = {"hb_ts": time.monotonic()}
                # fleet federation (docs/OBSERVABILITY.md "Live export
                # and SLOs"): an UPLINK beat (this rank -> its rank-0
                # aggregator) piggybacks a compact delta-encoded metric
                # summary. The field is optional by design — old
                # clients simply don't send it — and absent whenever
                # telemetry is off (the zero-cost-when-off rule) or
                # nothing changed since the last beat.
                if peer == 0 and self.mgr.rank != 0 \
                        and telemetry.METRICS.enabled:
                    from fedml_tpu.core import export as _export

                    summary = _export.fleet_summary(
                        _export.fleet_snapshot(telemetry.METRICS),
                        self._fleet_prev.setdefault(peer, {}),
                    )
                    if summary is not None:
                        payload["metrics"] = summary
                self.mgr.send_message(
                    Message(MSG_TYPE_HEARTBEAT, self.mgr.rank, peer,
                            payload)
                )
            except Exception:
                # endpoint gone (socket transports raise once the
                # retry budget is spent); pub/sub QoS-0 publishes
                # never raise for a dead PEER — there staleness is
                # the only detector. A send aborted because WE are
                # shutting down (stop event cut the retry short) is
                # not evidence about the peer — don't turn a clean
                # finish into a spurious dead-peer failure.
                if (self._stop.is_set()
                        or self.mgr.transport._stopped.is_set()):
                    return
                # a failed beat to a RECENTLY-heard-from peer (e.g. one
                # that just rejoined on a fresh endpoint) is retracted
                # by the staleness re-check: keep watching — a truly
                # dead peer goes stale within timeout_s and commits
                # then
                if self._mark_dead_if_stale(peer):
                    return

    def dead_snapshot(self) -> set[int]:
        """Consistent snapshot of the peers currently considered dead —
        the liveness source of truth the server's round-boundary
        rejoin/death reconciliation reads (docs/FAULT_TOLERANCE.md
        "Recovery")."""
        with self._lock:
            return set(self.dead)

    def revive(self, peer: int) -> None:
        """Re-arm monitoring for a peer that rejoined after being
        declared dead (docs/FAULT_TOLERANCE.md "Recovery"). Resets the
        peer's last-seen clock and restarts its watchdog thread (the old
        one returned when it fired). ``on_dead`` may therefore fire
        again for the same rank — once per death, not once per run.
        Idempotent for peers that were never declared dead (a duplicate
        JOIN only refreshes last-seen)."""
        with self._lock:
            if peer not in self.last_seen:
                return  # not a monitored peer
            self.last_seen[peer] = time.monotonic()
            if peer not in self.dead:
                return
            self.dead.discard(peer)
            # supersede the old watchdog (it may still be sleeping if
            # the dead flag came from unwatch rather than its own
            # staleness verdict): bump the generation so it exits on
            # wake instead of running alongside its replacement
            self._gen[peer] = gen = self._gen.get(peer, 0) + 1
        t = threading.Thread(
            target=self._run_peer, args=(peer, gen), daemon=True,
            name=f"liveness-rank{self.mgr.rank}-peer{peer}",
        )
        t.start()
        self._threads.append(t)

    def watch(self, peer: int) -> None:
        """Start monitoring a peer that was NOT part of the launch
        world — a mid-run elastic admission (docs/FAULT_TOLERANCE.md
        "Elastic membership"). For an already-known peer this is
        :meth:`revive`."""
        with self._lock:
            known = peer in self.last_seen
            if not known:
                self.peers.append(peer)
                self.last_seen[peer] = time.monotonic()
                self._gen[peer] = 0
        if known:
            self.revive(peer)
            return
        t = threading.Thread(
            target=self._run_peer, args=(peer, 0), daemon=True,
            name=f"liveness-rank{self.mgr.rank}-peer{peer}",
        )
        t.start()
        self._threads.append(t)

    def unwatch(self, peer: int) -> None:
        """Stop monitoring a peer that LEFT gracefully: its watchdog
        thread exits without firing ``on_dead`` (a departure is not a
        death), and a later :meth:`revive`/:meth:`watch` re-arms it.
        Implemented by marking the peer dead WITHOUT the on_dead
        callback — the watchdog loop's exit condition."""
        with self._lock:
            if peer not in self.last_seen:
                return
            self.dead.add(peer)

    def stop(self) -> None:
        self._stop.set()


def create_transport(
    backend: str,
    rank: int,
    *,
    hub=None,
    ip_config: dict[int, tuple[str, int]] | None = None,
    bus=None,
    store=None,
    size: int | None = None,
) -> BaseTransport:
    """Backend dispatch by name (reference ``client_manager.py:28-50``:
    backend in {MPI, MQTT, MQTT_S3, GRPC, TRPC}; here {LOOPBACK, TCP,
    GRPC, TRPC, PUBSUB, PUBSUB_BLOB} — PUBSUB is the MQTT-shaped topic bus,
    PUBSUB_BLOB adds the S3-shaped control/data-plane split)."""
    backend = backend.upper()
    if backend == "LOOPBACK":
        assert hub is not None, "loopback needs a shared LoopbackHub"
        return hub.create(rank)
    if backend == "TCP":
        from fedml_tpu.core.transport.tcp import TcpTransport

        assert ip_config is not None
        return TcpTransport(rank, ip_config)
    if backend == "GRPC":
        from fedml_tpu.core.transport.grpc_transport import GrpcTransport

        assert ip_config is not None
        return GrpcTransport(rank, ip_config)
    if backend in ("TRPC", "TENSOR_RPC"):
        from fedml_tpu.core.transport.tensor_rpc import TensorRpcTransport

        assert ip_config is not None
        return TensorRpcTransport(rank, ip_config)
    if backend in ("PUBSUB", "MQTT"):
        from fedml_tpu.core.transport.pubsub import PubSubTransport

        assert bus is not None and size is not None
        return PubSubTransport(rank, bus, size)
    if backend in ("PUBSUB_BLOB", "MQTT_S3"):
        from fedml_tpu.core.transport.pubsub import PubSubBlobTransport

        assert bus is not None and store is not None and size is not None
        return PubSubBlobTransport(rank, bus, store, size)
    raise ValueError(f"unknown backend: {backend}")


class Manager:
    """Common actor machinery (both sides)."""

    def __init__(self, rank: int, size: int, transport: BaseTransport):
        self.rank = rank
        self.size = size
        self.transport = transport
        self._handlers: dict[int, Handler] = {}
        self.liveness: LivenessMonitor | None = None
        # why the peer FINISHed us, when it said (e.g. "evicted" —
        # docs/FAULT_TOLERANCE.md "Elastic membership"): the deploy
        # summary reports it so a supervisor can tell a departure BY
        # DESIGN from an ordinary wind-down
        self.finish_reason: str | None = None
        transport.add_observer(self)
        self.register_message_receive_handler(
            MSG_TYPE_FINISH, self._on_finish
        )
        # liveness/handshake beacons are protocol-level: every actor
        # accepts them (their primary side effect — the last-seen
        # refresh — happens at deliver time, before dispatch; the
        # handler only services the RTT ping/echo)
        self.register_message_receive_handler(
            MSG_TYPE_HEARTBEAT, self._on_heartbeat
        )
        self.register_message_receive_handler(
            MSG_TYPE_S2C_ACK, lambda msg: None
        )

    def _on_finish(self, msg: Message) -> None:
        self.finish_reason = msg.get("reason")
        self.finish()

    def _on_heartbeat(self, msg: Message) -> None:
        """Ping/echo half of the RTT measurement: a beat carrying
        ``hb_ts`` is echoed back (``hb_echo``); an echo of OUR beat
        closes the loop into a per-peer RTT gauge. Echoes carry no
        ``hb_ts``, so the exchange terminates after one hop."""
        hb_echo = msg.get("hb_echo")
        if hb_echo is not None:
            # cardinality-capped per-peer family: beyond the cap new
            # peers fold into manager.heartbeat_rtt_s.other instead of
            # minting one gauge per peer forever (the 10k-client
            # registry/scrape bound, docs/OBSERVABILITY.md)
            telemetry.METRICS.gauge_labeled(
                "manager.heartbeat_rtt_s", f"peer{msg.sender}",
                time.monotonic() - float(hb_echo),
            )
            return
        fleet = msg.get("metrics")
        if fleet is not None and self.rank == 0 \
                and telemetry.METRICS.enabled:
            # fold the piggybacked client summary into the fleet.*
            # aggregates (chaos-protected: malformed fields are counted
            # and dropped at this receive edge, core/export.py)
            from fedml_tpu.core import export as _export

            _export.fold_fleet(fleet)
        hb_ts = msg.get("hb_ts")
        if hb_ts is not None:
            try:
                self.send_message(
                    Message(MSG_TYPE_HEARTBEAT, self.rank, msg.sender,
                            {"hb_echo": hb_ts})
                )
            except Exception:
                pass  # peer flapped mid-echo; its watchdog will notice

    def register_message_receive_handler(
        self, msg_type: int, handler: Handler
    ) -> None:
        self._handlers[msg_type] = handler

    def receive_message(self, msg_type: int, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            raise KeyError(
                f"rank {self.rank}: no handler for msg_type {msg_type}"
            )
        tr = telemetry.TRACER
        trace = getattr(msg, "trace", None) if tr is not None else None
        if trace is None:
            handler(msg)
            return
        # bind the inbound trace id for the handler's duration: any
        # message the handler sends inherits it, which is what connects
        # a server's round-sync to the client's result across processes
        telemetry.set_current_trace(trace[0])
        try:
            with tr.span(
                f"handle:{msg_type}", rank=self.rank, trace_id=trace[0],
                parent_span=trace[1], sender=msg.sender,
                msg_type=msg_type,
            ):
                handler(msg)
        finally:
            telemetry.set_current_trace(None)

    def send_message(self, msg: Message) -> None:
        tr = telemetry.TRACER
        if tr is not None and msg.msg_type != MSG_TYPE_HEARTBEAT:
            # heartbeats stay untraced: a 2 s beacon cadence would bury
            # the work-message timeline under protocol noise
            if getattr(msg, "trace", None) is None:
                tid = telemetry.current_trace()
                msg.trace = (
                    tid if tid is not None else telemetry.new_trace_id(),
                    telemetry.new_span_id(),
                )
            tr.event(
                "msg_send", rank=self.rank, trace_id=msg.trace[0],
                span_id=msg.trace[1], receiver=msg.receiver,
                msg_type=msg.msg_type,
            )
        self.transport.send_message(msg)

    def enable_liveness(
        self,
        peers: Iterable[int],
        interval_s: float = 2.0,
        timeout_s: float = 30.0,
        on_dead: Callable[[int], None] | None = None,
    ) -> LivenessMonitor:
        """Arm the heartbeat protocol toward ``peers``. ``on_dead(rank)``
        fires exactly once per peer DEATH, from the monitor thread (a
        peer revived via :meth:`LivenessMonitor.revive` is watched again
        and may die again)."""
        if self.liveness is not None:
            raise RuntimeError("liveness already enabled")
        self.liveness = LivenessMonitor(
            self, peers, interval_s, timeout_s, on_dead
        )
        return self.liveness

    def run(self) -> None:
        self.transport.handle_receive_message()

    def finish(self) -> None:
        if self.liveness is not None:
            self.liveness.stop()
        self.transport.stop()


class ServerManager(Manager):
    """Rank-0 actor (reference ``server_manager.py:15``)."""

    def client_ranks(self) -> list[int]:
        """The client ranks this server currently serves. The default
        is the launch world (``1..size-1``); elastic actors override it
        with their membership ledger so broadcasts and FINISH reach
        mid-run admissions and skip departed ranks
        (docs/FAULT_TOLERANCE.md "Elastic membership")."""
        return list(range(1, self.size))

    def broadcast(
        self,
        msg_type: int,
        payload_fn,
        ranks: Iterable[int] | None = None,
        on_send_error: Callable[[int, Exception], None] | None = None,
    ) -> None:
        """Send ``Message(msg_type, 0, r, payload_fn(r))`` to every
        served client rank (or just ``ranks``). With ``on_send_error`` a
        failed send is reported per-rank instead of aborting the whole
        broadcast — the fault-tolerant round path treats it as a dead
        peer and keeps the cohort's survivors moving."""
        targets = self.client_ranks() if ranks is None else ranks
        for r in targets:
            msg = Message(msg_type, self.rank, r, payload_fn(r))
            if on_send_error is None:
                self.send_message(msg)
                continue
            try:
                self.send_message(msg)
            except Exception as err:
                on_send_error(r, err)

    def finish_all(self) -> None:
        for r in self.client_ranks():
            try:
                self.send_message(
                    Message(MSG_TYPE_FINISH, self.rank, r, {})
                )
            except Exception:
                pass  # peer already gone — FINISH is best-effort
        self.finish()


class ClientManager(Manager):
    """Rank>=1 actor (reference ``client_manager.py:21``)."""

"""Runtime telemetry: metrics registry, trace context, flight recorder.

PR 1 made federated rounds survive drops, stragglers, and crashed
clients — but every one of those events was invisible: transports
counted nothing, the :class:`~fedml_tpu.core.tracing.Tracer` was wired
into nothing, and a quorum-lost abort left no artifact to debug from.
This module is the process-wide telemetry spine the rest of the runtime
hangs off (docs/OBSERVABILITY.md):

- :class:`MetricsRegistry` — thread-safe counters / gauges / histograms
  with a ``snapshot()``. One process-global instance (:data:`METRICS`)
  is instrumented into every transport (messages/bytes sent+received,
  retry attempts and exhaustions, reconnects, chaos faults), the manager
  (heartbeat RTT, dead-peer events, inbox depth) and the distributed
  round loop (wall time, stragglers, quorum renormalizations).
- trace context — ``(trace_id, span_id)`` pairs ride on
  :class:`~fedml_tpu.core.message.Message` envelopes; the thread-local
  *current trace* set at dispatch time makes a handler's outbound sends
  inherit the inbound message's trace id, so a send on rank 0 connects
  to its deliver (and the work it caused) on rank 1 across process
  boundaries. ``scripts/merge_trace.py`` folds the per-rank span dumps
  into one Perfetto-loadable Chrome trace (pid = rank).
- :class:`FlightRecorder` — a bounded ring of recent telemetry events,
  dumped to ``telemetry_dir`` on dead-peer detection, quorum-lost abort,
  and unhandled crash (sys/threading excepthooks), turning PR 1's loud
  failures into debuggable artifacts.

Disabled is the default and costs nothing per message: :data:`METRICS`
starts ``enabled=False`` (every ``inc``/``gauge``/``observe`` early-
returns), :data:`TRACER` is ``None`` (all tracing sites are guarded by
an ``is not None`` check and allocate no ids), and the recorder ring
accepts nothing. :func:`configure` — called by ``run.py`` under
``--telemetry_dir``/``--trace`` and by ``deploy.run_role`` — switches
the plane on for THIS process.

The reference leans on wandb logs and grep-able ``--Benchmark`` lines
(SURVEY.md §5.5); per-rank device/host timelines that line up are the
FedJAX-style stronger form (arxiv 2108.02117), and the transport byte
accounting is what Smart-NIC FL serving work optimizes against (arxiv
2307.06561).
"""

from __future__ import annotations

import atexit
import collections
import glob
import json
import os
import sys
import threading
import time
import uuid
from typing import Any

from fedml_tpu.core.tracing import Tracer


def percentiles_from_histogram(
    h: dict[str, Any], qs: tuple[float, ...] = (0.5, 0.95, 0.99)
) -> dict[str, float]:
    """Estimate quantiles from a histogram's power-of-two buckets.

    The target rank is located in the cumulative bucket counts and
    linearly interpolated inside its bucket ``(2^(k-1), 2^k]``, with
    the interpolation range clamped to the observed ``[min, max]``.

    **Error bound**: the estimate is EXACT whenever the selected
    bucket's value range collapses — single-observation histograms and
    any histogram whose observations all share one value (min == max
    clamps the bucket to a point). Otherwise the error is bounded by
    the selected bucket's width: for power-of-two buckets that means
    the estimate is within a factor of 2 of the true quantile (and
    tighter near the min/max clamps). Good enough for SLO monitoring
    (p99 round latency alarming on 2x regressions), not for
    microsecond-accurate timing — use the trace dumps for that.
    """
    count = h.get("count", 0)
    buckets = h.get("buckets", {})
    if not count or not buckets:
        return {}
    items = sorted(
        (int(k.split("^", 1)[1]), c) for k, c in buckets.items()
    )
    hmin = h.get("min", float("-inf"))
    hmax = h.get("max", float("inf"))
    out: dict[str, float] = {}
    for q in qs:
        target = q * count
        cum = 0
        for k, c in items:
            prev, cum = cum, cum + c
            if cum >= target:
                lo = 0.0 if k <= -20 else 2.0 ** (k - 1)
                hi = 2.0 ** k
                lo = min(max(lo, hmin), hmax)
                hi = max(min(hi, hmax), hmin)
                frac = (target - prev) / c if c else 0.0
                out[f"p{round(q * 100):d}"] = lo + (hi - lo) * frac
                break
    return out


class MetricsRegistry:
    """Thread-safe counters / gauges / histograms.

    Names are flat dotted strings (vocabulary in docs/OBSERVABILITY.md).
    Histograms keep count/sum/min/max plus power-of-two bucket counts —
    enough for a round-latency distribution without per-sample storage;
    ``snapshot()`` adds bucket-interpolated ``p50``/``p95``/``p99``
    estimates per histogram (:func:`percentiles_from_histogram` states
    the error bound — exact for single-valued histograms, within the
    2x bucket width otherwise), which is how a long-lived server
    reports round-latency SLOs without per-sample storage. All writes
    no-op while ``enabled`` is False, so the disabled hot path is one
    attribute check.
    """

    #: default per-family label cardinality cap (see
    #: :meth:`gauge_labeled`): at the 10k-client scale the per-peer
    #: gauge families (`heartbeat_rtt_s.peer<r>`, `inbox_hwm.rank<r>`)
    #: would otherwise grow the registry — and every scrape and
    #: ``snapshot()`` — without bound.
    LABEL_CAP = 64

    def __init__(self, enabled: bool = True,
                 label_cap: int = LABEL_CAP):
        self.enabled = enabled
        self.label_cap = label_cap
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, dict[str, Any]] = {}
        # family -> labels already minted (gauge_labeled's cap ledger)
        self._label_families: dict[str, set[str]] = {}

    def inc(self, name: str, value: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def gauge_labeled(self, family: str, label: str, value: float,
                      sep: str = ".") -> None:
        """Per-peer/per-rank gauge families with a cardinality cap
        (docs/OBSERVABILITY.md "Live export and SLOs"): the first
        ``label_cap`` distinct labels of a family mint real gauges
        (``<family><sep><label>``, e.g.
        ``manager.heartbeat_rtt_s.peer3``); every label beyond the cap
        folds into ONE ``<family>.other`` overflow gauge and counts
        ``telemetry.label_overflow`` — so a 10k-peer world keeps its
        registry (and every scrape) bounded while the overflow stays
        visible instead of silently dropped."""
        if not self.enabled:
            return
        with self._lock:
            labels = self._label_families.get(family)
            if labels is None:
                labels = self._label_families[family] = set()
            if label in labels or len(labels) < self.label_cap:
                labels.add(label)
                self._gauges[f"{family}{sep}{label}"] = float(value)
            else:
                self._gauges[f"{family}.other"] = float(value)
                self._counters["telemetry.label_overflow"] = (
                    self._counters.get("telemetry.label_overflow", 0) + 1
                )

    def labeled_name(self, family: str, label: str,
                     sep: str = ".") -> str:
        """Resolve (and register) a labeled gauge's FINAL name once —
        for per-message hot paths that cache the returned string and
        then write with plain :meth:`gauge`, keeping the deliver edge
        allocation-free while the family still honors the cardinality
        cap. An over-cap label resolves to the ``<family>.other``
        overflow slot (counted once, at resolution)."""
        with self._lock:
            labels = self._label_families.get(family)
            if labels is None:
                labels = self._label_families[family] = set()
            if label in labels or len(labels) < self.label_cap:
                labels.add(label)
                return f"{family}{sep}{label}"
            self._counters["telemetry.label_overflow"] = (
                self._counters.get("telemetry.label_overflow", 0) + 1
            )
            return f"{family}.other"

    def observe(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = {
                    "count": 0, "sum": 0.0,
                    "min": float("inf"), "max": float("-inf"),
                    "buckets": {},
                }
            h["count"] += 1
            h["sum"] += value
            h["min"] = min(h["min"], value)
            h["max"] = max(h["max"], value)
            # power-of-two bucket upper bounds: le_2^k for the smallest
            # k with value <= 2^k (k in [-20, 20], clamped)
            k = -20
            while k < 20 and value > 2.0 ** k:
                k += 1
            key = f"le_2^{k}"
            h["buckets"][key] = h["buckets"].get(key, 0) + 1

    def merge_histogram(self, name: str, h: dict[str, Any]) -> None:
        """Fold a REMOTE histogram delta (count/sum/min/max + bucket
        deltas in the registry's own ``le_2^k`` keying) into a local
        histogram — the fleet-federation fold (core/export.py): a
        client's heartbeat forwards its bucket deltas, and the server's
        ``fleet.*`` percentiles are computed over the cohort's real
        distribution, not a summary of summaries."""
        if not self.enabled:
            return
        count = int(h.get("count", 0))
        buckets = h.get("buckets", {})
        if count <= 0 and not buckets:
            return
        with self._lock:
            dst = self._hists.get(name)
            if dst is None:
                dst = self._hists[name] = {
                    "count": 0, "sum": 0.0,
                    "min": float("inf"), "max": float("-inf"),
                    "buckets": {},
                }
            dst["count"] += count
            dst["sum"] += float(h.get("sum", 0.0))
            mn, mx = h.get("min"), h.get("max")
            if mn is not None:
                dst["min"] = min(dst["min"], float(mn))
            if mx is not None:
                dst["max"] = max(dst["max"], float(mx))
            for k, v in buckets.items():
                dst["buckets"][k] = dst["buckets"].get(k, 0) + int(v)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def read_selected(
        self, counters=(), gauges=(), hists=()
    ) -> dict[str, Any]:
        """Targeted, constant-size read of named families — the
        heartbeat fleet-summary path uses this instead of
        :meth:`snapshot`, which deep-copies the WHOLE registry and
        interpolates percentiles for every histogram under the lock on
        every beat. Histogram entries carry the raw shape only (no
        percentiles — the summary ships bucket deltas, not
        estimates)."""
        with self._lock:
            return {
                "counters": {
                    k: self._counters[k] for k in counters
                    if k in self._counters
                },
                "gauges": {
                    k: self._gauges[k] for k in gauges
                    if k in self._gauges
                },
                "histograms": {
                    k: {
                        **self._hists[k],
                        "buckets": dict(self._hists[k]["buckets"]),
                    }
                    for k in hists if k in self._hists
                },
            }

    def snapshot(self) -> dict[str, Any]:
        """Deep-ish copy safe to mutate / serialize. Histogram entries
        carry estimated ``p50``/``p95``/``p99`` alongside the raw
        buckets (see :func:`percentiles_from_histogram` for the
        estimation error bound)."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: {
                        **v,
                        "buckets": dict(v["buckets"]),
                        **percentiles_from_histogram(v),
                    }
                    for k, v in self._hists.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._label_families.clear()


class FlightRecorder:
    """Bounded ring of recent telemetry events + crash-artifact writer.

    ``record`` is cheap (deque append under a lock) and a no-op while
    disabled. ``dump`` writes the ring, the metrics snapshot, and the
    trigger reason to ``<dir>/flight_rank<r>_<n>_<reason>.json`` —
    monotonic ``n`` so multiple triggers in one process never clobber
    each other.
    """

    def __init__(self, capacity: int = 1024, enabled: bool = False):
        self.enabled = enabled
        self.dir: str | None = None
        self.rank = 0
        # artifact-name stem: "rank<r>" plus the incarnation suffix a
        # supervised restart gets (so a restarted rank's dumps never
        # clobber its predecessor's — docs/FAULT_TOLERANCE.md
        # "Recovery")
        self.tag = "rank0"
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dumps = 0

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        ev = {"kind": kind, "ts": time.time(), **fields}
        with self._lock:
            self._ring.append(ev)

    def dump(self, reason: str, **fields) -> str | None:
        """Write the flight artifact; returns its path (None if no
        telemetry dir is configured)."""
        self.record(reason, **fields)
        if self.dir is None:
            return None
        with self._lock:
            self._dumps += 1
            n = self._dumps
            events = list(self._ring)
        path = os.path.join(
            self.dir, f"flight_{self.tag}_{n}_{reason}.json"
        )
        data = {
            "reason": reason,
            "rank": self.rank,
            "ts": time.time(),
            **fields,
            "events": events,
            "metrics": METRICS.snapshot(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, default=repr)
        os.replace(tmp, path)
        return path


#: Process-global registry — disabled until :func:`configure`.
METRICS = MetricsRegistry(enabled=False)
#: Process-global flight recorder — disabled until :func:`configure`.
RECORDER = FlightRecorder()
#: Process-global tracer — ``None`` until :func:`configure(trace=True)`.
#: Every tracing site guards on ``TRACER is not None`` so the disabled
#: path allocates nothing per message.
TRACER: Tracer | None = None

_DIR: str | None = None
_RANK = 0
# periodic metrics time-series flush (docs/OBSERVABILITY.md
# "Performance observability"): a daemon thread appending snapshot rows
# to metrics_rank<r>.jsonl so a long-lived server reports round-latency
# SLOs over time instead of only an at-exit snapshot
_TS_STOP: threading.Event | None = None
_TS_THREAD: threading.Thread | None = None
# whether the periodic thread APPENDS jsonl rows (an operator asked for
# --metrics_interval) or only ticks the SLO engine (the cadence was
# derived from --slo windows — a long-lived server must not get tens of
# MB of time series it never asked for as a side effect of an SLO)
_TS_ROWS = True
# serializes time-series appends: the periodic flusher and the at-exit
# final row must never interleave a partial JSONL line (the shutdown
# path additionally JOINS the flusher before appending the final row,
# so the file always ends on the end-state snapshot)
_TS_LOCK = threading.Lock()
# the live observability plane (core/export.py, core/slo.py): the
# OpenMetrics HTTP exporter and the SLO engine — both None until
# configure(metrics_port=...) / configure(slos=...), so the default
# path opens no socket and evaluates nothing
_EXPORTER = None
_SLO = None
# incarnation suffix ("" for a rank's first process; "_i<n>" for a
# supervised restart, chosen in configure() so a restarted rank never
# overwrites the artifacts its predecessor flushed —
# scripts/merge_trace.py folds all incarnations of a rank into one pid)
_SUFFIX = ""
_tls = threading.local()
_hooks_installed = False


# -- trace context -----------------------------------------------------------


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def set_current_trace(trace_id: str | None) -> None:
    """Bind the thread's current trace id (set at message dispatch so a
    handler's outbound sends inherit the inbound trace)."""
    _tls.trace = trace_id


def current_trace() -> str | None:
    return getattr(_tls, "trace", None)


def maybe_span(name: str, **attrs):
    """A tracer span when tracing is on, a null context otherwise."""
    import contextlib

    tr = TRACER
    if tr is None:
        return contextlib.nullcontext()
    return tr.span(name, **attrs)


def flight_dump(reason: str, **fields) -> str | None:
    """Record + dump a flight artifact (no-op without a telemetry dir).
    The triggers — dead peers, quorum-lost aborts, crashes — call this;
    see docs/OBSERVABILITY.md."""
    return RECORDER.dump(reason, **fields)


# -- lifecycle ---------------------------------------------------------------


def default_dir(out_dir: str, run_name: str) -> str:
    """Where artifacts land when tracing is requested without an
    explicit ``--telemetry_dir`` (one derivation shared by the sim and
    role CLI paths so they can never drift)."""
    return os.path.join(out_dir, run_name, "telemetry")


def artifact_dir() -> str | None:
    """The configured telemetry directory (None while disabled) — where
    satellite layers (the perf profiler's capture windows and
    breakdown artifact, core/perf.py) put their files so everything
    about one run lands in one place."""
    return _DIR


def rank_tag() -> str:
    """This process's artifact-name stem (``rank<r>`` plus the
    incarnation suffix a supervised restart gets)."""
    return f"rank{_RANK}{_SUFFIX}"


def slo_engine():
    """The process SLO engine (None unless ``configure(slos=...)``) —
    read by the ``/statusz`` assembler (core/export.py)."""
    return _SLO


def exporter():
    """The process OpenMetrics exporter (None while disabled)."""
    return _EXPORTER


def configure(
    telemetry_dir: str | None = None,
    rank: int = 0,
    trace: bool = True,
    jax_profiler: bool = False,
    flight_capacity: int = 1024,
    metrics_interval: float | None = None,
    metrics_port: int | None = None,
    metrics_host: str = "0.0.0.0",
    slos=(),
    slo_scope: str = "",
) -> None:
    """Enable telemetry for THIS process (idempotent).

    - metrics counting switches on unconditionally;
    - ``trace=True`` creates the process tracer (optionally wrapping
      spans in ``jax.profiler.TraceAnnotation`` so device work lines up
      with host spans in a jax profile);
    - a ``telemetry_dir`` additionally arms the flight recorder, the
      crash hooks (sys/threading excepthook -> flight dump), and the
      exit flush that writes ``trace_rank<r>.json`` +
      ``metrics_rank<r>.json``;
    - ``metrics_interval`` (seconds, with a dir) starts the periodic
      time-series flush: append-only ``metrics_rank<r>.jsonl`` rows
      (:func:`start_metrics_timeseries`);
    - ``metrics_port`` starts the OpenMetrics HTTP exporter
      (core/export.py: ``/metrics`` + ``/statusz`` + ``/healthz`` on
      one listener; 0 binds an ephemeral port, read back from
      ``telemetry.exporter().port`` / the ``telemetry.metrics_port``
      gauge / ``export_rank<r>.json``). None (the default) opens no
      socket and adds no work anywhere;
    - ``slos`` (``--slo`` strings, core/slo.py) arms the SLO engine;
      its windowed evaluation rides the time-series cadence (a default
      tick interval is derived from the tightest window when
      ``metrics_interval`` is not set), exports ``slo.*`` burn gauges,
      and writes ``slo_rank<r>.json`` verdicts at shutdown.
      ``slo_scope`` names the job the verdicts belong to (defaults to
      ``rank<r>``).
    """
    global TRACER, _DIR, _RANK, _SUFFIX, _EXPORTER, _SLO
    _RANK = rank
    METRICS.enabled = True
    RECORDER.rank = rank
    if trace:
        if TRACER is None:
            TRACER = Tracer(use_jax_profiler=jax_profiler, rank=rank)
        else:
            TRACER.rank = rank
    if telemetry_dir:
        os.makedirs(telemetry_dir, exist_ok=True)
        _DIR = telemetry_dir
        # a previous incarnation of this rank (supervised restart into
        # the same dir) already left artifacts here: pick the first
        # free "_i<n>" suffix instead of clobbering them. Flight dumps
        # count as evidence too — a chaos os._exit rank dies without
        # ever flushing trace/metrics, and its crash artifacts are
        # exactly what must not be overwritten.
        _SUFFIX = ""
        n = 0
        while any(
            os.path.exists(
                os.path.join(telemetry_dir,
                             f"{kind}_rank{rank}{_SUFFIX}.json")
            )
            for kind in ("trace", "metrics")
        ) or glob.glob(
            os.path.join(telemetry_dir,
                         f"flight_rank{rank}{_SUFFIX}_*.json")
        ):
            n += 1
            _SUFFIX = f"_i{n}"
        RECORDER.dir = telemetry_dir
        RECORDER.tag = f"rank{rank}{_SUFFIX}"
        RECORDER.enabled = True
        RECORDER._ring = collections.deque(
            RECORDER._ring, maxlen=flight_capacity
        )
        _install_hooks()
    if slos and _SLO is None:
        from fedml_tpu.core import slo as _slo_mod

        specs = _slo_mod.parse_specs(
            slos, scope=slo_scope or f"rank{rank}"
        )
        if specs:
            _SLO = _slo_mod.SloEngine(specs, METRICS, recorder=RECORDER)
    if metrics_port is not None and _EXPORTER is None:
        from fedml_tpu.core import export as _export

        _EXPORTER = _export.MetricsExporter(metrics_port,
                                            host=metrics_host)
        METRICS.gauge("telemetry.metrics_port", _EXPORTER.port)
        if _DIR is not None:
            # port discovery for ephemeral binds (--metrics_port 0):
            # scrapers read the bound port from the artifact dir
            try:
                with open(os.path.join(
                        _DIR, f"export_rank{_RANK}{_SUFFIX}.json"),
                        "w") as f:
                    json.dump(
                        {"port": _EXPORTER.port, "rank": rank}, f
                    )
            except OSError:
                pass
    if metrics_interval:
        start_metrics_timeseries(metrics_interval)
    elif _SLO is not None and _TS_THREAD is None:
        # the SLO engine rides the time-series cadence; without an
        # explicit interval, derive one from the tightest window so
        # every window sees several evaluations — but tick-only
        # (rows=False): an SLO must not start a jsonl time series the
        # operator never asked for
        w = min(s.window_s for s in _SLO.specs)
        start_metrics_timeseries(max(0.1, min(1.0, w / 5.0)),
                                 rows=False)


def _timeseries_path() -> str | None:
    if _DIR is None:
        return None
    return os.path.join(_DIR, f"metrics_rank{_RANK}{_SUFFIX}.jsonl")


def _append_timeseries_row() -> None:
    """One snapshot row (histograms compacted: percentiles kept, raw
    buckets dropped — the at-exit ``metrics_rank<r>.json`` carries the
    full shape)."""
    path = _timeseries_path()
    if path is None:
        return
    snap = METRICS.snapshot()
    row = {
        "ts": time.time(),
        "rank": _RANK,
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "histograms": {
            k: {kk: vv for kk, vv in v.items() if kk != "buckets"}
            for k, v in snap["histograms"].items()
        },
    }
    try:
        # one serialized append per row: the periodic flusher and the
        # at-exit final row must never interleave partial lines
        with _TS_LOCK, open(path, "a") as f:
            f.write(json.dumps(row, default=repr) + "\n")
    except OSError:
        pass


def _ts_tick() -> None:
    """One time-series beat: evaluate the SLO engine (it rides this
    cadence by design), then — when the operator asked for a time
    series — append the snapshot row, so every row already carries the
    fresh ``slo.*`` burn gauges."""
    slo = _SLO
    if slo is not None:
        try:
            slo.tick()
        except Exception:
            pass  # a broken spec must not kill the flusher
    if _TS_ROWS:
        _append_timeseries_row()


def start_metrics_timeseries(interval_s: float,
                             rows: bool = True) -> None:
    """Start the periodic metrics flush for this process (idempotent;
    needs a configured telemetry dir). Every ``interval_s`` seconds a
    snapshot row — counters, gauges, histograms with their
    p50/p95/p99 — is APPENDED to ``metrics_rank<r>.jsonl``, so a
    long-lived deployment's round-latency SLO is a time series, not
    only the at-exit state (the ``.json`` snapshot stays the
    latest-state artifact). The thread is a daemon and dies with the
    process; :func:`shutdown` stops it and writes one final row. With
    an SLO engine configured but no telemetry dir, the thread still
    runs (the engine's windowed ticks ride this cadence) — the row
    append itself stays dir-gated. ``rows=False`` runs the cadence for
    the SLO engine ONLY, appending nothing: the derived-from-``--slo``
    tick must not flood a long-lived server's disk with a time series
    the operator never asked for."""
    global _TS_STOP, _TS_THREAD, _TS_ROWS
    if (_DIR is None and _SLO is None) or interval_s <= 0 \
            or _TS_THREAD is not None:
        return
    _TS_ROWS = rows
    stop = threading.Event()

    def loop():
        while not stop.wait(interval_s):
            _ts_tick()

    t = threading.Thread(target=loop, daemon=True,
                         name="metrics-timeseries")
    _TS_STOP, _TS_THREAD = stop, t
    t.start()


def flush_metrics() -> None:
    """Durably snapshot JUST the metrics registry (cheap, bounded —
    unlike the trace dump, which grows with the run). The server actor
    calls this at every round checkpoint so counters survive a SIGKILL
    instead of dying with the exit-time flush (docs/FAULT_TOLERANCE.md
    "Recovery")."""
    if _DIR is None:
        return
    path = os.path.join(_DIR, f"metrics_rank{_RANK}{_SUFFIX}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(METRICS.snapshot(), f, indent=2, default=repr)
    os.replace(tmp, path)


def _stop_timeseries(write_final: bool) -> None:
    """Stop + JOIN the periodic flusher, then (optionally) append ONE
    final row. The join-before-append ordering is the fix for the
    shutdown race: a fast exit used to let the daemon's in-flight row
    interleave with the final one; now the final row is always the
    file's last line, written after the flusher is provably gone.
    Idempotent — a second flush appends nothing."""
    global _TS_STOP, _TS_THREAD
    stop, thread = _TS_STOP, _TS_THREAD
    _TS_STOP = _TS_THREAD = None
    if stop is not None:
        stop.set()
    if thread is not None:
        thread.join(timeout=2.0)
        if write_final:
            _ts_tick()


def flush() -> None:
    """Write the per-rank trace dump and metrics snapshot now (also runs
    at interpreter exit once a telemetry dir is configured). With the
    time-series flush armed, the flusher is joined first and exactly one
    final row is appended — the tail of the series always reflects the
    end state, and a fast exit cannot interleave a partial row with it.
    SLO verdicts (``slo_rank<r>.json``) are written here too."""
    if _DIR is None:
        return
    if TRACER is not None and TRACER.events:
        TRACER.dump(
            os.path.join(_DIR, f"trace_rank{_RANK}{_SUFFIX}.json")
        )
    _stop_timeseries(write_final=True)
    if _SLO is not None:
        try:
            _SLO.write_verdicts(
                os.path.join(_DIR, f"slo_rank{_RANK}{_SUFFIX}.json"),
                rank=_RANK,
            )
        except Exception:
            pass  # the verdict artifact must never block the flush
    flush_metrics()


def shutdown() -> None:
    """Flush, then return to the all-disabled state (test isolation)."""
    global TRACER, _DIR, _SUFFIX, _EXPORTER, _SLO, _TS_ROWS
    _stop_timeseries(write_final=_DIR is not None)
    flush()
    _TS_ROWS = True
    if _EXPORTER is not None:
        _EXPORTER.stop()
        _EXPORTER = None
    _SLO = None
    try:
        from fedml_tpu.core import export as _export

        _export.reset_status_sources()
    except Exception:
        pass
    try:
        import sys as _sys

        # memory-plane state (program table, headroom flag, high-water
        # mark) resets with the rest of the plane — but only if the
        # module was ever imported; shutdown must not pull it in
        _mem = _sys.modules.get("fedml_tpu.core.memscope")
        if _mem is not None:
            _mem.reset()
    except Exception:
        pass
    try:
        import sys as _sys

        # anatomy-plane state (phase ring, breach profiler) resets the
        # same lazy way — an open capture window is closed here so a
        # dangling jax.profiler session cannot break the next run
        _an = _sys.modules.get("fedml_tpu.core.anatomy")
        if _an is not None:
            _an.reset()
    except Exception:
        pass
    METRICS.enabled = False
    METRICS.reset()
    RECORDER.enabled = False
    RECORDER.dir = None
    RECORDER.tag = "rank0"
    RECORDER._ring.clear()
    RECORDER._dumps = 0
    TRACER = None
    _DIR = None
    _SUFFIX = ""
    set_current_trace(None)


def _install_hooks() -> None:
    """Crash hooks + exit flush, installed once per process. They read
    the module globals at fire time, so a later :func:`shutdown` renders
    them inert."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_exc = sys.excepthook

    def on_crash(exc_type, exc, tb):
        if RECORDER.enabled:
            flight_dump("crash", error=repr(exc),
                        error_type=exc_type.__name__)
            flush()
        prev_exc(exc_type, exc, tb)

    sys.excepthook = on_crash

    prev_thread_exc = threading.excepthook

    def on_thread_crash(args):
        if RECORDER.enabled and args.exc_type is not SystemExit:
            flight_dump(
                "crash",
                error=repr(args.exc_value),
                error_type=args.exc_type.__name__,
                thread=getattr(args.thread, "name", None),
            )
        prev_thread_exc(args)

    threading.excepthook = on_thread_crash
    atexit.register(flush)

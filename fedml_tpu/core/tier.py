"""Multi-tier aggregator topology: spec parsing + the partial payload.

The Smart-NIC FL serving line ("Performance Improvement of Federated
Learning Server using Smart NIC", arxiv 2307.06561) shows where the
single-aggregator bottleneck breaks: partial reduction CLOSE TO THE
WIRE, before the root ever sees a delta. This module is the shared
vocabulary of that shape for this runtime (docs/FAULT_TOLERANCE.md
"Async + tiered worlds"):

- :class:`TierSpec` — the topology flag (``--tier_spec root:L``): a
  root aggregator serving ``L`` leaf aggregators, each leaf
  terminating its own clients' transports in its own deployment world
  (the leaf is rank 0 of a leaf world; the root world is
  ``{0: root, 1..L: leaves}``). Each tier runs its OWN
  ``MembershipLedger`` / ``LivenessMonitor`` / reputation scope, so
  churn, crashes, and quarantine stay per-tier.
- the **partial payload** — the one typed message a leaf forwards
  upstream per flush: ``[sum, n, count]`` where ``sum`` is the
  weighted delta sum over the leaf's included (screened,
  defense-clipped, non-quarantined) client results, ``n`` the total
  sample mass, and ``count`` how many client results it folds.
  ``sum / n`` is the leaf's weighted-mean delta, so the root folding
  one row per leaf with weight ``n`` through the unchanged
  ``server_update`` body reproduces the flat world's weighted mean
  over all clients — the tier tree changes WHERE reduction happens,
  not what is computed.

Partials are validated at the root's receive edge
(:func:`validate_partial`) exactly like compressed payloads are at the
server's (structure, shapes, dtypes, finiteness): a malformed or
poisoned partial is counted and dropped, never folded.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np

#: payload keys for MSG_TYPE_L2R_PARTIAL (core/message.py)
KEY_TIER_SUM = "tier_sum"
KEY_TIER_COUNT = "tier_count"


@dataclasses.dataclass(frozen=True)
class TierSpec:
    """Parsed ``--tier_spec``. Current grammar: ``root:<L>`` — one
    root and ``L`` leaf aggregators (deeper trees are a composition of
    this two-level unit and can reuse the same actor pair)."""

    n_leaves: int

    def __post_init__(self):
        if self.n_leaves < 1:
            raise ValueError(
                f"tier_spec needs >= 1 leaf, got {self.n_leaves}"
            )

    @staticmethod
    def parse(spec: str) -> "TierSpec":
        head, sep, leaves = spec.partition(":")
        if head != "root" or not sep or not leaves.isdigit():
            raise ValueError(
                f"--tier_spec expects 'root:<n_leaves>' (e.g. root:2), "
                f"got {spec!r}"
            )
        return TierSpec(n_leaves=int(leaves))

    @property
    def root_world_size(self) -> int:
        """The root deployment world: rank 0 = root, ranks 1..L =
        leaf aggregators."""
        return self.n_leaves + 1

    def leaf_ranks(self) -> list[int]:
        return list(range(1, self.n_leaves + 1))

    def client_base(self, leaf_rank: int, leaf_clients: int) -> int:
        """Default global-client-id base for a leaf's slot 0 when the
        operator does not pass ``--tier_client_base`` explicitly:
        equal-size leaves get contiguous id blocks, so two sibling
        leaves never train the same seeded shard."""
        if not (1 <= leaf_rank <= self.n_leaves):
            raise ValueError(
                f"leaf rank {leaf_rank} outside 1..{self.n_leaves}"
            )
        return (leaf_rank - 1) * leaf_clients


def build_partial(sum_tree, n_total: float, count: int) -> dict:
    """The leaf->root payload: host-converted sum tree + scalars.
    Rides the sealed wire frames like every other message (the tensor
    leaves take the native codec path)."""
    return {
        KEY_TIER_SUM: jax.tree.map(np.asarray, sum_tree),
        KEY_TIER_COUNT: int(count),
    }


def validate_partial(template_vars, payload, n_total: float) -> str | None:
    """Receive-edge screen for one partial: returns an error string
    (counted ``tier.partial_rejected`` by the caller and dropped) or
    None when the partial is foldable. Mirrors
    ``compress.validate_payload``: structure against the model
    template, per-leaf shape/dtype, finiteness everywhere — one NaN
    leaf in a partial would poison the whole root aggregate."""
    if not isinstance(payload, dict) or KEY_TIER_SUM not in payload:
        return "missing tier_sum"
    count = payload.get(KEY_TIER_COUNT)
    if not isinstance(count, int) or count < 1:
        return f"bad tier_count {count!r}"
    if not (isinstance(n_total, (int, float)) and math.isfinite(n_total)
            and n_total > 0):
        return f"bad sample mass {n_total!r}"
    try:
        t_leaves, treedef = jax.tree.flatten(template_vars)
        p_leaves, p_def = jax.tree.flatten(payload[KEY_TIER_SUM])
    except Exception as err:  # exotic containers from a hostile peer
        return f"unflattenable partial: {err}"
    if treedef != p_def:
        return "partial tree structure != model template"
    for t, p in zip(t_leaves, p_leaves):
        a = np.asarray(p)
        if a.shape != np.shape(t):
            return f"leaf shape {a.shape} != template {np.shape(t)}"
        if not np.issubdtype(a.dtype, np.floating):
            return f"non-float partial leaf dtype {a.dtype}"
        if not np.all(np.isfinite(a)):
            return "non-finite partial leaf"
    return None

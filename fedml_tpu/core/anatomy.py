"""Round anatomy: per-phase time attribution, straggler accounting,
and SLO-breach-triggered deep profiles.

The perf plane (core/perf.py), the SLO engine (core/slo.py), and the
memory plane (core/memscope.py) answer *what* degraded — ``slo.ok``
flipped, ``perf.mfu`` sagged, headroom shrank. This module is the *why*
plane (docs/OBSERVABILITY.md "Round anatomy"): it attributes each
round's wall time to a fixed phase vocabulary, attributes barrier wait
to the slowest contributors, and — armed with ``--profile_on_breach`` —
captures a one-shot ``jax.profiler`` window at the moment an SLO breach
transition (or a ``mem_headroom`` crossing) happens, so the run
diagnoses itself instead of requiring a human to reproduce the bad
state. The Smart-NIC FL paper (arxiv 2307.06561) motivates exactly this
server-side bottleneck decomposition; FedJAX (arxiv 2108.02117) is the
reminder that throughput claims are only trustworthy when the per-phase
breakdown is measured, not inferred.

Three legs:

- :class:`RoundAnatomy` — per-round phase attribution over the fixed
  vocabulary :data:`PHASES`, timed at sync points the round ALREADY has
  (the run loop's dispatch boundary, the one ``jax.device_get`` host
  force, eval returns, checkpoint blocks; never a new
  ``block_until_ready`` on the hot path). The residual between the
  explicit phases and the round wall is itself exported as
  ``host_gap`` — attribution is conserved, never silently dropped.
  Emits ``perf.phase.<name>_s`` histograms + the ``perf.phase.dominant``
  gauge, keeps a last-N-rounds ring served as the ``/tracez`` section
  of the live listener (core/export.py), and — on the deploy server —
  computes the per-round critical path + straggler attribution from the
  result-arrival timestamps the round close already collects
  (``perf.straggler_wait_s``, capped ``perf.straggler.rank<r>`` via the
  ``gauge_labeled`` cardinality machinery).
- critical-path trace events — rank 0 emits one ``critical_path``
  tracer event per closed round (sync → slowest-contributor wait →
  aggregate); ``scripts/merge_trace.py`` renders them as a dedicated
  track in the merged Perfetto view.
- :class:`BreachProfiler` — a one-shot ``jax.profiler.trace`` window
  (``--profile_window_s``, default 5 s) fired on an SLO breach
  *transition* or a ``mem_headroom`` crossing, capped by
  ``--profile_max_captures`` with a cooldown between windows, written
  under ``<telemetry_dir>/profiles/`` with a flight-recorder event
  linking breach → artifact path. The capture runs on a timer thread
  and NEVER extends a round deadline (docs/FAULT_TOLERANCE.md).

Like every other plane, disabled is the default and costs nothing:
:data:`ANATOMY` starts ``enabled=False`` (every call site guards on one
attribute check and the round results are byte-identical — pinned in
``tests/test_anatomy.py``), and no profiler is armed until
:func:`configure`. ``telemetry.shutdown()`` resets this module lazily,
the same way it resets the memory plane.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Any

from fedml_tpu.core import telemetry

#: The fixed phase vocabulary (docs/OBSERVABILITY.md "Round anatomy").
#: Not every path emits every phase — a compiled simulator round is one
#: fused program, so ``local`` carries the whole device execution there,
#: while the deploy server decomposes ``wire``/``defense_agg``/
#: ``server_update``/``checkpoint`` at the boundaries its close path
#: already syncs on. ``host_gap`` is always the residual.
PHASES = (
    "sample",
    "h2d",
    "local",
    "defense_agg",
    "server_update",
    "wire",
    "eval",
    "checkpoint",
    "host_gap",
)

#: ``/tracez`` ring depth: the last N closed rounds' anatomy entries.
RING_CAPACITY = 64

#: Seconds a finished capture window blocks the next one — breaches
#: often arrive in bursts (every tick of a breached window transitions
#: nothing, but flapping SLOs re-transition), and back-to-back windows
#: would trade the whole capture budget for near-duplicate artifacts.
DEFAULT_COOLDOWN_S = 30.0


class RoundAnatomy:
    """Per-round phase attribution + the ``/tracez`` anatomy ring.

    One instance per process (:data:`ANATOMY`). All methods no-op while
    ``enabled`` is False, so the disabled hot path is one attribute
    check at each call site — the instrumented loops check
    ``ANATOMY.enabled`` themselves before computing timestamps, keeping
    the off path free of even a ``perf_counter()`` call.
    """

    def __init__(self, ring_capacity: int = RING_CAPACITY):
        self.enabled = False
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_capacity
        )
        self._open: dict[str, Any] | None = None
        # deploy server: per-round result-arrival timestamps
        # (rank -> perf_counter seconds), the straggler-attribution and
        # critical-path inputs the close path already collects
        self._arrivals: dict[int, float] = {}
        self._rounds = 0

    # -- round lifecycle ---------------------------------------------------

    def begin_round(self, round_idx: int, path: str = "stacked",
                    rounds: int = 1) -> None:
        """Open the round's attribution window. ``path`` names the round
        body that will run (``stacked``/``bulk``/``fused``/``sharded``/
        ``personal``/``deploy``); ``rounds`` > 1 means this window spans
        a fused block of that many rounds and the per-round histogram
        observations are divided accordingly (the same normalization
        ``PerfMonitor.note_block`` applies to ``perf.round_wall_s``)."""
        if not self.enabled:
            return
        with self._lock:
            self._open = {
                "round": int(round_idx),
                "path": path,
                "rounds": max(1, int(rounds)),
                "t0": time.perf_counter(),
                "phases": {},
            }
            self._arrivals = {}

    def phase(self, name: str, seconds: float) -> None:
        """Attribute ``seconds`` to ``name`` inside the open round
        (accumulating — eval and checkpoint legs may land in several
        pieces). ``name`` must be in :data:`PHASES`; an unknown phase is
        a programming error, not a metric to invent."""
        if not self.enabled:
            return
        if name not in PHASES:
            raise ValueError(
                f"unknown anatomy phase {name!r}; the vocabulary is "
                f"fixed (docs/OBSERVABILITY.md): {PHASES}"
            )
        with self._lock:
            if self._open is None:
                return
            p = self._open["phases"]
            p[name] = p.get(name, 0.0) + max(0.0, float(seconds))

    def note_arrival(self, rank: int, ts: float | None = None) -> None:
        """Deploy server: timestamp a client result's arrival (one host
        clock read on the receive edge — the straggler-attribution
        input)."""
        if not self.enabled:
            return
        with self._lock:
            if self._open is None:
                return
            self._arrivals.setdefault(
                int(rank), time.perf_counter() if ts is None else ts
            )

    def end_round(self, wall_s: float | None = None) -> dict | None:
        """Close the window: compute ``host_gap`` as the residual
        between the explicit phases and the round wall (clamped at 0 —
        clock jitter may oversum by microseconds), emit the
        ``perf.phase.<name>_s`` histograms (per-round normalized for
        fused blocks) + the ``perf.phase.dominant`` gauge, and append
        the entry to the ``/tracez`` ring. Returns the ring entry (None
        while disabled / unopened)."""
        if not self.enabled:
            return None
        with self._lock:
            ent = self._open
            self._open = None
            if ent is None:
                return None
            wall = (time.perf_counter() - ent["t0"]
                    if wall_s is None else float(wall_s))
            phases = ent["phases"]
            explicit = sum(phases.values())
            phases["host_gap"] = max(0.0, wall - explicit)
            k = ent["rounds"]
            dominant = max(phases, key=phases.get) if phases else None
            entry = {
                "round": ent["round"],
                "path": ent["path"],
                "rounds": k,
                "wall_s": wall,
                "phases": {n: phases[n] for n in PHASES if n in phases},
                "dominant": dominant,
                "ts": time.time(),
            }
            self._ring.append(entry)
            self._rounds += 1
        m = telemetry.METRICS
        for name, sec in phases.items():
            m.observe(f"perf.phase.{name}_s", sec / k)
        if dominant is not None:
            m.gauge("perf.phase.dominant", float(PHASES.index(dominant)))
        return entry

    def amend_last(self, name: str, seconds: float) -> None:
        """Attribute ``seconds`` to ``name`` on the LAST closed ring
        entry — the fused drivers close each block's entry at the
        pipeline flush and only then run the boundary eval/checkpoint,
        so those phases amend the block they belong to. The entry's
        wall grows by the same amount: attribution stays conserved
        (phases still sum to wall_s) and ``host_gap`` is untouched."""
        if not self.enabled:
            return
        if name not in PHASES:
            raise ValueError(
                f"unknown anatomy phase {name!r}; the vocabulary is "
                f"fixed (docs/OBSERVABILITY.md): {PHASES}"
            )
        sec = max(0.0, float(seconds))
        with self._lock:
            if not self._ring:
                return
            e = self._ring[-1]
            e["phases"][name] = e["phases"].get(name, 0.0) + sec
            e["wall_s"] += sec
            e["dominant"] = max(e["phases"], key=e["phases"].get)
        telemetry.METRICS.observe(f"perf.phase.{name}_s", sec)

    # -- straggler + critical path (deploy server, rank 0) -----------------

    def attribute_stragglers(
        self, round_idx: int, t_sync: float, t_close: float,
        t_agg_s: float = 0.0,
    ) -> int | None:
        """Attribute the closed round's barrier wait to its slowest
        contributors from the arrival timestamps collected by
        :meth:`note_arrival`, and emit the per-round critical path.

        - ``perf.straggler_wait_s`` — seconds the round barrier spent
          waiting after the FIRST result had already arrived (the time
          bought by fixing the slowest contributor);
        - ``perf.straggler.rank<r>`` — each contributor's margin behind
          the first arrival, capped by the ``gauge_labeled``
          cardinality machinery so a 10k-client world stays bounded;
        - ``perf.critical_path_s`` — sync → slowest-contributor arrival
          → aggregate, the longest dependent chain through the round;
        - one ``critical_path`` tracer event carrying the segments,
          which ``scripts/merge_trace.py`` renders as a dedicated track.

        Returns the dominant straggler's rank (None without >= 2
        arrivals — a single contributor has no barrier to wait on).
        """
        if not self.enabled:
            return None
        with self._lock:
            arrivals = dict(self._arrivals)
        if not arrivals:
            return None
        first = min(arrivals.values())
        last_rank = max(arrivals, key=arrivals.get)
        last = arrivals[last_rank]
        m = telemetry.METRICS
        if len(arrivals) >= 2:
            m.observe("perf.straggler_wait_s", last - first)
            for r, at in arrivals.items():
                m.gauge_labeled("perf.straggler", f"rank{r}", at - first)
        critical = (last - t_sync) + t_agg_s
        m.gauge("perf.critical_path_s", max(0.0, critical))
        tr = telemetry.TRACER
        if tr is not None:
            tr.event(
                "critical_path",
                round=int(round_idx),
                rank_path=int(last_rank),
                sync_to_result_s=max(0.0, last - t_sync),
                straggler_wait_s=max(0.0, last - first),
                aggregate_s=max(0.0, t_agg_s),
                total_s=max(0.0, critical),
                closed_after_s=max(0.0, t_close - t_sync),
            )
        return last_rank if len(arrivals) >= 2 else None

    # -- /tracez -----------------------------------------------------------

    def ring_snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def tracez(self, rank: int = 0) -> dict:
        """The ``/tracez`` section payload (core/export.py): the last-N
        closed rounds' anatomy entries, newest last."""
        with self._lock:
            entries = [dict(e) for e in self._ring]
            return {
                "rank": rank,
                "phases": list(PHASES),
                "capacity": self._ring.maxlen,
                "rounds": self._rounds,
                "entries": entries,
            }

    def reset(self) -> None:
        with self._lock:
            self.enabled = False
            self._ring.clear()
            self._open = None
            self._arrivals = {}
            self._rounds = 0


class BreachProfiler:
    """One-shot ``jax.profiler.trace`` windows fired on degradation.

    Armed by ``--profile_on_breach`` (requires ``--slo`` or
    ``--mem_headroom_warn`` — without a breach source the trigger can
    never fire, which parse-time validation rejects). Each trigger:

    - is a breach *transition* (ok -> breach from the SLO engine) or the
      memory plane's one-shot ``mem_headroom`` crossing — never one
      capture per breached tick;
    - starts ``jax.profiler.start_trace`` into
      ``<telemetry_dir>/profiles/breach_<n>_<reason>/`` and stops it
      ``window_s`` later from a daemon timer thread, so a capture never
      blocks the round loop or extends a round deadline
      (docs/FAULT_TOLERANCE.md);
    - records one ``breach_profile`` flight event linking the breach to
      the artifact path, and writes a ``breach.json`` manifest inside
      the artifact dir;
    - respects the ``max_captures`` cap and a ``cooldown_s`` gap between
      windows — skipped triggers count ``profile.skipped`` and record a
      ``breach_profile_skipped`` flight event instead of silently
      vanishing.

    ``jax.profiler`` allows ONE live session per process: a trigger
    while another session is active (``--profile_rounds``'s
    ``RoundProfiler``, or an unfinished breach window) is a skip, and a
    start/stop failure marks the profiler broken (``profile.failed``)
    rather than crashing the run — the same containment contract
    ``core/perf.py`` uses. ``clock``/``timer`` are injectable so the
    cap + cooldown semantics are testable without wall sleeps.
    """

    def __init__(
        self,
        out_dir: str,
        window_s: float = 5.0,
        max_captures: int = 3,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        clock=time.monotonic,
        timer=None,
    ):
        if not (window_s > 0):
            raise ValueError(
                f"--profile_window_s must be > 0, got {window_s!r}"
            )
        if max_captures < 1:
            raise ValueError(
                f"--profile_max_captures must be >= 1, got "
                f"{max_captures!r}"
            )
        self.out_dir = out_dir
        self.window_s = float(window_s)
        self.max_captures = int(max_captures)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._timer = timer or self._default_timer
        self._lock = threading.Lock()
        self._captures = 0
        self._active_path: str | None = None
        self._last_end: float | None = None
        self._broken = False
        self._pending: threading.Timer | None = None

    @staticmethod
    def _default_timer(delay_s: float, fn) -> threading.Timer:
        t = threading.Timer(delay_s, fn)
        t.daemon = True
        t.start()
        return t

    @property
    def captures(self) -> int:
        return self._captures

    @property
    def active(self) -> bool:
        return self._active_path is not None

    def _skip(self, reason: str, why: str) -> None:
        telemetry.METRICS.inc("profile.skipped")
        telemetry.RECORDER.record(
            "breach_profile_skipped", reason=reason, why=why
        )

    def on_breach(self, reason: str, **attrs) -> str | None:
        """Fire one capture window for this breach (returns the artifact
        directory, or None for a skip/failure)."""
        import jax

        with self._lock:
            if self._broken:
                self._skip(reason, "profiler broken")
                return None
            if self._active_path is not None:
                self._skip(reason, "capture window already open")
                return None
            if self._captures >= self.max_captures:
                self._skip(
                    reason,
                    f"capture cap spent ({self.max_captures})",
                )
                return None
            now = self._clock()
            if (self._last_end is not None
                    and now - self._last_end < self.cooldown_s):
                self._skip(
                    reason,
                    f"cooldown ({self.cooldown_s}s since last window)",
                )
                return None
            n = self._captures + 1
            slug = re.sub(r"[^0-9a-zA-Z_.-]+", "_", reason)[:80]
            path = os.path.join(self.out_dir,
                                f"breach_{n}_{slug}")
            try:
                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
            except Exception as err:
                # one live session per process: a collision with
                # --profile_rounds (or a broken runtime) must contain,
                # not crash — the run matters more than its profile
                self._broken = True
                telemetry.METRICS.inc("profile.failed")
                telemetry.RECORDER.record(
                    "breach_profile_failed", reason=reason,
                    error=repr(err),
                )
                return None
            self._captures = n
            self._active_path = path
            telemetry.METRICS.inc("profile.captures")
            telemetry.METRICS.gauge("profile.active", 1.0)
            telemetry.RECORDER.record(
                "breach_profile", reason=reason, path=path,
                window_s=self.window_s, capture=n, **attrs,
            )
            try:
                with open(os.path.join(path, "breach.json"), "w") as f:
                    json.dump(
                        {
                            "reason": reason,
                            "capture": n,
                            "window_s": self.window_s,
                            "ts": time.time(),
                            **{k: repr(v) if not isinstance(
                                v, (int, float, str, bool, type(None))
                            ) else v for k, v in attrs.items()},
                        },
                        f, indent=2,
                    )
            except OSError:
                pass  # the manifest must never fail the capture
            self._pending = self._timer(self.window_s, self._stop)
            return path

    def _stop(self) -> None:
        import jax

        with self._lock:
            path = self._active_path
            if path is None:
                return
            self._active_path = None
            self._pending = None
            self._last_end = self._clock()
            try:
                jax.profiler.stop_trace()
            except Exception as err:
                self._broken = True
                telemetry.METRICS.inc("profile.failed")
                telemetry.RECORDER.record(
                    "breach_profile_failed", path=path, error=repr(err)
                )
                telemetry.METRICS.gauge("profile.active", 0.0)
                return
            telemetry.METRICS.gauge("profile.active", 0.0)
            telemetry.RECORDER.record("breach_profile_done", path=path)

    def close(self) -> None:
        """Stop any open window now (shutdown path — a dangling
        ``jax.profiler`` session would break the next run's profilers
        in-process)."""
        with self._lock:
            pending = self._pending
        if pending is not None:
            try:
                pending.cancel()
            except Exception:
                pass
        self._stop()


def fetch_corrected_time(fn, *args, n: int = 30,
                         warmup: int = 2) -> float:
    """The ONE amortized device-timing path the offline profiling
    scripts share (``scripts/profile_round.py`` and friends used to
    hand-roll three drifting copies of this loop): run ``warmup``
    dispatches, measure the D2H fetch cost of one scalar leaf, then
    time ``n`` dispatches closed by a single scalar fetch — the fetch
    cost is subtracted so the figure is device execution, not host
    turnaround. Returns per-call seconds.

    This times a *compiled callable in a loop*; the live per-round
    attribution is :class:`RoundAnatomy`, which never adds syncs. The
    scripts pair this with :class:`~fedml_tpu.core.memscope.ProgramSite`
    so their compiles land in the same ``mem.program.*`` accounting as
    the production sims."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    out = None
    for _ in range(max(1, warmup)):
        out = fn(*args)
    jax.block_until_ready(out)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(jax.device_get(jnp.sum(leaf))))
    fs = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(np.asarray(jax.device_get(jnp.sum(leaf))))
        fs.append(time.perf_counter() - t0)
    fetch = min(fs)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(jax.device_get(jnp.sum(leaf))))
    wall = time.perf_counter() - t0
    return max(wall - fetch, wall / 2) / n


#: Process-global anatomy plane — disabled until :func:`configure`.
ANATOMY = RoundAnatomy()
_BREACH: BreachProfiler | None = None


def breach_profiler() -> BreachProfiler | None:
    return _BREACH


def notify_mem_headroom(**fields) -> None:
    """The memory plane's one-shot ``mem_headroom`` crossing forwards
    here (lazily — memscope only calls this if anatomy was ever
    imported), the second breach-profile trigger alongside SLO
    transitions."""
    p = _BREACH
    if p is not None:
        p.on_breach("mem_headroom", **fields)


def _on_slo_transition(spec, breaching: bool, value) -> None:
    if breaching and _BREACH is not None:
        _BREACH.on_breach(
            f"slo_{spec.slug}", slo=spec.describe(),
            scope=spec.scope, value=value,
        )


def configure(
    anatomy: bool = False,
    ring_capacity: int = RING_CAPACITY,
    profile_on_breach: bool = False,
    profile_window_s: float = 5.0,
    profile_max_captures: int = 3,
    cooldown_s: float = DEFAULT_COOLDOWN_S,
) -> None:
    """Arm the round-anatomy plane for THIS process (idempotent; call
    AFTER :func:`telemetry.configure` — the breach profiler needs the
    telemetry dir and subscribes to the SLO engine built there).

    ``anatomy=True`` switches phase attribution + the ``/tracez`` ring
    on. ``profile_on_breach=True`` arms the :class:`BreachProfiler`
    under ``<telemetry_dir>/profiles/`` and registers its SLO-breach
    listener; without a telemetry dir there is nowhere to write the
    artifact, so arming requires one (run.py guarantees it the same way
    ``--trace`` does)."""
    global _BREACH
    if anatomy:
        if ANATOMY._ring.maxlen != ring_capacity:
            ANATOMY._ring = collections.deque(
                ANATOMY._ring, maxlen=ring_capacity
            )
        ANATOMY.enabled = True
    if profile_on_breach and _BREACH is None:
        tdir = telemetry.artifact_dir()
        if tdir is None:
            raise ValueError(
                "--profile_on_breach needs a telemetry dir for its "
                "artifacts (configure telemetry first)"
            )
        _BREACH = BreachProfiler(
            os.path.join(tdir, "profiles"),
            window_s=profile_window_s,
            max_captures=profile_max_captures,
            cooldown_s=cooldown_s,
        )
        eng = telemetry.slo_engine()
        if eng is not None:
            eng.add_transition_listener(_on_slo_transition)


def reset() -> None:
    """Return to the all-disabled state (``telemetry.shutdown()`` calls
    this lazily, like the memory plane's reset)."""
    global _BREACH
    if _BREACH is not None:
        try:
            _BREACH.close()
        except Exception:
            pass
        _BREACH = None
    ANATOMY.reset()

"""Dynamic world membership: the server-side ledger of who is in the run.

The reference FedML's MQTT/cross-device path exists because real
federated populations churn — devices appear, vanish, and reappear
continuously — yet both the reference's MPI path and (until this module)
this runtime froze the world at launch: a JOIN from a rank outside the
initial ``world_size`` was silently dropped, and every per-rank state
array assumed ``rank < world_size``. FedJAX (arxiv 2108.02117) stops at
fixed-population simulation; the ROADMAP north-star ("millions of
users") demands a world that grows and shrinks mid-run.

:class:`MembershipLedger` is the single source of truth the
:class:`~fedml_tpu.algorithms.distributed_fedavg.FedAvgServerActor`
consults (docs/FAULT_TOLERANCE.md "Elastic membership"):

- **Admission** — a ``MSG_TYPE_C2S_JOIN`` from a rank *beyond* the
  launch world is admitted with a stable client id derived purely from
  its rank (``(rank - 1) % num_clients`` — the same id it would have
  been assigned had it been present at launch, so a late joiner derives
  the same seeded data shards as an original member of that rank).
  Per-round WORK assignment stays the reference's: the server samples
  a cohort of client ids and deals it over the member ranks by their
  position in the SORTED active set — so admission order cannot
  perturb any assignment (the slot map depends only on the member
  set), a full world trains each rank on exactly its rank-derived id,
  and a shrunken world keeps every sampled cohort entry covered by
  re-dealing rather than leaving a departed rank's slice untrained.
  Admission takes effect at the NEXT round boundary
  (``active_from = current_round + 1``): a member admitted mid-round
  must not raise the in-flight round's quorum bar for a sync it never
  received.
- **Graceful departure** — ``MSG_TYPE_C2S_LEAVE`` marks the rank LEFT:
  distinct from a crash (no restart budget spent, no dead-peer flight
  dump, no quarantine suspicion). A LEFT rank may JOIN again later.
- **Eviction** — permanent ban: subsequent JOINs are rejected and
  counted (``membership.rejected_joins``). Nothing un-evicts a rank
  short of a fresh run directory.

State is four parallel int32 arrays (``ranks / status / client_id /
active_from``) so the ledger rides the server's
:class:`~fedml_tpu.utils.checkpoint.RoundCheckpointer` composite payload
— a SIGKILLed server does not forget who joined, left, or was banned,
and the arrays restore across a *different* relaunch ``world_size``
(the checkpoint, not the launch flag, is authoritative for membership).
"""

from __future__ import annotations

import threading

import numpy as np

from fedml_tpu.core import telemetry

#: member status codes (the ``status`` checkpoint array)
ACTIVE = 0
LEFT = 1
EVICTED = 2

_STATUS_NAMES = {ACTIVE: "active", LEFT: "left", EVICTED: "evicted"}


class MembershipLedger:
    """Per-rank membership state for an elastic world.

    Thread-safe: admission/leave/evict arrive on transport dispatch
    threads while round closes read the active set under the server's
    own lock."""

    def __init__(self, world_size: int, num_clients: int):
        self.num_clients = num_clients
        self._lock = threading.Lock()
        # rank -> [status, client_id, active_from]
        self._members: dict[int, list[int]] = {
            r: [ACTIVE, self.client_id_for(r), 0]
            for r in range(1, world_size)
        }

    # -- identity ----------------------------------------------------------

    def client_id_for(self, rank: int) -> int:
        """The rank's stable client identity: purely rank-derived, so a
        late joiner gets the SAME id (and therefore the same seeded data
        partition) it would have received at launch — admission order
        cannot perturb anyone's shards."""
        return (rank - 1) % max(1, self.num_clients)

    def _n_active_locked(self) -> int:
        """Caller holds ``self._lock``. The one definition of 'counts
        as active' the ``membership.active`` gauge reports after every
        transition."""
        return sum(
            1 for v in self._members.values() if v[0] == ACTIVE
        )

    # -- transitions -------------------------------------------------------

    def admit(self, rank: int, round_idx: int, *,
              immediate: bool = False) -> str:
        """Process a JOIN. Returns the verdict:

        - ``"member"`` — already an active member (a rejoin after a
          crash; the caller runs the JOIN/WELCOME rejoin protocol).
        - ``"admitted"`` — new or returning (LEFT) rank, now ACTIVE
          from round ``round_idx + 1`` (or ``round_idx`` itself with
          ``immediate`` — the caller's round is not in flight, so there
          is no quorum bar the admission could retroactively raise).
        - ``"rejected"`` — permanently evicted; the JOIN is dropped
          (and never ACKed, so the client times out loudly instead of
          idling forever against a world that will never serve it).
        """
        with self._lock:
            rec = self._members.get(rank)
            if rec is not None and rec[0] == EVICTED:
                telemetry.METRICS.inc("membership.rejected_joins")
                telemetry.RECORDER.record(
                    "join_rejected", peer=rank, round=round_idx
                )
                return "rejected"
            if rec is not None and rec[0] == ACTIVE:
                return "member"
            returning = rec is not None
            self._members[rank] = [
                ACTIVE, self.client_id_for(rank),
                round_idx if immediate else round_idx + 1
            ]
            n_active = self._n_active_locked()
        telemetry.METRICS.inc("membership.joins")
        telemetry.METRICS.gauge("membership.active", n_active)
        telemetry.RECORDER.record(
            "member_admitted", peer=rank, round=round_idx,
            returning=returning,
        )
        return "admitted"

    def leave(self, rank: int, round_idx: int) -> bool:
        """Graceful departure. Returns True if the rank was active."""
        with self._lock:
            rec = self._members.get(rank)
            if rec is None or rec[0] != ACTIVE:
                return False
            rec[0] = LEFT
            rec[2] = round_idx
            n_active = self._n_active_locked()
        telemetry.METRICS.inc("membership.leaves")
        telemetry.METRICS.gauge("membership.active", n_active)
        telemetry.RECORDER.record("member_left", peer=rank,
                                  round=round_idx)
        return True

    def evict(self, rank: int, round_idx: int) -> None:
        """Permanent ban; future JOINs from this rank are rejected."""
        with self._lock:
            rec = self._members.get(rank)
            if rec is not None and rec[0] == EVICTED:
                return
            cid = (rec[1] if rec is not None
                   else self.client_id_for(rank))
            self._members[rank] = [EVICTED, cid, round_idx]
            n_active = self._n_active_locked()
        telemetry.METRICS.inc("membership.evictions")
        telemetry.METRICS.gauge("membership.active", n_active)
        telemetry.RECORDER.record("member_evicted", peer=rank,
                                  round=round_idx)

    # -- queries -----------------------------------------------------------

    def active_ranks(self, round_idx: int | None = None) -> list[int]:
        """Sorted ACTIVE ranks. With ``round_idx``, only members whose
        admission has taken effect (``active_from <= round_idx``) — a
        mid-round admission waits for the next boundary."""
        with self._lock:
            return sorted(
                r for r, v in self._members.items()
                if v[0] == ACTIVE
                and (round_idx is None or v[2] <= round_idx)
            )

    def is_active(self, rank: int) -> bool:
        with self._lock:
            rec = self._members.get(rank)
            return rec is not None and rec[0] == ACTIVE

    def status(self, rank: int) -> str | None:
        with self._lock:
            rec = self._members.get(rank)
            return None if rec is None else _STATUS_NAMES[rec[0]]

    def client_id(self, rank: int) -> int:
        with self._lock:
            rec = self._members.get(rank)
            return (rec[1] if rec is not None
                    else self.client_id_for(rank))

    def summary(self) -> dict:
        """Run-summary view: rank lists per status."""
        with self._lock:
            out: dict[str, list[int]] = {
                name: [] for name in _STATUS_NAMES.values()
            }
            for r, v in sorted(self._members.items()):
                out[_STATUS_NAMES[v[0]]].append(r)
            return out

    # -- checkpoint persistence (utils/checkpoint.py) ----------------------

    def state_arrays(self) -> dict[str, np.ndarray]:
        """Parallel int32 arrays for the round checkpointer (rides the
        server's composite payload next to ServerState + reputation)."""
        with self._lock:
            ranks = sorted(self._members)
            return {
                "ranks": np.asarray(ranks, np.int32),
                "status": np.asarray(
                    [self._members[r][0] for r in ranks], np.int32
                ),
                "client_id": np.asarray(
                    [self._members[r][1] for r in ranks], np.int32
                ),
                "active_from": np.asarray(
                    [self._members[r][2] for r in ranks], np.int32
                ),
            }

    def load_arrays(self, blob: dict) -> None:
        """Restore from a checkpoint — REPLACES the launch-derived
        membership entirely: after a server restart the checkpoint, not
        the relaunch ``world_size``, is authoritative (that is what lets
        a grown/shrunk world survive a SIGKILL)."""
        ranks = np.asarray(blob["ranks"], np.int32).ravel()
        status = np.asarray(blob["status"], np.int32).ravel()
        cid = np.asarray(blob["client_id"], np.int32).ravel()
        active_from = np.asarray(blob["active_from"], np.int32).ravel()
        if not (len(ranks) == len(status) == len(cid)
                == len(active_from)):
            raise ValueError(
                "membership checkpoint arrays disagree on length: "
                f"{len(ranks)}/{len(status)}/{len(cid)}/"
                f"{len(active_from)}"
            )
        with self._lock:
            self._members = {
                int(r): [int(s), int(c), int(a)]
                for r, s, c, a in zip(ranks, status, cid, active_from)
            }

"""Byzantine-robust aggregation as pure functions on stacked client deltas.

TPU-native redesign of the reference ``RobustAggregator``
(``fedml_core/robustness/robust_aggregation.py:32-90``): norm-diff clipping,
weak-DP gaussian noise, and coordinate-wise median. The reference applies
these per-client in Python; here each defense is one vectorized op over the
stacked ``[C, ...]`` delta pytree so it fuses into the aggregation pass.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fedml_tpu.core import tree as T

Pytree = Any


def clip_deltas_by_norm(stacked_deltas: Pytree, clip: float) -> Pytree:
    """Scale each client's delta to at most L2 norm ``clip`` (reference
    ``norm_diff_clipping``, ``robust_aggregation.py:38-49``)."""
    norms = jax.vmap(T.tree_l2_norm)(stacked_deltas)  # [C]
    scale = jnp.minimum(1.0, clip / jnp.maximum(norms, 1e-12))
    return jax.tree.map(
        lambda x: x * scale.reshape((-1,) + (1,) * (x.ndim - 1)), stacked_deltas
    )


def add_gaussian_noise(tree_: Pytree, stddev: float, rng: jax.Array) -> Pytree:
    """Weak-DP defense: additive gaussian noise on the aggregate (reference
    ``add_noise``, ``robust_aggregation.py:51-55``)."""
    leaves, treedef = jax.tree.flatten(tree_)
    keys = jax.random.split(rng, len(leaves))
    noisy = [
        l + stddev * jax.random.normal(k, l.shape, l.dtype)
        for l, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noisy)


def coordinate_median(stacked: Pytree) -> Pytree:
    """Coordinate-wise median over the client axis (reference
    ``coordinate_median_agg``, ``robust_aggregation.py:57-66``)."""
    return jax.tree.map(lambda x: jnp.median(x, axis=0), stacked)


def trimmed_mean(stacked: Pytree, trim_frac: float = 0.1) -> Pytree:
    """Coordinate-wise trimmed mean (standard robust-FL baseline; not in the
    reference but a natural companion to the median defense)."""

    def leaf(x):
        c = x.shape[0]
        # clamp so at least one row survives: k >= c/2 (over-trimming a
        # small cohort) would slice an empty range and average to NaN —
        # the defense must degrade to the median-most rows, not poison
        # the aggregate it exists to protect
        k = min(int(c * trim_frac), (c - 1) // 2)
        if k == 0:
            return jnp.mean(x, axis=0)
        s = jnp.sort(x, axis=0)
        return jnp.mean(s[k : c - k], axis=0)

    return jax.tree.map(leaf, stacked)

"""Byzantine-robust aggregation as pure functions on stacked client deltas.

TPU-native redesign of the reference ``RobustAggregator``
(``fedml_core/robustness/robust_aggregation.py:32-90``): norm-diff clipping,
weak-DP gaussian noise, and coordinate-wise median. The reference applies
these per-client in Python; here each defense is one vectorized op over the
stacked ``[C, ...]`` delta pytree so it fuses into the aggregation pass.

Beyond the reference's coordinate-wise defenses, this module carries the
*selection/scoring* family used against actively malicious clients
(:mod:`fedml_tpu.core.adversary` injects them deterministically):

- **Krum / multi-Krum** (Blanchard et al., NeurIPS'17) — pairwise-
  distance selection; the ``[C, C]`` distance matrix is one matmul over
  the flattened deltas so it fuses on TPU.
- **FLTrust-style cosine trust weighting** (Cao et al., NDSS'21) — each
  delta is reweighted by its ReLU'd cosine similarity to a server
  reference delta and norm-matched to it. Without a server root
  dataset the reference defaults to the coordinate-median of the
  cohort's deltas (itself a robust statistic).
- **Anomaly scores** — per-client L2-norm z-score, cosine to the
  mean/median delta, and a near-duplicate (collusion) signal; the
  cross-round reputation plane (``fedml_tpu.core.reputation``)
  accumulates these.

:class:`DefensePipeline` assembles the families into the single
aggregation-rule hook both round programs share
(:func:`fedml_tpu.algorithms.fedavg.server_update`). Its ``mean``
configuration with clip/noise off is byte-identical to the plain
weighted mean — the defense plane is invisible until switched on.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from fedml_tpu.core import tree as T

Pytree = Any


def clip_deltas_by_norm(stacked_deltas: Pytree, clip: float) -> Pytree:
    """Scale each client's delta to at most L2 norm ``clip`` (reference
    ``norm_diff_clipping``, ``robust_aggregation.py:38-49``).

    Dtype-preserving: the scale is computed in f32 but each leaf is cast
    back to its own dtype (a bf16 leaf under mixed precision used to
    silently upcast the whole stacked tree to f32). Zero-size leaves
    (and leafless trees) pass through untouched — ``vmap`` over an
    empty tree cannot infer a batch size."""
    if not jax.tree.leaves(stacked_deltas):
        return stacked_deltas
    norms = jax.vmap(T.tree_l2_norm)(stacked_deltas)  # [C]
    scale = jnp.minimum(1.0, clip / jnp.maximum(norms, 1e-12))

    def leaf(x):
        if x.size == 0:
            return x
        s = scale.reshape((-1,) + (1,) * (x.ndim - 1))
        return (x.astype(jnp.float32) * s).astype(x.dtype)

    return jax.tree.map(leaf, stacked_deltas)


def add_gaussian_noise(tree_: Pytree, stddev: float, rng: jax.Array) -> Pytree:
    """Weak-DP defense: additive gaussian noise on the aggregate (reference
    ``add_noise``, ``robust_aggregation.py:51-55``)."""
    leaves, treedef = jax.tree.flatten(tree_)
    keys = jax.random.split(rng, len(leaves))
    noisy = [
        l + stddev * jax.random.normal(k, l.shape, l.dtype)
        for l, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noisy)


def coordinate_median(stacked: Pytree,
                      valid: jax.Array | None = None) -> Pytree:
    """Coordinate-wise median over the client axis (reference
    ``coordinate_median_agg``, ``robust_aggregation.py:57-66``).

    With ``valid`` (``[C]`` bool, possibly traced) the median is taken
    over the VALID rows only — the bucket-padded elastic rounds
    (:mod:`fedml_tpu.core.elastic`) pad the cohort with zero-weight
    rows that must not perturb the coordinate statistics: invalid rows
    sort to ``+inf`` and the two middle elements are gathered at the
    dynamic valid count, so a change in that count never retraces."""
    if valid is None:
        return jax.tree.map(lambda x: jnp.median(x, axis=0), stacked)
    n = jnp.sum(valid.astype(jnp.int32))
    lo_i = (n - 1) // 2
    hi_i = n // 2

    def leaf(x):
        m = valid.reshape((-1,) + (1,) * (x.ndim - 1))
        inf = jnp.asarray(jnp.inf, x.dtype)
        s = jnp.sort(jnp.where(m, x, inf), axis=0)
        lo = jnp.take(s, lo_i, axis=0)
        hi = jnp.take(s, hi_i, axis=0)
        # (lo + hi) / 2 == jnp.median's interpolated midpoint bit-for-
        # bit: halving commutes with the one rounding of the sum
        return ((lo + hi) / 2).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


def trimmed_mean(stacked: Pytree, trim_frac: float = 0.1,
                 valid: jax.Array | None = None) -> Pytree:
    """Coordinate-wise trimmed mean (standard robust-FL baseline; not in the
    reference but a natural companion to the median defense).

    With ``valid`` the trim count derives from the VALID row count (the
    bucket-padded elastic path): invalid rows sort to ``+inf`` and the
    mean runs over the ``[k, n-k)`` band of the valid prefix — so the
    masked rows are provably content-blind (they are replaced before
    the sort and excluded from the band sum; pinned exactly in
    ``tests/test_elastic.py``). Versus the UNPADDED static path the
    live terms are identical but XLA may associate the wider reduce
    differently (~1 ulp; see core/elastic.py for the parity tiers)."""

    def leaf_static(x):
        c = x.shape[0]
        # clamp so at least one row survives: k >= c/2 (over-trimming a
        # small cohort) would slice an empty range and average to NaN —
        # the defense must degrade to the median-most rows, not poison
        # the aggregate it exists to protect
        k = min(int(c * trim_frac), (c - 1) // 2)
        if k == 0:
            return jnp.mean(x, axis=0)
        s = jnp.sort(x, axis=0)
        return jnp.mean(s[k : c - k], axis=0)

    if valid is None:
        return jax.tree.map(leaf_static, stacked)

    c_max = jax.tree.leaves(stacked)[0].shape[0]
    # trim count per possible live count, computed host-side with the
    # SAME Python-float formula as leaf_static — deriving k in traced
    # f32 can disagree (f32(10) * f32(0.3) rounds to 3.0000001, so the
    # padded path would trim 3 rows where the unpadded path trims
    # int(10 * 0.3) == 2) and break padded-vs-unpadded parity outright
    ks = jnp.asarray(
        [max(0, min(int(c * trim_frac), (c - 1) // 2))
         for c in range(c_max + 1)], jnp.int32,
    )
    n = jnp.sum(valid.astype(jnp.int32))
    k = ks[n]
    idx = jnp.arange(c_max)
    band = (idx >= k) & (idx < n - k)  # [C] rows kept after trimming

    # one formula covers k == 0 too: the band is then the whole valid
    # prefix of the sorted rows, whose sum is the plain mean's terms
    def leaf(x):
        m = valid.reshape((-1,) + (1,) * (x.ndim - 1))
        inf = jnp.asarray(jnp.inf, x.dtype)
        s = jnp.sort(jnp.where(m, x, inf), axis=0)
        b = band.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.sum(
            jnp.where(b, s, jnp.zeros((), x.dtype)), axis=0
        ) / (n - 2 * k).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


# ---------------------------------------------------------------------------
# selection / scoring defenses
# ---------------------------------------------------------------------------


def flatten_clients(stacked: Pytree) -> jax.Array:
    """``[C, D]`` f32 matrix of flattened client deltas — the shared
    substrate of every distance/cosine defense (one gather, then every
    score is a matmul or row reduction that fuses on TPU)."""
    x = jax.vmap(T.tree_vectorize)(stacked)
    return x.astype(jnp.float32)


def pairwise_sq_dists_rows(x_rows: jax.Array, rows: jax.Array,
                           x_all: jax.Array) -> jax.Array:
    """``[R, C]`` row block of the squared-distance matrix: distances
    from ``x_rows`` (rows ``rows`` of the cohort) to every client in
    ``x_all``. This is the mesh-sharded form of
    :func:`pairwise_sq_dists` — each shard computes ONLY its own rows'
    block (``x_loc @ x_all.T``), so the ``O(C^2 D)`` gram that
    dominates Krum at C=1000 partitions over the client axis while the
    per-element dot products keep the full, unpartitioned ``D``
    contraction (the reassociation-free property the bitwise
    sharded-vs-replicated selection parity rests on)."""
    sq_r = jnp.sum(x_rows * x_rows, axis=1)
    sq_a = jnp.sum(x_all * x_all, axis=1)
    d2 = sq_r[:, None] + sq_a[None, :] - 2.0 * (x_rows @ x_all.T)
    d2 = jnp.maximum(d2, 0.0)  # float error can dip negative
    eye = rows[:, None] == jnp.arange(x_all.shape[0])[None, :]
    return d2 * (1.0 - eye.astype(d2.dtype))  # exact-zero self slots


def pairwise_sq_dists(stacked: Pytree) -> jax.Array:
    """``[C, C]`` squared L2 distances between client deltas, computed
    as ONE gram matmul over the flattened ``[C, D]`` deltas (never a
    python double loop): ``d2_ij = |x_i|^2 + |x_j|^2 - 2 x_i.x_j``.
    The full-matrix special case of :func:`pairwise_sq_dists_rows`
    (one implementation, so the replicated and row-sharded paths
    cannot drift)."""
    x = flatten_clients(stacked)
    return pairwise_sq_dists_rows(x, jnp.arange(x.shape[0]), x)


#: large-but-finite stand-in for "not a neighbor" in the Krum scores —
#: summing a handful of these stays representable in f32 where a true
#: inf would flatten every score to inf and make argmin arbitrary
_FAR = 1e30


def krum_scores(d2: jax.Array, num_adversaries: int,
                valid: jax.Array | None = None,
                n_valid: jax.Array | None = None) -> jax.Array:
    """Krum score per client: the sum of its ``C - f - 2`` smallest
    distances to OTHER clients (Blanchard et al.; lower = more central).
    Degenerate cohorts (``C <= f + 2``) fall back to the single nearest
    neighbor so the selection stays defined. ``valid`` (``[C]`` bool)
    marks rows eligible for selection: zero-weight rows — e.g. the
    non-finite screen's healed zero deltas — would otherwise form an
    exact-zero-distance cluster that Krum scores as maximally central
    (a screening-induced DoS on the selection defenses), so distances
    to and from invalid rows count as :data:`_FAR`, pushing them to
    the bottom of every ranking while valid rows still order by their
    real neighborhoods.

    ``n_valid`` (traced scalar) switches the neighbor count to derive
    from the VALID row count instead of the static row count — required
    on the bucket-padded elastic path, where the padded ``C`` would
    otherwise pull :data:`_FAR` terms into every valid row's score
    (1e30 absorbs the real distances in f32 and the argmin degenerates
    to row 0). Invalid rows score ``+inf`` so they can never win a
    selection regardless of how small the valid cohort gets."""
    return krum_scores_rows(
        d2, jnp.arange(d2.shape[0]), num_adversaries, valid, n_valid
    )


def krum_scores_rows(d2: jax.Array, rows: jax.Array,
                     num_adversaries: int,
                     valid: jax.Array | None = None,
                     n_valid: jax.Array | None = None) -> jax.Array:
    """:func:`krum_scores` for a ROW BLOCK of the distance matrix:
    ``d2`` is ``[R, C]`` (this shard's rows against the full cohort),
    ``rows`` the rows' global indices, ``valid`` the FULL ``[C]``
    eligibility mask. Each row's score involves only its own distance
    row — exactly the ops the full-matrix path applies to that row —
    so stacking the shards' blocks reproduces the replicated scores
    bitwise (the sharded-vs-replicated parity
    ``tests/test_compress.py`` pins)."""
    c = d2.shape[1]
    if valid is not None:
        pair_ok = valid[rows][:, None] & valid[None, :]
        # keep the exact-zero self distance
        pair_ok = pair_ok | (rows[:, None] == jnp.arange(c)[None, :])
        d2 = jnp.where(pair_ok, d2, _FAR)
    s = jnp.sort(d2, axis=1)  # column 0 is the exact-zero self distance
    if n_valid is None:
        k = max(1, min(c - 2 - num_adversaries, c - 1))
        return jnp.sum(s[:, 1 : k + 1], axis=1)
    k = jnp.clip(n_valid - 2 - num_adversaries, 1,
                 jnp.maximum(n_valid - 1, 1))
    cols = jnp.arange(c)
    sel = (cols >= 1) & (cols <= k)
    scores = jnp.sum(jnp.where(sel[None, :], s, 0.0), axis=1)
    if valid is not None:
        scores = jnp.where(valid[rows], scores, jnp.inf)
    return scores


def krum(stacked: Pytree, num_adversaries: int,
         weights: jax.Array | None = None,
         n_valid: jax.Array | None = None,
         scores: jax.Array | None = None,
         ) -> tuple[Pytree, jax.Array, jax.Array]:
    """Krum selection: return ``(selected delta, scores, best index)``
    — the single most central client's delta IS the aggregate. Rows
    with zero ``weights`` are never selected. ``n_valid`` (traced)
    switches to the dynamic neighbor count for bucket-padded cohorts.
    ``scores`` short-circuits the distance computation — the
    mesh-sharded path precomputes them blockwise
    (:func:`krum_scores_rows`) and hands the gathered vector in."""
    if scores is None:
        valid = None if weights is None else weights > 0
        scores = krum_scores(pairwise_sq_dists(stacked),
                             num_adversaries, valid, n_valid)
    best = jnp.argmin(scores)
    return jax.tree.map(lambda x: x[best], stacked), scores, best


def multi_krum(stacked: Pytree, weights: jax.Array, num_adversaries: int,
               m: int = 0, n_valid: jax.Array | None = None,
               scores: jax.Array | None = None,
               ) -> tuple[Pytree, jax.Array, jax.Array]:
    """Multi-Krum: weighted mean over the ``m`` best-scored clients
    (``m = 0`` auto-resolves to ``C - f``, clamped to ``[1, C]``).
    Returns ``(aggregate, scores, selected mask)``. Zero-weight rows
    rank last and contribute nothing even if the keep count reaches
    them (their aggregation weight is already 0).

    ``n_valid`` (traced) makes BOTH the neighbor count and the auto
    keep count derive from the valid row count — on a bucket-padded
    cohort the static ``C - f`` would keep every valid row plus padded
    debris instead of dropping the ``f`` most suspect valid rows.
    ``scores`` short-circuits the distance computation (the
    mesh-sharded blockwise path)."""
    c = jax.tree.leaves(stacked)[0].shape[0]
    f = num_adversaries
    if scores is None:
        scores = krum_scores(pairwise_sq_dists(stacked), f, weights > 0,
                             n_valid)
    if n_valid is None:
        m_eff = m if m > 0 else max(1, c - f)
        m_eff = max(1, min(m_eff, c))
        _, idx = jax.lax.top_k(-scores, m_eff)
        mask = jnp.zeros((c,), bool).at[idx].set(True)
    else:
        m_dyn = (jnp.asarray(m) if m > 0
                 else jnp.maximum(1, n_valid - f))
        m_dyn = jnp.clip(m_dyn, 1, n_valid)
        # selection by rank: stable argsort ties break by index, the
        # same order lax.top_k uses on the static path
        order = jnp.argsort(scores)
        rank = jnp.zeros((c,), jnp.int32).at[order].set(
            jnp.arange(c, dtype=jnp.int32)
        )
        mask = rank < m_dyn
    w = jnp.where(mask, weights.astype(jnp.float32), 0.0)
    return T.tree_weighted_mean(stacked, w), scores, mask


def fltrust(stacked: Pytree, ref: Pytree, eps: float = 1e-12,
            weights: jax.Array | None = None
            ) -> tuple[Pytree, jax.Array]:
    """FLTrust-style trust-weighted aggregation against a server
    reference delta ``ref``: trust ``t_i = relu(cos(d_i, ref))``, each
    delta norm-matched to ``|ref|``, aggregate = trust-weighted mean.
    When every trust score is zero (the whole cohort points away from
    the reference) the aggregate degrades to ``ref`` itself rather than
    dividing by zero. Rows with zero ``weights`` (screened results)
    get zero trust. Returns ``(aggregate, trust scores)``."""
    x = flatten_clients(stacked)  # [C, D]
    r = T.tree_vectorize(ref).astype(jnp.float32)  # [D]
    rn = jnp.sqrt(jnp.sum(r * r))
    xn = jnp.sqrt(jnp.sum(x * x, axis=1))  # [C]
    cos = (x @ r) / jnp.maximum(xn * rn, eps)
    trust = jax.nn.relu(cos)
    if weights is not None:
        trust = trust * (weights > 0)
    norm_match = rn / jnp.maximum(xn, eps)  # [C]
    w = trust / jnp.maximum(jnp.sum(trust), eps)
    agg_vec = jnp.sum(x * (w * norm_match)[:, None], axis=0)
    agg_vec = jnp.where(jnp.sum(trust) > 0, agg_vec, r)
    return T.tree_unvectorize(agg_vec, ref), trust


def anomaly_scores(stacked: Pytree,
                   valid: jax.Array | None = None
                   ) -> dict[str, jax.Array]:
    """Per-client anomaly signals over a stacked delta tree, all
    derived from one flatten + one gram matmul:

    - ``l2_norm`` / ``l2_z``: delta norm and its cohort z-score (the
      scale-boost signature);
    - ``cos_to_mean`` / ``cos_to_med``: cosine to the cohort mean and
      coordinate-median delta (sign-flip points away from the robust
      center; the mean variant is reported but poisonable by a large
      minority, so the combined score uses the median one);
    - ``nearest_rel``: nearest-neighbor distance relative to the
      client's own norm — near-zero means another client sent (almost)
      the same delta, the colluding-copy signature honest data cannot
      produce;
    - ``score``: the combined scalar the reputation plane accumulates:
      ``relu(l2_z) + relu(-cos_to_med) + 2 * near_duplicate``.

    ``valid`` (``[C]`` bool, possibly traced) restricts every cohort
    statistic — norm mean/std, mean/median reference vectors, nearest
    neighbor — to the valid rows, so the bucket-padded elastic path's
    zero-delta padding rows neither skew the z-scores nor trip the
    near-duplicate collusion signal against each other. Scores at
    invalid slots are meaningless and must be discarded by the caller.
    """
    eps = 1e-12
    x = flatten_clients(stacked)  # [C, D]
    c = x.shape[0]
    sq = jnp.sum(x * x, axis=1)
    norms = jnp.sqrt(sq)
    if valid is None:
        mu = jnp.mean(norms)
        sd = jnp.std(norms)
        mean_vec = jnp.mean(x, axis=0)
    else:
        vf = valid.astype(jnp.float32)
        n = jnp.sum(vf)
        mu = jnp.sum(jnp.where(valid, norms, 0.0)) / n
        sd = jnp.sqrt(
            jnp.sum(jnp.where(valid, jnp.square(norms - mu), 0.0)) / n
        )
        mean_vec = jnp.sum(
            jnp.where(valid[:, None], x, 0.0), axis=0
        ) / n
    l2_z = (norms - mu) / jnp.maximum(sd, 1e-6)

    med_vec = T.tree_vectorize(
        coordinate_median(stacked, valid)
    ).astype(jnp.float32)

    def _cos(ref):
        rn = jnp.sqrt(jnp.sum(ref * ref))
        return (x @ ref) / jnp.maximum(norms * rn, eps)

    gram = x @ x.T
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * gram, 0.0)
    d2 = jnp.where(jnp.eye(c, dtype=bool), jnp.inf, d2)  # mask self
    if valid is not None:
        # an invalid row must neither be anyone's nearest neighbor nor
        # find one among the other padding rows
        pair_ok = valid[:, None] & valid[None, :]
        d2 = jnp.where(pair_ok, d2, jnp.inf)
    nearest = jnp.sqrt(jnp.min(d2, axis=1)) if c > 1 else jnp.full(
        (c,), jnp.inf
    )
    nearest_rel = nearest / jnp.maximum(norms, eps)
    dup = (nearest_rel < 1e-3).astype(jnp.float32)

    cos_to_mean = _cos(mean_vec)
    cos_to_med = _cos(med_vec)
    score = (
        jax.nn.relu(l2_z)
        + jax.nn.relu(-cos_to_med)
        + 2.0 * dup
    )
    return {
        "l2_norm": norms,
        "l2_z": l2_z,
        "cos_to_mean": cos_to_mean,
        "cos_to_med": cos_to_med,
        "nearest_rel": nearest_rel,
        "score": score,
    }


# ---------------------------------------------------------------------------
# non-finite screening (shared with the deploy-path message handler)
# ---------------------------------------------------------------------------


def finite_client_mask(stacked: Pytree, n_k: jax.Array) -> jax.Array:
    """``[C]`` bool: True where EVERY floating leaf of client ``c`` is
    finite and its sample count is finite. Integer leaves are finite by
    construction (mirrors ``_result_is_finite`` on the deploy path,
    inside jit)."""
    ok = jnp.isfinite(n_k.astype(jnp.float32))
    for x in jax.tree.leaves(stacked):
        if not jnp.issubdtype(x.dtype, jnp.floating):
            continue
        axes = tuple(range(1, x.ndim))
        ok = ok & jnp.all(jnp.isfinite(x), axis=axes)
    return ok


def check_fednova_compat(algorithm: str, method: str) -> None:
    """The single source of the fednova-vs-defense rule, raised early
    by the CLI and both round-program constructors and as a backstop
    inside ``server_update``: fednova's tau-normalized averaging IS
    the aggregation rule, so a configured reduce defense would be
    silently bypassed while the summary reports it in force."""
    if algorithm == "fednova" and method not in ("mean", "", None):
        raise ValueError(
            f"robust_method={method!r} is incompatible with "
            "algorithm='fednova' (tau-normalized averaging is the "
            "aggregation rule); use fedavg/fedopt with a defense, or "
            "keep fednova with robust_norm_clip/robust_noise_stddev "
            "(which do compose)"
        )


# ---------------------------------------------------------------------------
# the configurable pipeline
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DefensePipeline:
    """The composable defense stack applied inside the aggregation pass
    (both round programs: the compiled simulator's ``server_update``
    and the actor server's round close call the SAME instance shape).

    Order — clip each delta, reduce with the configured rule, then
    noise the aggregate::

        preprocess  -> clip_deltas_by_norm        (clip > 0)
        reduce      -> mean | median | trimmed_mean
                       | krum | multikrum | fltrust
        postprocess -> add_gaussian_noise          (noise_stddev > 0)

    The default (``mean``, clip 0, noise 0) is byte-identical to the
    plain weighted mean — the zero-defense path costs nothing."""

    method: str = "mean"
    clip: float = 0.0
    noise_stddev: float = 0.0
    num_adversaries: int = 0
    multikrum_m: int = 0  # 0 = auto (C - f)
    trim_frac: float = 0.1

    METHODS = ("mean", "median", "trimmed_mean", "krum", "multikrum",
               "fltrust")

    def __post_init__(self):
        if self.method not in self.METHODS:
            raise ValueError(
                f"defense method must be one of {self.METHODS}, "
                f"got {self.method!r}"
            )
        if (self.method == "multikrum" and self.num_adversaries == 0
                and self.multikrum_m == 0):
            # auto m = C - f with f = 0 keeps every client: the plain
            # weighted mean wearing a 'multikrum' label — reject the
            # vacuous configuration instead of reporting a defense
            # that is not in force
            raise ValueError(
                "multikrum with num_adversaries=0 and multikrum_m=0 "
                "selects every client (plain mean); set "
                "--defense_num_adversaries f (auto m = C - f) or an "
                "explicit --defense_multikrum_m"
            )

    @staticmethod
    def from_fed(fed) -> "DefensePipeline":
        """Build from :class:`~fedml_tpu.config.FedConfig` robust_*
        fields (the single CLI/config surface)."""
        return DefensePipeline(
            method=fed.robust_method or "mean",
            clip=fed.robust_norm_clip,
            noise_stddev=fed.robust_noise_stddev,
            num_adversaries=fed.robust_num_adversaries,
            multikrum_m=fed.robust_multikrum_m,
            trim_frac=fed.robust_trim_frac,
        )

    def preprocess(self, deltas: Pytree) -> Pytree:
        return (
            clip_deltas_by_norm(deltas, self.clip)
            if self.clip > 0 else deltas
        )

    def reduce(self, deltas: Pytree, weights: jax.Array, red,
               valid: jax.Array | None = None) -> Pytree:
        """Aggregate stacked deltas under the configured rule. ``red``
        is the :class:`~fedml_tpu.algorithms.fedavg.Reducer` — selection
        defenses gather the full ``[C, ...]`` stack (like the median
        rule always has), so they compose with the mesh-sharded
        runtime unchanged.

        ``valid`` (``[C]`` bool, possibly traced) marks the live rows
        of a bucket-padded cohort (:mod:`fedml_tpu.core.elastic`):
        every rule then reduces over the valid rows only, and the
        padded zero-weight / zero-delta rows provably cannot perturb
        the aggregate (content-blind bitwise; see core/elastic.py for
        the parity tiers ``tests/test_elastic.py`` pins)."""
        if self.method == "mean":
            # padding rows carry weight 0 and delta 0: they vanish from
            # both the weighted sum and the weight total exactly
            return red.wmean(deltas, weights)
        g = red.gather(deltas)
        gv = None if valid is None else red.gather(valid)
        n_valid = None if gv is None else jnp.sum(gv.astype(jnp.int32))
        if self.method == "median":
            return coordinate_median(g, gv)
        if self.method == "trimmed_mean":
            return trimmed_mean(g, self.trim_frac, gv)
        gw = red.gather(weights)
        if gv is not None:
            # selection rules key eligibility off weights > 0; make the
            # padding mask authoritative even if a live client ever
            # reported a zero sample count
            gw = jnp.where(gv, gw, 0.0)
        if self.method in ("krum", "multikrum"):
            scores = self._sharded_krum_scores(
                deltas, g, gw, red, self.num_adversaries, n_valid
            )
            if self.method == "krum":
                return krum(g, self.num_adversaries, gw, n_valid,
                            scores=scores)[0]
            return multi_krum(
                g, gw, self.num_adversaries, self.multikrum_m, n_valid,
                scores=scores,
            )[0]
        if self.method == "fltrust":
            # no server root dataset in the loop: the reference delta
            # defaults to the coordinate-median of the cohort (robust
            # to a minority of adversaries by construction)
            return fltrust(g, coordinate_median(g, gv), weights=gw)[0]
        raise ValueError(f"unknown defense method: {self.method!r}")

    @staticmethod
    def _sharded_krum_scores(local_deltas, gathered, gw, red,
                             num_adversaries,
                             n_valid) -> jax.Array | None:
        """Row-block Krum scores when the reduce runs over a mesh axis
        (``red.axis``): each shard computes ITS rows' block of the
        ``O(C^2 D)`` gram against the gathered stack
        (:func:`pairwise_sq_dists_rows`) and only the ``[C]`` score
        vector is all-gathered — the distance work partitions over the
        client axis instead of replicating on every device. Per row
        the ops are identical to the replicated path, so the selection
        stays bitwise (parity pinned in ``tests/test_compress.py``).
        Returns None on a local reduce (the replicated path computes
        its own scores)."""
        axis = getattr(red, "axis", None)
        if axis is None:
            return None
        x_rows = flatten_clients(local_deltas)
        x_all = flatten_clients(gathered)
        b = x_rows.shape[0]
        rows = jax.lax.axis_index(axis) * b + jnp.arange(b)
        d2_rows = pairwise_sq_dists_rows(x_rows, rows, x_all)
        scores_rows = krum_scores_rows(d2_rows, rows, num_adversaries,
                                       gw > 0, n_valid)
        return jax.lax.all_gather(scores_rows, axis, tiled=True)

    def postprocess(self, agg: Pytree, rng: jax.Array) -> Pytree:
        return (
            add_gaussian_noise(agg, self.noise_stddev, rng)
            if self.noise_stddev > 0 else agg
        )

    def excluded_count(self, cohort_size: int) -> int:
        """How many of ``cohort_size`` results the reduce rule excludes
        from the aggregate by construction (telemetry: the
        ``defense.excluded`` counter). Reweighting rules (fltrust)
        exclude nobody statically — they zero trust at runtime."""
        if self.method == "krum":
            return max(0, cohort_size - 1)
        if self.method == "multikrum":
            m = self.multikrum_m if self.multikrum_m > 0 else max(
                1, cohort_size - self.num_adversaries
            )
            return max(0, cohort_size - min(m, cohort_size))
        return 0

"""Shape-bucketed compiled rounds: membership churn costs a cache hit.

Every distinct cohort size hands XLA a new ``[C, ...]`` stacked-delta
shape — and therefore a full recompile of the aggregation program. In a
static world that happens once; in an elastic world (mid-run admission,
graceful LEAVEs, crashes — docs/FAULT_TOLERANCE.md "Elastic
membership") the cohort size walks up and down every few rounds and a
naive runtime spends more time in XLA than in training.

The fix is the classic bucketing trick: pad the cohort to the next
power-of-two **bucket** with zero-weight rows whose delta is exactly
zero (the padded row carries the global variables, so ``stacked - g``
vanishes — the same healed-row construction PR 4's non-finite screen
uses). Those rows provably cannot perturb any supported aggregation
rule:

- ``mean`` / FedNova: weight 0 ⇒ every padded term is an exact ``±0``
  in both numerator and denominator sums;
- ``median`` / ``trimmed_mean``: the mask-aware variants
  (:func:`fedml_tpu.core.robust.coordinate_median` /
  ``trimmed_mean`` with ``valid``) sort invalid rows to the far end and
  reduce over the valid prefix only;
- ``krum`` / ``multikrum``: invalid rows score :data:`robust._FAR` and
  the neighbor count derives from the VALID count;
- ``fltrust``: invalid rows get zero trust.

The exact contract ``tests/test_elastic.py`` pins, in two tiers:

1. **Content-blindness (bitwise, every rule)**: at a fixed bucket, the
   masked rows cannot perturb the aggregate no matter what finite
   content they carry — replacing the padding with garbage yields a
   byte-identical result. This is the churn-proof property the elastic
   runtime rests on: the compiled round's output depends only on the
   live cohort.
2. **Padded vs unpadded**: the pure selection/gather rules (``median``,
   ``krum``) and the dot-product-combined ``fltrust`` reproduce the
   unpadded cohort's aggregate byte-for-byte for every cohort size
   ``1..2*bucket``. The sum-based rules (``mean``, ``multikrum``'s
   final mean, ``trimmed_mean``) feed the reduction the identical live
   terms plus exact zeros, but XLA's reduce emitter may associate the
   wider extent differently — parity there is ~1 ulp (pinned with a
   tight tolerance), the same reassociation two *unpadded* programs of
   different surrounding fusion exhibit. The static, elastic-off path
   never pads and stays byte-identical to its pre-elastic self.

:class:`CompiledRoundCache` holds the ahead-of-time compiled executable
per bucket in a true LRU (evicting an entry frees the executable, which
a bare ``jax.jit`` cache never does) and feeds the
``elastic.compile_cache_{hits,misses,evictions}`` telemetry the
acceptance tests pin.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core import memscope, telemetry


def bucket_for(n: int, min_bucket: int = 1) -> int:
    """Next power-of-two bucket that fits ``n`` cohort rows."""
    if n < 1:
        raise ValueError(f"cohort size must be >= 1, got {n}")
    b = max(1, min_bucket)
    while b < n:
        b <<= 1
    return b


def pad_stacked(stacked_vars, weights, global_vars, bucket: int):
    """Pad a ``[C, ...]`` stacked variables tree to ``[bucket, ...]``.

    Padded rows replicate the GLOBAL variables (delta exactly zero — a
    neutral row by construction) with aggregation weight 0. Returns
    ``(padded_stacked, padded_weights, valid_mask)``. Works on host
    numpy or device arrays alike (`jnp` ops; everything lands on
    device, which is where the bucket-compiled round wants it)."""
    c = int(np.shape(weights)[0])
    if c > bucket:
        raise ValueError(f"cohort {c} does not fit bucket {bucket}")
    pad = bucket - c
    w = jnp.asarray(weights, jnp.float32)
    if pad == 0:
        return stacked_vars, w, jnp.ones((bucket,), bool)

    def leaf(s, g):
        s = jnp.asarray(s)
        fill = jnp.broadcast_to(
            jnp.asarray(g, s.dtype)[None], (pad,) + np.shape(g)
        )
        return jnp.concatenate([s, fill], axis=0)

    padded = jax.tree.map(leaf, stacked_vars, global_vars)
    w = jnp.concatenate([w, jnp.zeros((pad,), jnp.float32)])
    valid = jnp.concatenate(
        [jnp.ones((c,), bool), jnp.zeros((pad,), bool)]
    )
    return padded, w, valid


def active_mask(bucket: int, n_active) -> jax.Array:
    """``[bucket]`` bool: the first ``n_active`` slots are live. Used
    by the compiled sims, where ``n_active`` is a traced operand so a
    cohort-size change never retraces the round program."""
    return jnp.arange(bucket) < n_active


def mask_padded(stacked_vars, n_k, msums, global_vars, live):
    """Neutralize the padded slots of a bucketed cohort BEFORE
    screening/aggregation: params healed to the global variables (delta
    exactly zero), sample count zero, metric sums zero — downstream the
    padding is indistinguishable from absent. One implementation shared
    by the sim and sharded round bodies (their parity pin requires the
    two to stay byte-equivalent)."""

    def heal(s, g):
        m = live.reshape((-1,) + (1,) * (s.ndim - 1))
        return jnp.where(m, s, g[None].astype(s.dtype))

    stacked_vars = jax.tree.map(heal, stacked_vars, global_vars)
    n_k = jnp.where(live, n_k, jnp.zeros_like(n_k))
    msums = jax.tree.map(
        lambda v: jnp.where(
            live.reshape((-1,) + (1,) * (v.ndim - 1)),
            v, jnp.zeros_like(v),
        ),
        msums,
    )
    return stacked_vars, n_k, msums


def mirror_jit_cache(round_fn, call):
    """Invoke ``call()`` (one application of ``round_fn``) and mirror
    the jit executable cache's hit/miss into the ``elastic.*``
    vocabulary (docs/OBSERVABILITY.md) — churn cost must be observable.
    Shared by the sim and sharded ``run_round`` elastic paths so the
    accounting cannot drift between them. ``round_fn`` exposes its
    executable count via ``_cache_size`` (models/ops jit wrapper);
    without it the call runs unmirrored."""
    size_fn = getattr(round_fn, "_cache_size", None)
    before = size_fn() if size_fn is not None else None
    out = call()
    if before is not None:
        if size_fn() > before:
            telemetry.METRICS.inc("elastic.compile_cache_misses")
        else:
            telemetry.METRICS.inc("elastic.compile_cache_hits")
    return out


class CompiledRoundCache:
    """LRU of ahead-of-time compiled executables, keyed by any hashable
    — the deploy paths key by bucket size; a caller whose executables
    vary on more than shape may compound the key (e.g. ``(bucket,
    block_length)``; each (shape, scan-length) pair is its own
    executable). Note the fused SIM paths do not route through this
    cache: their block programs live in ``jax.jit``'s own cache, with
    hits/misses mirrored by :func:`mirror_jit_cache`.

    ``jax.jit`` already caches by shape, but it neither evicts nor
    reports — an elastic server that saw 40 distinct cohort sizes would
    silently hold 40 executables forever and nothing would tell you the
    bucketing was (or wasn't) working. This cache lowers + compiles
    explicitly, bounds the resident set, and counts
    ``elastic.compile_cache_{hits,misses,evictions}``
    (docs/OBSERVABILITY.md). Thread-safe: round closes arrive on
    transport dispatch threads."""

    def __init__(self, fn: Callable, max_entries: int = 8,
                 static_argnums=(), jit_kwargs: dict | None = None,
                 family: str | None = None):
        """``jit_kwargs`` passes straight through to ``jax.jit`` —
        the sharded-aggregation path uses it for
        ``in_shardings``/``out_shardings`` (client-axis NamedSharding);
        ``donate_argnums`` is accepted for callers whose operands have
        a single owner (the actor paths deliberately do not donate —
        see parallel/sharded_agg.py). ``family`` names this site in the
        memory-observability plane (core/memscope.py): every miss's
        compile wall lands in the ``mem.compile_s.<family>`` histogram
        and its ``memory_analysis()`` in the ``mem.program.*`` gauges —
        default is the wrapped function's name."""
        self._fn = fn
        self._static_argnums = tuple(static_argnums)
        self._jit_kwargs = dict(jit_kwargs or {})
        self.family = (
            family
            or getattr(fn, "__name__", "program").lstrip("_")
        )
        self.max_entries = max_entries
        self._cache: OrderedDict[object, object] = OrderedDict()
        self._lock = threading.Lock()
        # local mirror of the telemetry counters so tests (and callers
        # running with the metrics plane off) can still read hit rates
        self.stats = {"hits": 0, "misses": 0, "evictions": 0}

    def __call__(self, bucket, *args):
        """``bucket`` is the cache key — an int bucket size on the
        classic paths; any hashable works for callers whose
        executables vary on more than shape."""
        with self._lock:
            exe = self._cache.get(bucket)
            if exe is not None:
                self._cache.move_to_end(bucket)
        if exe is None:
            t0 = time.perf_counter()
            exe = (
                jax.jit(self._fn, static_argnums=self._static_argnums,
                        **self._jit_kwargs)
                .lower(*args)
                .compile()
            )
            compile_s = time.perf_counter() - t0
            evicted = False
            with self._lock:
                self._cache[bucket] = exe
                self._cache.move_to_end(bucket)
                if len(self._cache) > self.max_entries:
                    self._cache.popitem(last=False)
                    evicted = True
                self.stats["misses"] += 1
                if evicted:
                    self.stats["evictions"] += 1
            telemetry.METRICS.inc("elastic.compile_cache_misses")
            if evicted:
                telemetry.METRICS.inc("elastic.compile_cache_evictions")
            telemetry.RECORDER.record("elastic_compile", bucket=bucket)
            # a miss is no longer a bare counter bump: the compile wall
            # (eviction thrash burns seconds, not just counts) and the
            # executable's memory analysis are recorded per program
            memscope.note_program(self.family, bucket, exe,
                                  compile_s=compile_s)
        else:
            with self._lock:
                self.stats["hits"] += 1
            telemetry.METRICS.inc("elastic.compile_cache_hits")
        if self._static_argnums:
            dynamic = tuple(
                a for i, a in enumerate(args)
                if i not in self._static_argnums
            )
            return exe(*dynamic)
        return exe(*args)

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)

"""TCP socket transport: length-prefixed, sealed message frames.

The DCN-class control-plane transport (reference analog: the gRPC backend,
``fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:22-98`` —
each process runs a server, send opens a channel to ``ip_config[receiver]``).
Here: each rank runs one accept loop; sends use pooled persistent
connections; frames are ``8-byte big-endian length || sealed payload``
where the seal is the protocol-version byte + CRC32 of
:mod:`fedml_tpu.core.transport.wire`. A CRC mismatch (bit-flip in
flight, or the chaos ``corrupt`` fault) is counted
(``transport.corrupt_frames``) and DROPPED — the retry/heartbeat/
straggler machinery heals it like any loss; a protocol-version mismatch
(rolling-restart skew) fails the rank loudly instead of garbling a
pytree (docs/FAULT_TOLERANCE.md).
"""

from __future__ import annotations

import socket
import struct
import sys
import threading

from fedml_tpu.core import telemetry
from fedml_tpu.core.message import Message
from fedml_tpu.core.transport import wire
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.core.transport.retry import RetryPolicy, call_with_retry

_HDR = struct.Struct(">Q")

#: Per-attempt socket timeout for connect. A wedged peer (bound port,
#: dead process) turns into a retryable ``socket.timeout`` instead of an
#: unbounded stall.
_SOCKET_TIMEOUT_S = 10.0
#: Floor throughput assumed when bounding a send: the per-attempt send
#: timeout is ``max(_SOCKET_TIMEOUT_S, frame_bytes / _MIN_SEND_BPS)`` —
#: a multi-GB model sync over a slow cross-silo link gets the time it
#: legitimately needs, while a truly wedged peer still times out.
_MIN_SEND_BPS = 1 << 20  # 1 MiB/s


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpTransport(BaseTransport):
    def __init__(
        self,
        rank: int,
        ip_config: dict[int, tuple[str, int]],
        retry: RetryPolicy | None = None,
    ):
        """``ip_config``: rank -> (host, port) for every participant
        (reference ``ip_config_utils.py`` CSV tables)."""
        super().__init__(rank)
        self.ip_config = ip_config
        self.retry = retry if retry is not None else RetryPolicy()
        self._server: socket.socket | None = None
        self._conns: dict[int, socket.socket] = {}
        # one lock per peer rank so a slow/blocked connect or send to one
        # peer never serializes traffic to the others; a global lock guards
        # only the dict itself
        self._lock = threading.Lock()
        self._rank_locks: dict[int, threading.Lock] = {}
        self._threads: list[threading.Thread] = []

    # -- receive side ------------------------------------------------------
    def start(self) -> None:
        if self._server is not None:
            return
        host, port = self.ip_config[self.rank]
        srv = socket.create_server((host, port), reuse_port=False)
        srv.settimeout(0.5)
        self._server = srv
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        assert self._server is not None
        while not self._stopped.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _read_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stopped.is_set():
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                (length,) = _HDR.unpack(hdr)
                data = _recv_exact(conn, length)
                if data is None:
                    return
                try:
                    payload = wire.open_sealed(data)
                except wire.CorruptFrameError:
                    self.note_receive(_HDR.size + length)
                    # damaged in flight: count + drop; the length
                    # prefix framed the stream correctly, so the next
                    # frame parses — and the fault-tolerance layer
                    # above heals the loss (retries re-send syncs,
                    # straggler rounds close without the result)
                    telemetry.METRICS.inc("transport.corrupt_frames")
                    telemetry.RECORDER.record(
                        "corrupt_frame", rank=self.rank, nbytes=length
                    )
                    continue
                except wire.WireVersionError as err:
                    # rolling-restart skew: every further frame from
                    # this peer is unparseable — fail THIS rank loudly
                    # (stop unblocks the actor's run loop into its
                    # incomplete-run error) instead of silently
                    # dropping traffic forever
                    self.note_receive(_HDR.size + length)
                    telemetry.flight_dump(
                        "wire_version_mismatch", rank=self.rank,
                        detail=str(err),
                    )
                    print(f"rank {self.rank}: {err}", file=sys.stderr)
                    self.stop()
                    return
                msg = Message.decode(payload)
                self.note_receive(_HDR.size + length, msg.msg_type)
                self.deliver(msg)

    # -- send side ---------------------------------------------------------
    def _rank_lock(self, rank: int) -> threading.Lock:
        with self._lock:
            lock = self._rank_locks.get(rank)
            if lock is None:
                lock = self._rank_locks[rank] = threading.Lock()
            return lock

    def send_message(self, msg: Message) -> None:
        payload = msg.encode()
        corrupt_seed = getattr(msg, "chaos_corrupt", None)
        if corrupt_seed is not None:
            # the chaos 'corrupt' fault marked this message: flip
            # seeded bits in the SEALED frame (after the CRC was
            # computed, so the receiver's checksum catches it)
            sealed = wire.flip_bits(
                wire.seal(payload), corrupt_seed
            )
            frame = _HDR.pack(len(sealed)) + sealed
        else:
            # single join: length prefix + 5-byte seal + payload — the
            # payload is the multi-MB model frame on sync/result sends,
            # so an intermediate sealed copy is a real cost
            frame = b"".join((
                _HDR.pack(wire.SEAL_OVERHEAD + len(payload)),
                wire.seal_header(payload),
                payload,
            ))
        self.note_send(msg, len(frame))
        self._send_wire(msg.receiver, frame)

    def _evict(self, rank: int) -> None:
        with self._lock:
            sock = self._conns.pop(rank, None)
        if sock is not None:
            telemetry.METRICS.inc("transport.reconnects")
            try:
                sock.close()
            except OSError:
                pass

    def _send_once(self, rank: int, frame: bytes) -> None:
        """One attempt: reuse (or open) the pooled connection, ship the
        frame. Raises OSError/socket.timeout on a dead or wedged peer.
        The send timeout bounds the WHOLE ``sendall`` (python >= 3.5
        semantics), so it scales with the frame size — a legitimate
        slow bulk transfer must not be indistinguishable from a stall."""
        with self._lock:
            sock = self._conns.get(rank)
        if sock is None:
            host, port = self.ip_config[rank]
            sock = socket.create_connection(
                (host, port), timeout=_SOCKET_TIMEOUT_S
            )
            with self._lock:
                self._conns[rank] = sock
        sock.settimeout(
            max(_SOCKET_TIMEOUT_S, len(frame) / _MIN_SEND_BPS)
        )
        sock.sendall(frame)

    def _send_wire(self, rank: int, frame: bytes) -> None:
        """Ship pre-framed bytes to ``rank`` over the pooled connection,
        with exponential-backoff retries and a per-op deadline (peer
        restarted / broken pipe / not yet bound). Subclasses with their
        own wire format (tensor_rpc) reuse this for the connection
        machinery. A half-sent frame poisons the stream, so every retry
        starts on a FRESH connection (``_evict`` between attempts)."""
        with self._rank_lock(rank):
            call_with_retry(
                lambda: self._send_once(rank, frame),
                policy=self.retry,
                retry_on=(OSError,),
                describe=f"tcp send rank {self.rank} -> {rank}",
                seed=self.rank * 1000 + rank,
                stop=self._stopped,
                cleanup=lambda: self._evict(rank),
            )

    def stop(self) -> None:
        super().stop()
        if self._server is not None:
            self._server.close()
        with self._lock:
            for sock in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()

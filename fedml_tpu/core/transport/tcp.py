"""TCP socket transport: length-prefixed message frames.

The DCN-class control-plane transport (reference analog: the gRPC backend,
``fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:22-98`` —
each process runs a server, send opens a channel to ``ip_config[receiver]``).
Here: each rank runs one accept loop; sends use pooled persistent
connections; frames are ``8-byte big-endian length || pickled Message``.
"""

from __future__ import annotations

import socket
import struct
import threading

from fedml_tpu.core.message import Message
from fedml_tpu.core.transport.base import BaseTransport

_HDR = struct.Struct(">Q")


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpTransport(BaseTransport):
    def __init__(self, rank: int, ip_config: dict[int, tuple[str, int]]):
        """``ip_config``: rank -> (host, port) for every participant
        (reference ``ip_config_utils.py`` CSV tables)."""
        super().__init__(rank)
        self.ip_config = ip_config
        self._server: socket.socket | None = None
        self._conns: dict[int, socket.socket] = {}
        # one lock per peer rank so a slow/blocked connect or send to one
        # peer never serializes traffic to the others; a global lock guards
        # only the dict itself
        self._lock = threading.Lock()
        self._rank_locks: dict[int, threading.Lock] = {}
        self._threads: list[threading.Thread] = []

    # -- receive side ------------------------------------------------------
    def start(self) -> None:
        if self._server is not None:
            return
        host, port = self.ip_config[self.rank]
        srv = socket.create_server((host, port), reuse_port=False)
        srv.settimeout(0.5)
        self._server = srv
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        assert self._server is not None
        while not self._stopped.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _read_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stopped.is_set():
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                (length,) = _HDR.unpack(hdr)
                data = _recv_exact(conn, length)
                if data is None:
                    return
                self.deliver(Message.decode(data))

    # -- send side ---------------------------------------------------------
    def _rank_lock(self, rank: int) -> threading.Lock:
        with self._lock:
            lock = self._rank_locks.get(rank)
            if lock is None:
                lock = self._rank_locks[rank] = threading.Lock()
            return lock

    def send_message(self, msg: Message) -> None:
        data = msg.encode()
        self._send_wire(msg.receiver, _HDR.pack(len(data)) + data)

    def _send_wire(self, rank: int, frame: bytes) -> None:
        """Ship pre-framed bytes to ``rank`` over the pooled connection
        (one dead-socket retry). Subclasses with their own wire format
        (tensor_rpc) reuse this for the connection machinery."""
        with self._rank_lock(rank):
            with self._lock:
                sock = self._conns.get(rank)
            if sock is None:
                host, port = self.ip_config[rank]
                sock = socket.create_connection((host, port), timeout=30)
                with self._lock:
                    self._conns[rank] = sock
            try:
                sock.sendall(frame)
            except OSError:
                # evict the dead socket and retry once on a fresh connection
                # (peer restarted / broken pipe)
                with self._lock:
                    self._conns.pop(rank, None)
                try:
                    sock.close()
                except OSError:
                    pass
                host, port = self.ip_config[rank]
                sock = socket.create_connection((host, port), timeout=30)
                with self._lock:
                    self._conns[rank] = sock
                sock.sendall(frame)

    def stop(self) -> None:
        super().stop()
        if self._server is not None:
            self._server.close()
        with self._lock:
            for sock in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()

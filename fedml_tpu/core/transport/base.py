"""Transport contract.

Reference: ``BaseCommunicationManager``
(``fedml_core/distributed/communication/base_com_manager.py:7-27``) and
``Observer`` (``observer.py:4-8``). The reference runs per-backend
send/receive daemon threads with a 0.3s poll loop
(``mpi/com_manager.py:71-79``) and kills them via
``PyThreadState_SetAsyncExc`` (``mpi_receive_thread.py:44-50``); here every
transport drains into one thread-safe inbox and a single dispatch loop with
cooperative shutdown — no async thread kills (SURVEY.md §5.2).
"""

from __future__ import annotations

import abc
import queue
import threading
from typing import Callable, Protocol

from fedml_tpu.core.message import Message


class Observer(Protocol):
    def receive_message(self, msg_type: int, msg: Message) -> None: ...


class BaseTransport(abc.ABC):
    """4-method contract + shared inbox/dispatch machinery."""

    def __init__(self, rank: int):
        self.rank = rank
        self._observers: list[Observer] = []
        self._inbox: queue.Queue[Message | None] = queue.Queue()
        self._stopped = threading.Event()
        # called at DELIVER time (receiver thread), before the message
        # waits in the inbox — liveness tracking must see arrivals even
        # while the dispatch thread is busy inside a long handler (a
        # client mid-local-update would otherwise look dead to itself)
        self._deliver_hooks: list[Callable[[Message], None]] = []

    # -- to implement ------------------------------------------------------
    @abc.abstractmethod
    def send_message(self, msg: Message) -> None: ...

    def start(self) -> None:  # start background receivers if any
        pass

    def stop(self) -> None:
        self._stopped.set()
        self._inbox.put(None)  # wake the dispatch loop

    # -- shared ------------------------------------------------------------
    def add_observer(self, obs: Observer) -> None:
        self._observers.append(obs)

    def add_deliver_hook(self, hook: Callable[[Message], None]) -> None:
        self._deliver_hooks.append(hook)

    def deliver(self, msg: Message) -> None:
        """Called by receiver machinery (or peers, for loopback)."""
        for hook in self._deliver_hooks:
            hook(msg)
        self._inbox.put(msg)

    def handle_receive_message(self, timeout: float | None = None) -> None:
        """Blocking dispatch loop (reference
        ``MpiCommunicationManager.handle_receive_message``,
        ``com_manager.py:71-79`` — but event-driven, no 0.3s poll)."""
        self.start()
        while not self._stopped.is_set():
            try:
                msg = self._inbox.get(timeout=timeout)
            except queue.Empty:
                return
            if msg is None:
                break
            for obs in self._observers:
                obs.receive_message(msg.msg_type, msg)

"""Transport contract.

Reference: ``BaseCommunicationManager``
(``fedml_core/distributed/communication/base_com_manager.py:7-27``) and
``Observer`` (``observer.py:4-8``). The reference runs per-backend
send/receive daemon threads with a 0.3s poll loop
(``mpi/com_manager.py:71-79``) and kills them via
``PyThreadState_SetAsyncExc`` (``mpi_receive_thread.py:44-50``); here every
transport drains into one thread-safe inbox and a single dispatch loop with
cooperative shutdown — no async thread kills (SURVEY.md §5.2).
"""

from __future__ import annotations

import abc
import collections
import queue
import threading
import time
from typing import Callable, Protocol

from fedml_tpu.core import telemetry
from fedml_tpu.core.message import (
    MSG_TYPE_HEARTBEAT,
    Message,
    msg_type_name,
)

#: default bound on the dispatch inbox (docs/OBSERVABILITY.md
#: ``manager.inbox_*``): under open-loop async arrivals an unbounded
#: inbox can grow without bound while the depth gauge — only SAMPLED at
#: deliver time — shows whatever the last arrival saw. The bound sheds
#: the OLDEST HEARTBEAT first (liveness beacons are refreshed by ANY
#: delivery and re-sent every interval, so one is always safe to drop);
#: work messages (results, joins, partials) are NEVER shed — a full
#: inbox of work degrades to the old unbounded behavior, visibly via
#: the high-water-mark gauge.
INBOX_CAPACITY = 4096


class _BoundedInbox:
    """Drop-in for the previous ``queue.Queue`` with the shed policy
    above. ``get`` keeps the queue.Empty contract the dispatch loop
    (and the TRPC handshake) rely on."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._d: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self.hwm = 0
        self.shed = 0

    def put(self, item: "Message | None") -> bool:
        """Enqueue; returns True when an old heartbeat was shed to
        make room (the caller counts it — this class stays
        metrics-free so the lock never nests into telemetry)."""
        shed = False
        with self._cv:
            if item is not None and len(self._d) >= self.capacity:
                for i, m in enumerate(self._d):
                    if (m is not None
                            and m.msg_type == MSG_TYPE_HEARTBEAT):
                        del self._d[i]
                        self.shed += 1
                        shed = True
                        break
            self._d.append(item)
            if len(self._d) > self.hwm:
                self.hwm = len(self._d)
            self._cv.notify()
        return shed

    def get(self, timeout: float | None = None):
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._cv:
            while not self._d:
                if deadline is None:
                    self._cv.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        if not self._d:
                            raise queue.Empty
            return self._d.popleft()

    def qsize(self) -> int:
        return len(self._d)

#: metric-name cache for the per-type byte counters: one small string
#: per DISTINCT message type, so the enabled hot path still allocates
#: no per-message strings (docs/OBSERVABILITY.md vocabulary)
_BYTES_BY_TYPE: dict[int, str] = {}


def _bytes_by_type_metric(msg_type: int) -> str:
    name = _BYTES_BY_TYPE.get(msg_type)
    if name is None:
        name = _BYTES_BY_TYPE[msg_type] = (
            f"transport.bytes_by_type.{msg_type_name(msg_type)}"
        )
    return name


class Observer(Protocol):
    def receive_message(self, msg_type: int, msg: Message) -> None: ...


class BaseTransport(abc.ABC):
    """4-method contract + shared inbox/dispatch machinery."""

    # cleared on a wrapped inner transport (ChaosTransport) so the one
    # message is not trace-marked/gauged twice on its way to the actor
    _telemetry_deliver = True

    def __init__(self, rank: int, inbox_capacity: int = INBOX_CAPACITY):
        self.rank = rank
        self._observers: list[Observer] = []
        self._inbox = _BoundedInbox(inbox_capacity)
        self._stopped = threading.Event()
        # called at DELIVER time (receiver thread), before the message
        # waits in the inbox — liveness tracking must see arrivals even
        # while the dispatch thread is busy inside a long handler (a
        # client mid-local-update would otherwise look dead to itself)
        self._deliver_hooks: list[Callable[[Message], None]] = []
        # gauge names resolved ONCE through the registry's label-capped
        # families (a 10k-rank world folds ranks beyond the cap into
        # one `...other` overflow gauge instead of growing the registry
        # and every scrape forever), then CACHED so the enabled
        # per-message hot path allocates no strings and takes no
        # label-ledger lock — resolution is lazy because the cap
        # decision belongs to the registry that is live at first use,
        # not whichever was live at construction
        self._inbox_label = f"rank{rank}"
        self._depth_gauge: str | None = None
        self._hwm_gauge: str | None = None

    # -- to implement ------------------------------------------------------
    @abc.abstractmethod
    def send_message(self, msg: Message) -> None: ...

    def start(self) -> None:  # start background receivers if any
        pass

    def stop(self) -> None:
        self._stopped.set()
        self._inbox.put(None)  # wake the dispatch loop

    # -- shared ------------------------------------------------------------
    def add_observer(self, obs: Observer) -> None:
        self._observers.append(obs)

    def add_deliver_hook(self, hook: Callable[[Message], None]) -> None:
        self._deliver_hooks.append(hook)

    # -- telemetry (docs/OBSERVABILITY.md) ---------------------------------
    def note_send(self, msg: Message, nbytes: int) -> None:
        """Account one outbound wire frame. Every concrete transport
        calls this once per send with the encoded frame size. Bytes are
        also attributed per message type
        (``transport.bytes_by_type.<name>``) so a wire-reduction claim
        can name the payload class it shrank."""
        m = telemetry.METRICS
        if m.enabled:
            m.inc("transport.messages_sent")
            m.inc("transport.bytes_sent", nbytes)
            m.inc(_bytes_by_type_metric(msg.msg_type), nbytes)

    def note_receive(self, nbytes: int, msg_type: int | None = None) -> None:
        """Account one inbound wire frame — called at the transport's
        decode site (real I/O), NOT in :meth:`deliver`, so a wrapping
        transport (chaos) never double-counts. ``msg_type`` (known only
        after a successful decode; None for frames dropped before
        decode, e.g. CRC failures) feeds the per-type attribution."""
        m = telemetry.METRICS
        if m.enabled:
            m.inc("transport.messages_received")
            m.inc("transport.bytes_received", nbytes)
            if msg_type is not None:
                m.inc(_bytes_by_type_metric(msg_type), nbytes)

    def deliver(self, msg: Message) -> None:
        """Called by receiver machinery (or peers, for loopback)."""
        if self._telemetry_deliver:
            tr = telemetry.TRACER
            if tr is not None:
                trace = getattr(msg, "trace", None)
                if trace is not None:
                    tr.event(
                        "msg_deliver", rank=self.rank, trace_id=trace[0],
                        span_id=trace[1], sender=msg.sender,
                        msg_type=msg.msg_type,
                    )
            m = telemetry.METRICS
            if m.enabled:
                name = self._depth_gauge
                if name is None:
                    name = self._depth_gauge = m.labeled_name(
                        "transport.inbox_depth", self._inbox_label
                    )
                m.gauge(name, self._inbox.qsize())
        for hook in self._deliver_hooks:
            hook(msg)
        shed = self._inbox.put(msg)
        if self._telemetry_deliver:
            # backpressure surface (docs/OBSERVABILITY.md): the
            # high-water-mark is cumulative truth about the worst
            # backlog, where the sampled depth gauge above only shows
            # what the last arrival happened to see. Per-rank name
            # (gauges are last-write-wins) and gated exactly like the
            # depth gauge — a chaos-wrapped inner inbox, drained by
            # its pump thread, must not overwrite the real one's hwm.
            # The shed counter is additive, so a shared name is fine.
            m = telemetry.METRICS
            if m.enabled:
                name = self._hwm_gauge
                if name is None:
                    name = self._hwm_gauge = m.labeled_name(
                        "manager.inbox_hwm", self._inbox_label
                    )
                m.gauge(name, self._inbox.hwm)
                if shed:
                    m.inc("manager.inbox_shed")

    def handle_receive_message(self, timeout: float | None = None) -> None:
        """Blocking dispatch loop (reference
        ``MpiCommunicationManager.handle_receive_message``,
        ``com_manager.py:71-79`` — but event-driven, no 0.3s poll)."""
        self.start()
        while not self._stopped.is_set():
            try:
                msg = self._inbox.get(timeout=timeout)
            except queue.Empty:
                return
            if msg is None:
                break
            for obs in self._observers:
                obs.receive_message(msg.msg_type, msg)

"""Transports for the cross-process runtime.

The reference ships five backends behind one 4-method contract
(``fedml_core/distributed/communication/base_com_manager.py:7``): MPI, gRPC,
Torch-RPC, MQTT, MQTT+S3 (SURVEY.md §2.8). The TPU build keeps the contract
and provides:

- ``LoopbackTransport`` — in-memory, for tests (the reference lacks this);
- ``TcpTransport``     — length-prefixed frames over sockets (DCN-class
  cross-host control plane);
- ``GrpcTransport``    — grpc bytes-RPC (no protoc needed);
- ``ChaosTransport``   — seeded deterministic fault injection over any of
  the above (docs/FAULT_TOLERANCE.md); the real transports share the
  retry/backoff policy in :mod:`fedml_tpu.core.transport.retry`.

Bulk tensor traffic between chips should ride ICI collectives
(:mod:`fedml_tpu.parallel`), not these transports — they carry control
messages and cross-host (DCN) model blobs only, mirroring the reference's
MQTT(control)+S3(data) split.
"""

from fedml_tpu.core.transport.base import BaseTransport, Observer
from fedml_tpu.core.transport.chaos import ChaosTransport, FaultPolicy
from fedml_tpu.core.transport.loopback import LoopbackHub, LoopbackTransport
from fedml_tpu.core.transport.retry import RetryPolicy
from fedml_tpu.core.transport.tcp import TcpTransport

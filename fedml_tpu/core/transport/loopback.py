"""In-memory loopback transport for tests and single-host multi-actor runs.

The reference has NO mock/in-memory transport — its CI launches real MPI
worlds (SURVEY.md §4). This fills that gap: N ranks share a
:class:`LoopbackHub`; sends go through the full encode/decode path so codec
bugs surface in unit tests."""

from __future__ import annotations

from fedml_tpu.core.message import Message
from fedml_tpu.core.transport.base import BaseTransport


class LoopbackHub:
    def __init__(self):
        self.transports: dict[int, "LoopbackTransport"] = {}

    def create(self, rank: int) -> "LoopbackTransport":
        t = LoopbackTransport(rank, self)
        self.transports[rank] = t
        return t


class LoopbackTransport(BaseTransport):
    def __init__(self, rank: int, hub: LoopbackHub):
        super().__init__(rank)
        self.hub = hub

    def send_message(self, msg: Message) -> None:
        # round-trip through the wire codec to keep tests honest
        data = msg.encode()
        self.note_send(msg, len(data))
        peer = self.hub.transports[msg.receiver]
        decoded = Message.decode(data)
        peer.note_receive(len(data), decoded.msg_type)
        peer.deliver(decoded)

"""gRPC transport without protoc: generic bytes-RPC.

Reference: ``GRPCCommManager``
(``fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:22-98``) —
a proto service with one ``sendMessage`` RPC, JSON payloads, 1 GB message
cap. Here the service is registered dynamically
(``grpc.method_handlers_generic_handler`` with identity serializers), the
payload is the shared binary codec, and the same 1 GB cap is applied.
"""

from __future__ import annotations

import threading
from concurrent import futures

from fedml_tpu.core import telemetry
from fedml_tpu.core.message import Message
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.core.transport.retry import RetryPolicy, call_with_retry

_SERVICE = "fedml_tpu.Comm"
_METHOD = "SendMessage"
MAX_MESSAGE_BYTES = 1 << 30  # reference grpc_comm_manager.py:36-40
#: Floor throughput assumed when bounding an RPC: a bulk model sync gets
#: the deadline it legitimately needs (mirrors tcp._MIN_SEND_BPS).
_MIN_SEND_BPS = 1 << 20  # 1 MiB/s


class GrpcTransport(BaseTransport):
    def __init__(
        self,
        rank: int,
        ip_config: dict[int, tuple[str, int]],
        retry: RetryPolicy | None = None,
    ):
        super().__init__(rank)
        import grpc  # lazy: keep core importable without grpcio

        self._grpc = grpc
        self.ip_config = ip_config
        self.retry = retry if retry is not None else RetryPolicy()
        self._server = None
        self._channels: dict[int, object] = {}
        self._chan_lock = threading.Lock()

    def start(self) -> None:
        if self._server is not None:
            return
        grpc = self._grpc

        def handler(request: bytes, context) -> bytes:
            msg = Message.decode(request)
            self.note_receive(len(request), msg.msg_type)
            self.deliver(msg)
            return b""

        generic = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                _METHOD: grpc.unary_unary_rpc_method_handler(
                    handler,
                    request_deserializer=None,
                    response_serializer=None,
                )
            },
        )
        opts = [
            ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
            ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
        ]
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4), options=opts
        )
        self._server.add_generic_rpc_handlers((generic,))
        host, port = self.ip_config[self.rank]
        self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def _stub(self, rank: int):
        grpc = self._grpc
        with self._chan_lock:
            ch = self._channels.get(rank)
            if ch is None:
                host, port = self.ip_config[rank]
                opts = [
                    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
                    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ]
                ch = grpc.insecure_channel(f"{host}:{port}", options=opts)
                self._channels[rank] = ch
        return ch.unary_unary(f"/{_SERVICE}/{_METHOD}")

    def _evict_channel(self, rank: int) -> None:
        with self._chan_lock:
            ch = self._channels.pop(rank, None)
        if ch is not None:
            telemetry.METRICS.inc("transport.reconnects")
            ch.close()

    def send_message(self, msg: Message) -> None:
        """Unary send with backoff retries (reference
        ``grpc_comm_manager.py`` raises on first failure; real cross-silo
        peers restart). Each RPC carries a per-attempt deadline so a hung
        server surfaces as DEADLINE_EXCEEDED, and the channel is rebuilt
        between attempts (a broken subchannel otherwise stays in
        TRANSIENT_FAILURE for its own internal backoff window)."""
        data = msg.encode()
        self.note_send(msg, len(data))
        rank = msg.receiver
        # per-RPC deadline: a FRACTION of the overall budget so a hung
        # (not refusing) server leaves room for the rebuilt-channel
        # retries — but scaled up for bulk frames, which legitimately
        # need transfer time proportional to their size
        per_attempt = max(
            2.0, self.retry.deadline_s / 3, len(data) / _MIN_SEND_BPS
        )
        call_with_retry(
            lambda: self._stub(rank)(data, timeout=per_attempt),
            policy=self.retry,
            retry_on=(self._grpc.RpcError,),
            describe=f"grpc send rank {self.rank} -> {rank}",
            seed=self.rank * 1000 + rank,
            stop=self._stopped,
            cleanup=lambda: self._evict_channel(rank),
        )

    def stop(self) -> None:
        super().stop()
        if self._server is not None:
            # stop() returns an event; WAIT for in-flight handlers to
            # drain before closing client channels — a handler may be
            # mid-send (replies run on server pool threads), and closing
            # its channel under it raises _InactiveRpcError("Channel
            # closed!") on that thread.
            self._server.stop(grace=2.0).wait(timeout=5)
        with self._chan_lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            ch.close()

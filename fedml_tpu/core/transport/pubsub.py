"""Pub-sub (MQTT-shaped) + blob-store (S3-shaped) transports.

The reference's production cross-silo path is MQTT for the control plane
and S3 for bulk model blobs:

- ``MqttCommManager`` (``fedml_core/distributed/communication/mqtt/
  mqtt_comm_manager.py:14``): broker pub/sub with the topic scheme
  *server publishes* ``{prefix}0_{client}``, *client publishes*
  ``{prefix}{client}``; full model params ride inline.
- ``MqttS3CommManager`` (``mqtt_s3/mqtt_s3_comm_manager.py:172-211``):
  ``send_message`` swaps the ``model_params`` payload entry for an S3 key
  (+ presigned URL) after uploading the blob; the receiver re-inflates it
  (``:141-163``). ``S3Storage`` (``remote_storage.py:14``) is put/get of
  serialized params.

This module provides the same two backends with the broker and object
store behind tiny interfaces:

- :class:`TopicBus` — in-process broker (topic -> subscribers). A real
  deployment would adapt this interface onto an external broker; every
  message still round-trips the full wire codec so the behavior under test
  is the real one.
- :class:`BlobStore` — S3-shaped put/get with generated keys and mock
  "presigned URLs". ``root`` = in-memory dict, or a directory for
  cross-process file-backed blobs.
- :class:`PubSubTransport` — MQTT-shaped: whole message on the topic.
- :class:`PubSubBlobTransport` — MQTT+S3-shaped: control/data-plane split;
  any payload entry under ``KEY_MODEL_PARAMS`` moves to the blob store and
  only its key + URL ride the topic.
"""

from __future__ import annotations

import os
import sys
import threading
import uuid
from typing import Callable

from fedml_tpu.core import telemetry
from fedml_tpu.core.message import KEY_MODEL_PARAMS, Message
from fedml_tpu.core.transport import wire
from fedml_tpu.core.transport.base import BaseTransport

KEY_BLOB = "model_params_blob_key"
KEY_BLOB_URL = "model_params_url"


class TopicBus:
    """In-process MQTT-broker stand-in: publish/subscribe on string topics.

    Thread-safe; callbacks run on the publisher's thread (like paho's
    network loop thread calling ``on_message``)."""

    def __init__(self):
        self._subs: dict[str, list[Callable[[str, bytes], None]]] = {}
        self._lock = threading.Lock()

    def subscribe(self, topic: str, callback: Callable[[str, bytes], None]):
        with self._lock:
            self._subs.setdefault(topic, []).append(callback)

    def publish(self, topic: str, payload: bytes):
        with self._lock:
            subs = list(self._subs.get(topic, ()))
        for cb in subs:
            cb(topic, payload)


class BlobStore:
    """S3-shaped object store (reference ``S3Storage``,
    ``remote_storage.py:14``): ``put`` returns a mock presigned URL,
    ``get`` fetches by key. ``root=None`` keeps blobs in memory; a
    directory path makes them file-backed (cross-process)."""

    def __init__(self, root: str | None = None, bucket: str = "fedml"):
        self.root = root
        self.bucket = bucket
        self._mem: dict[str, bytes] = {}
        self._lock = threading.Lock()
        if root is not None:
            os.makedirs(root, exist_ok=True)

    def put(self, key: str, data: bytes) -> str:
        if self.root is None:
            with self._lock:
                self._mem[key] = data
        else:
            tmp = os.path.join(self.root, f".{key}.tmp")
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, os.path.join(self.root, key))
        return f"blob://{self.bucket}/{key}?presigned=1"  # mock presign

    def get(self, key: str) -> bytes:
        if self.root is None:
            with self._lock:
                return self._mem[key]
        with open(os.path.join(self.root, key), "rb") as f:
            return f.read()

    def delete(self, key: str) -> None:
        if self.root is None:
            with self._lock:
                self._mem.pop(key, None)
        else:
            try:
                os.remove(os.path.join(self.root, key))
            except FileNotFoundError:
                pass


class PubSubTransport(BaseTransport):
    """MQTT-shaped transport over a :class:`TopicBus`.

    Topic scheme mirrors the reference (``mqtt_comm_manager.py:47-57``):
    rank 0 (server) publishes to ``{prefix}0_{receiver}`` and subscribes to
    every ``{prefix}{client}``; clients publish ``{prefix}{rank}`` and
    subscribe ``{prefix}0_{rank}``."""

    def __init__(
        self,
        rank: int,
        bus: TopicBus,
        size: int,
        topic_prefix: str = "fedml_",
    ):
        super().__init__(rank)
        self.bus = bus
        self.size = size
        self.prefix = topic_prefix
        if rank == 0:
            for c in range(1, size):
                bus.subscribe(f"{self.prefix}{c}", self._on_message)
        else:
            bus.subscribe(f"{self.prefix}0_{rank}", self._on_message)

    def _topic_for(self, receiver: int) -> str:
        return (
            f"{self.prefix}0_{receiver}"
            if self.rank == 0
            else f"{self.prefix}{self.rank}"
        )

    def _on_message(self, topic: str, payload: bytes) -> None:
        try:
            data = wire.open_sealed(payload)
        except wire.CorruptFrameError:
            self.note_receive(len(payload))
            # damaged between publisher and subscriber (the broker
            # daemon routes payloads untouched, so the seal is
            # end-to-end): count + drop — QoS-0 semantics make the
            # drop legal and the layers above heal it
            telemetry.METRICS.inc("transport.corrupt_frames")
            telemetry.RECORDER.record(
                "corrupt_frame", rank=self.rank, nbytes=len(payload)
            )
            return
        except wire.WireVersionError as err:
            self.note_receive(len(payload))
            telemetry.flight_dump(
                "wire_version_mismatch", rank=self.rank,
                detail=str(err),
            )
            print(f"rank {self.rank}: {err}", file=sys.stderr)
            self.stop()
            return
        msg = Message.decode(data)
        self.note_receive(len(payload), msg.msg_type)
        self.deliver(self._inflate(msg))

    def _deflate(self, msg: Message) -> Message:
        return msg  # plain MQTT: whole message on the topic

    def _inflate(self, msg: Message) -> Message:
        return msg

    def send_message(self, msg: Message) -> None:
        sealed = wire.seal(self._deflate(msg).encode())
        corrupt_seed = getattr(msg, "chaos_corrupt", None)
        if corrupt_seed is not None:
            # chaos 'corrupt' fault: flip seeded bits AFTER sealing so
            # the subscriber-side CRC catches the damage
            sealed = wire.flip_bits(sealed, corrupt_seed)
        self.note_send(msg, len(sealed))
        self.bus.publish(self._topic_for(msg.receiver), sealed)


class PubSubBlobTransport(PubSubTransport):
    """MQTT+S3-shaped: control plane on the topic bus, bulk ``model_params``
    in the blob store (reference ``mqtt_s3_comm_manager.py:172-211`` /
    ``:141-163``)."""

    def __init__(
        self,
        rank: int,
        bus: TopicBus,
        store: BlobStore,
        size: int,
        topic_prefix: str = "fedml_",
    ):
        super().__init__(rank, bus, size, topic_prefix)
        self.store = store

    def _deflate(self, msg: Message) -> Message:
        params = msg.get(KEY_MODEL_PARAMS)
        if params is None:
            return msg
        # blob = the params subtree through the SAME wire codec (pickle-5
        # meta + native tensor frame) as whole messages
        carrier = Message(-1, msg.sender, msg.receiver,
                          {KEY_MODEL_PARAMS: params})
        key = f"{self._topic_for(msg.receiver)}_{uuid.uuid4()}"
        url = self.store.put(key, carrier.encode())
        payload = {
            k: v for k, v in msg.payload.items() if k != KEY_MODEL_PARAMS
        }
        payload[KEY_BLOB] = key
        payload[KEY_BLOB_URL] = url
        return Message(msg.msg_type, msg.sender, msg.receiver, payload,
                       trace=msg.trace)

    def _inflate(self, msg: Message) -> Message:
        key = msg.get(KEY_BLOB)
        if key is None:
            return msg
        carrier = Message.decode(self.store.get(key))
        # each key is a fresh uuid with a single receiver: reclaim the blob
        # immediately so a long run does not accumulate one model-sized
        # object per message
        self.store.delete(key)
        payload = {
            k: v
            for k, v in msg.payload.items()
            if k not in (KEY_BLOB, KEY_BLOB_URL)
        }
        payload[KEY_MODEL_PARAMS] = carrier.get(KEY_MODEL_PARAMS)
        return Message(msg.msg_type, msg.sender, msg.receiver, payload,
                       trace=msg.trace)

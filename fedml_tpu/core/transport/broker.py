"""TCP pub/sub broker: the `TopicBus` interface served over a socket.

The reference's MQTT backends talk to an EXTERNAL broker process via
paho-mqtt (``fedml_core/distributed/communication/mqtt/
mqtt_comm_manager.py:14,47-57``): the broker is what makes the pub/sub
path cross-process. No MQTT broker exists in this environment, so this
module provides the minimal broker a federated run needs:

- :class:`BrokerDaemon` — a standalone TCP daemon (also runnable as
  ``python -m fedml_tpu.core.transport.broker --port N``) that routes
  PUBLISH frames to every connection SUBSCRIBEd to the topic. Like an
  MQTT broker, it is payload-agnostic: the federated wire codec rides
  through it untouched.
- :class:`RemoteTopicBus` — the client side; implements the same
  ``subscribe(topic, cb)`` / ``publish(topic, payload)`` contract as the
  in-process :class:`~fedml_tpu.core.transport.pubsub.TopicBus`, so
  ``PubSubTransport`` / ``PubSubBlobTransport`` run unchanged across OS
  processes (paho analog: ``mqtt.Client`` + network-loop thread calling
  ``on_message``).

Wire protocol (both directions, length-prefixed frames)::

    op(1: b"S" subscribe | b"P" publish) || u32 topic_len || topic utf-8
        || u64 payload_len || payload

Subscribe frames carry an empty payload. Delivery semantics match MQTT
QoS 0: no retained messages, publishes to a topic with no subscriber are
dropped (deployment readiness must therefore be handshaken above the
transport — see :mod:`fedml_tpu.experiments.deploy`).
"""

from __future__ import annotations

import argparse
import os
import queue
import socket
import struct
import threading
from typing import Callable

from fedml_tpu.core import telemetry
from fedml_tpu.core.transport.retry import (
    RetryExhausted,
    RetryPolicy,
    iter_attempts,
)

_OP_SUB = b"S"
_OP_PUB = b"P"
_TOPIC_HDR = struct.Struct(">I")
_PAYLOAD_HDR = struct.Struct(">Q")

#: Outbound frames queued per subscriber before the broker declares it
#: wedged and drops it (MQTT brokers do the same with their inflight
#: window; QoS-0 semantics make the drop legal).
_SUB_QUEUE_MAX = 256
#: Socket-level send timeout per frame to one subscriber.
_SUB_SEND_TIMEOUT_S = 10.0


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(sock: socket.socket) -> tuple[bytes, str, bytes] | None:
    op = _recv_exact(sock, 1)
    if op is None:
        return None
    hdr = _recv_exact(sock, _TOPIC_HDR.size)
    if hdr is None:
        return None
    (tlen,) = _TOPIC_HDR.unpack(hdr)
    topic = _recv_exact(sock, tlen)
    if topic is None:
        return None
    hdr = _recv_exact(sock, _PAYLOAD_HDR.size)
    if hdr is None:
        return None
    (plen,) = _PAYLOAD_HDR.unpack(hdr)
    payload = _recv_exact(sock, plen) if plen else b""
    if payload is None:
        return None
    return op, topic.decode("utf-8"), payload


def _frame(op: bytes, topic: str, payload: bytes = b"") -> bytes:
    t = topic.encode("utf-8")
    return (
        op + _TOPIC_HDR.pack(len(t)) + t
        + _PAYLOAD_HDR.pack(len(payload)) + payload
    )


class _SubWriter:
    """Per-connection outbound queue + writer thread. Routing threads
    enqueue and move on; only THIS thread ever blocks on the subscriber's
    socket, so one wedged consumer cannot stall routing from any
    publisher (ADVICE round-5: the old per-connection write lock held the
    publisher's reader thread hostage)."""

    def __init__(self, conn: socket.socket, on_dead):
        self.conn = conn
        self._on_dead = on_dead
        self._q: queue.Queue[bytes | None] = queue.Queue(
            maxsize=_SUB_QUEUE_MAX
        )
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def offer(self, data: bytes) -> bool:
        """Enqueue without blocking; a full queue means the consumer is
        wedged — report failure so the router drops it (QoS 0)."""
        try:
            self._q.put_nowait(data)
            return True
        except queue.Full:
            return False

    def close(self) -> None:
        # sentinel, not queue teardown: the writer drains what it can,
        # then exits; put_nowait keeps close() non-blocking on a full
        # queue (the writer is stuck anyway — its socket is being closed)
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass

    def _run(self) -> None:
        try:
            self.conn.settimeout(_SUB_SEND_TIMEOUT_S)
        except OSError:
            pass
        while True:
            data = self._q.get()
            if data is None:
                return
            try:
                self.conn.sendall(data)
            except OSError:  # includes socket.timeout: wedged consumer
                self._on_dead(self.conn)
                return


class BrokerDaemon:
    """Topic router. One reader thread per connection; outbound frames go
    through per-subscriber send queues (:class:`_SubWriter`), so a slow or
    stuck subscriber is dropped instead of stalling the router."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.5)
        self.host, self.port = self._srv.getsockname()[:2]
        self._subs: dict[str, list[socket.socket]] = {}
        self._writers: dict[socket.socket, _SubWriter] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._accept_thread: threading.Thread | None = None
        # keyed by connection and pruned in _drop, so a long-lived
        # broker serving reconnecting clients doesn't accumulate one
        # dead Thread object per historical connection
        self._readers: dict[socket.socket, threading.Thread] = {}

    def start(self) -> "BrokerDaemon":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._accept_thread = t
        return self

    def serve_forever(self) -> None:
        self.start()
        self._stopped.wait()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True
            )
            with self._lock:
                self._writers[conn] = _SubWriter(conn, self._drop)
                self._readers[conn] = t
            t.start()

    def _client_loop(self, conn: socket.socket) -> None:
        try:
            while not self._stopped.is_set():
                frame = _read_frame(conn)
                if frame is None:
                    return
                op, topic, payload = frame
                if op == _OP_SUB:
                    with self._lock:
                        subs = self._subs.setdefault(topic, [])
                        # dedupe: a client that reconnects replays its
                        # subscriptions AND may retry the triggering SUB
                        # frame; a doubled entry would deliver every
                        # publish twice for the rest of the run
                        if conn not in subs:
                            subs.append(conn)
                elif op == _OP_PUB:
                    self._route(topic, payload)
        finally:
            self._drop(conn)

    def _route(self, topic: str, payload: bytes) -> None:
        with self._lock:
            subs = list(self._subs.get(topic, ()))
        data = _frame(_OP_PUB, topic, payload)
        for s in subs:
            with self._lock:
                writer = self._writers.get(s)
            if writer is None:
                continue
            if not writer.offer(data):
                # queue full: the consumer stopped draining long ago —
                # cut it loose so the rest of the world keeps routing
                self._drop(s)

    def _drop(self, conn: socket.socket) -> None:
        with self._lock:
            writer = self._writers.pop(conn, None)
            self._readers.pop(conn, None)
            for subs in self._subs.values():
                while conn in subs:
                    subs.remove(conn)
        if writer is not None:
            writer.close()
        try:
            conn.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stopped.set()
        self._srv.close()
        # close every live connection: reader threads blocked in recv()
        # unblock instead of lingering into interpreter shutdown (daemon
        # threads inside recv at finalization are a segfault factory)
        with self._lock:
            conns = list(self._writers)
            readers = list(self._readers.values())
        for conn in conns:
            self._drop(conn)
        for t in readers:
            if t is not threading.current_thread():
                t.join(timeout=2.0)


class RemoteTopicBus:
    """Client side of the broker: the ``TopicBus`` contract over one TCP
    connection. Callbacks run on the bus's reader thread (paho's
    ``loop_start`` network thread calling ``on_message``).

    Connect uses the shared exponential-backoff policy (the broker may
    still be starting); a send that hits a dead socket transparently
    re-dials and replays the topic subscriptions — paho's
    ``reconnect_on_failure`` behavior, which the reference's MQTT path
    gets for free from the library."""

    def __init__(
        self, host: str, port: int, connect_timeout: float = 10.0
    ):
        self.host, self.port = host, port
        self._connect_policy = RetryPolicy(
            max_attempts=1000, base_delay_s=0.1, max_delay_s=1.0,
            deadline_s=connect_timeout,
        )
        self._cbs: dict[str, list[Callable[[str, bytes], None]]] = {}
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._stopped = threading.Event()
        self._reader: threading.Thread | None = None
        self._sock: socket.socket | None = None
        with self._wlock:
            self._dial_locked()

    def _dial_locked(self) -> None:
        """(Re)connect + replay subscriptions + restart the reader.
        Caller holds ``_wlock``."""
        last_err: Exception | None = None
        # per-process jitter seed: after a broker restart, N clients
        # must not retry in lockstep waves against the recovering daemon
        for _ in iter_attempts(self._connect_policy, seed=os.getpid(),
                               stop=self._stopped):
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=5
                )
                break
            except OSError as err:  # broker may still be starting
                last_err = err
        else:
            raise RetryExhausted(
                f"broker {self.host}:{self.port} unreachable: {last_err}"
            ) from last_err
        self._sock.settimeout(None)
        with self._lock:
            topics = list(self._cbs)
        for topic in topics:  # replay subscriptions on the new conn
            self._sock.sendall(_frame(_OP_SUB, topic))
        # the previous reader (if any) exits on its dead socket
        self._reader = threading.Thread(
            target=self._read_loop, args=(self._sock,), daemon=True
        )
        self._reader.start()

    def _send_frame(self, data: bytes) -> None:
        with self._wlock:
            last: Exception | None = None
            for attempt in range(3):
                if attempt:
                    # redial can itself die mid-handshake (broker
                    # flapping): the SUB replay inside _dial_locked and
                    # the resend below stay inside this loop so no bare
                    # OSError escapes to publish()/subscribe() callers
                    telemetry.METRICS.inc("transport.reconnects")
                    telemetry.RECORDER.record(
                        "broker_redial",
                        broker=f"{self.host}:{self.port}",
                    )
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    try:
                        self._dial_locked()
                    except RetryExhausted:
                        raise  # broker unreachable: fail loudly now
                    except OSError as err:
                        last = err  # flapped mid-handshake: try again
                        continue
                    if data[:1] == _OP_SUB:
                        # the redial already replayed every subscription
                        # (including the one this frame carries) —
                        # resending would double-subscribe
                        return
                try:
                    # fedlint: disable=lock-hygiene  _wlock IS the
                    # frame serializer: one socket, whole frames — a
                    # send outside it could interleave with a redial's
                    # SUB replay and corrupt the stream. Nothing else
                    # ever waits on _wlock holders (publish/subscribe
                    # are the only takers), so the block is bounded by
                    # the socket timeout, not a deadlock risk.
                    self._sock.sendall(data)
                    return
                except OSError as err:
                    if self._stopped.is_set():
                        raise
                    last = err
            raise RetryExhausted(
                f"publish to broker {self.host}:{self.port} failed "
                f"after reconnects: {last!r}"
            ) from last

    def subscribe(self, topic: str, callback: Callable[[str, bytes], None]):
        first = False
        with self._lock:
            cbs = self._cbs.setdefault(topic, [])
            first = not cbs
            cbs.append(callback)
        if first:  # one broker-side subscription per topic per process
            self._send_frame(_frame(_OP_SUB, topic))

    def publish(self, topic: str, payload: bytes) -> None:
        self._send_frame(_frame(_OP_PUB, topic, payload))

    def _read_loop(self, sock: socket.socket) -> None:
        while not self._stopped.is_set():
            frame = _read_frame(sock)
            if frame is None:
                return
            _, topic, payload = frame
            with self._lock:
                cbs = list(self._cbs.get(topic, ()))
            for cb in cbs:
                cb(topic, payload)

    def close(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if (self._reader is not None
                and self._reader is not threading.current_thread()):
            self._reader.join(timeout=2.0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="fedml_tpu pub/sub broker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=29950)
    a = p.parse_args(argv)
    daemon = BrokerDaemon(a.host, a.port)
    print(f"broker listening on {daemon.host}:{daemon.port}", flush=True)
    daemon.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""TCP pub/sub broker: the `TopicBus` interface served over a socket.

The reference's MQTT backends talk to an EXTERNAL broker process via
paho-mqtt (``fedml_core/distributed/communication/mqtt/
mqtt_comm_manager.py:14,47-57``): the broker is what makes the pub/sub
path cross-process. No MQTT broker exists in this environment, so this
module provides the minimal broker a federated run needs:

- :class:`BrokerDaemon` — a standalone TCP daemon (also runnable as
  ``python -m fedml_tpu.core.transport.broker --port N``) that routes
  PUBLISH frames to every connection SUBSCRIBEd to the topic. Like an
  MQTT broker, it is payload-agnostic: the federated wire codec rides
  through it untouched.
- :class:`RemoteTopicBus` — the client side; implements the same
  ``subscribe(topic, cb)`` / ``publish(topic, payload)`` contract as the
  in-process :class:`~fedml_tpu.core.transport.pubsub.TopicBus`, so
  ``PubSubTransport`` / ``PubSubBlobTransport`` run unchanged across OS
  processes (paho analog: ``mqtt.Client`` + network-loop thread calling
  ``on_message``).

Wire protocol (both directions, length-prefixed frames)::

    op(1: b"S" subscribe | b"P" publish) || u32 topic_len || topic utf-8
        || u64 payload_len || payload

Subscribe frames carry an empty payload. Delivery semantics match MQTT
QoS 0: no retained messages, publishes to a topic with no subscriber are
dropped (deployment readiness must therefore be handshaken above the
transport — see :mod:`fedml_tpu.experiments.deploy`).
"""

from __future__ import annotations

import argparse
import socket
import struct
import threading
import time
from typing import Callable

_OP_SUB = b"S"
_OP_PUB = b"P"
_TOPIC_HDR = struct.Struct(">I")
_PAYLOAD_HDR = struct.Struct(">Q")


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(sock: socket.socket) -> tuple[bytes, str, bytes] | None:
    op = _recv_exact(sock, 1)
    if op is None:
        return None
    hdr = _recv_exact(sock, _TOPIC_HDR.size)
    if hdr is None:
        return None
    (tlen,) = _TOPIC_HDR.unpack(hdr)
    topic = _recv_exact(sock, tlen)
    if topic is None:
        return None
    hdr = _recv_exact(sock, _PAYLOAD_HDR.size)
    if hdr is None:
        return None
    (plen,) = _PAYLOAD_HDR.unpack(hdr)
    payload = _recv_exact(sock, plen) if plen else b""
    if payload is None:
        return None
    return op, topic.decode("utf-8"), payload


def _frame(op: bytes, topic: str, payload: bytes = b"") -> bytes:
    t = topic.encode("utf-8")
    return (
        op + _TOPIC_HDR.pack(len(t)) + t
        + _PAYLOAD_HDR.pack(len(payload)) + payload
    )


class BrokerDaemon:
    """Topic router. One reader thread per connection; writes to each
    subscriber are serialized by a per-connection lock (a slow subscriber
    never interleaves another's frame)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.5)
        self.host, self.port = self._srv.getsockname()[:2]
        self._subs: dict[str, list[socket.socket]] = {}
        self._wlocks: dict[socket.socket, threading.Lock] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._accept_thread: threading.Thread | None = None

    def start(self) -> "BrokerDaemon":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._accept_thread = t
        return self

    def serve_forever(self) -> None:
        self.start()
        self._stopped.wait()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                self._wlocks[conn] = threading.Lock()
            threading.Thread(
                target=self._client_loop, args=(conn,), daemon=True
            ).start()

    def _client_loop(self, conn: socket.socket) -> None:
        try:
            while not self._stopped.is_set():
                frame = _read_frame(conn)
                if frame is None:
                    return
                op, topic, payload = frame
                if op == _OP_SUB:
                    with self._lock:
                        self._subs.setdefault(topic, []).append(conn)
                elif op == _OP_PUB:
                    self._route(topic, payload)
        finally:
            self._drop(conn)

    def _route(self, topic: str, payload: bytes) -> None:
        with self._lock:
            subs = list(self._subs.get(topic, ()))
        data = _frame(_OP_PUB, topic, payload)
        for s in subs:
            with self._lock:
                wlock = self._wlocks.get(s)
            if wlock is None:
                continue
            try:
                with wlock:
                    s.sendall(data)
            except OSError:
                self._drop(s)

    def _drop(self, conn: socket.socket) -> None:
        with self._lock:
            self._wlocks.pop(conn, None)
            for subs in self._subs.values():
                while conn in subs:
                    subs.remove(conn)
        try:
            conn.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stopped.set()
        self._srv.close()


class RemoteTopicBus:
    """Client side of the broker: the ``TopicBus`` contract over one TCP
    connection. Callbacks run on the bus's reader thread (paho's
    ``loop_start`` network thread calling ``on_message``)."""

    def __init__(
        self, host: str, port: int, connect_timeout: float = 10.0
    ):
        retry = threading.Event()
        self._sock = None
        t_end = time.monotonic() + connect_timeout
        last_err: Exception | None = None
        while time.monotonic() < t_end:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5)
                break
            except OSError as err:  # broker may still be starting
                last_err = err
                retry.wait(0.2)
        if self._sock is None:
            raise ConnectionError(
                f"broker {host}:{port} unreachable: {last_err}"
            )
        self._sock.settimeout(None)
        self._cbs: dict[str, list[Callable[[str, bytes], None]]] = {}
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._stopped = threading.Event()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def subscribe(self, topic: str, callback: Callable[[str, bytes], None]):
        first = False
        with self._lock:
            cbs = self._cbs.setdefault(topic, [])
            first = not cbs
            cbs.append(callback)
        if first:  # one broker-side subscription per topic per process
            with self._wlock:
                self._sock.sendall(_frame(_OP_SUB, topic))

    def publish(self, topic: str, payload: bytes) -> None:
        with self._wlock:
            self._sock.sendall(_frame(_OP_PUB, topic, payload))

    def _read_loop(self) -> None:
        while not self._stopped.is_set():
            frame = _read_frame(self._sock)
            if frame is None:
                return
            _, topic, payload = frame
            with self._lock:
                cbs = list(self._cbs.get(topic, ()))
            for cb in cbs:
                cb(topic, payload)

    def close(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="fedml_tpu pub/sub broker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=29950)
    a = p.parse_args(argv)
    daemon = BrokerDaemon(a.host, a.port)
    print(f"broker listening on {daemon.host}:{daemon.port}", flush=True)
    daemon.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

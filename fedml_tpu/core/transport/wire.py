"""Sealed wire frames: protocol-version byte + CRC32 payload checksum.

PR 1's chaos transport covered drop/delay/dup/reorder/crash — every wire
failure class except CORRUPTION. A flipped bit in a pickled pytree is
the nastiest of the lot: without a checksum it either crashes the
decoder or, far worse, silently garbles a tensor that then aggregates
into the global model. This module closes that hole for the socket
codecs that ship raw frames (``tcp.py``; the pub/sub payloads that ride
``broker.py``'s daemon end to end):

``seal(payload)``  -> ``u8 version || u32 crc32(payload) || payload``
``open_sealed(b)`` -> payload, or raises

- :class:`CorruptFrameError` — CRC mismatch. The receiving transport
  counts ``transport.corrupt_frames`` and DROPS the frame; the
  fault-tolerance layer above (retry/heartbeat/straggler rounds,
  docs/FAULT_TOLERANCE.md) heals the loss like any drop.
- :class:`WireVersionError` — the version byte does not match. This is
  rolling-restart skew (one rank runs an older build with a different
  frame layout) and MUST fail loudly: treating mismatched framing as
  corruption would silently drop every message forever. The legacy
  pre-seal TCP frame is detected specifically (its first payload byte
  is ``FMG1``'s ``F``/0x46, never a version number) so the diagnostic
  names the actual problem.

gRPC keeps its own HTTP/2 integrity machinery and stays unsealed.
"""

from __future__ import annotations

import random
import struct
import zlib

#: bump when the sealed frame layout changes; receivers reject anything
#: else loudly (rolling-restart skew must not garble pytrees)
PROTOCOL_VERSION = 1

_SEAL_HDR = struct.Struct(">BI")  # version byte || crc32
SEAL_OVERHEAD = _SEAL_HDR.size

#: first byte of a legacy (pre-seal) message frame: the wire magic
#: ``FMG1`` of the Message codec
_LEGACY_MAGIC0 = ord("F")


class CorruptFrameError(ValueError):
    """CRC32 mismatch: the payload was damaged in flight. Count it,
    drop it, let retries/stragglers heal it."""


class WireVersionError(RuntimeError):
    """Frame carries a different protocol version — rolling-restart
    skew. Fail loudly; do not attempt to parse."""


def seal_header(payload) -> bytes:
    """The 5-byte seal for ``payload`` alone. Transports that build a
    frame from pieces anyway (length prefix + seal + payload) use this
    to skip ``seal``'s intermediate full-payload concatenation — on the
    model-sync path the payload is multi-MB and the extra copy is pure
    waste."""
    return _SEAL_HDR.pack(
        PROTOCOL_VERSION, zlib.crc32(payload) & 0xFFFFFFFF
    )


def seal(payload: bytes) -> bytes:
    """Wrap ``payload`` with the version byte + CRC32."""
    return seal_header(payload) + payload


def open_sealed(data):
    """Verify + strip the seal. Raises :class:`WireVersionError` on a
    version mismatch, :class:`CorruptFrameError` on a CRC mismatch.

    Returns a zero-copy :class:`memoryview` of the payload region —
    every downstream consumer (``Message.decode``, ``zlib``, ``pickle``)
    reads buffers, and copying the multi-MB model frames here would
    double the receive path's transient memory."""
    if len(data) < SEAL_OVERHEAD:
        raise CorruptFrameError(
            f"sealed frame truncated to {len(data)} bytes"
        )
    version, crc = _SEAL_HDR.unpack_from(data, 0)
    if version != PROTOCOL_VERSION:
        hint = (
            " (peer is running a pre-seal build — the legacy frame "
            "starts with the FMG1 message magic)"
            if version == _LEGACY_MAGIC0 else ""
        )
        raise WireVersionError(
            f"wire protocol version mismatch: got {version}, this "
            f"build speaks {PROTOCOL_VERSION}{hint}; rolling restarts "
            "must upgrade every rank of a world together"
        )
    payload = memoryview(data)[SEAL_OVERHEAD:]
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CorruptFrameError(
            f"frame CRC mismatch over {len(payload)} payload bytes"
        )
    return payload


def flip_bits(frame: bytes, seed: int, n_flips: int = 3) -> bytes:
    """Seeded bit corruption of a SEALED frame (the chaos ``corrupt``
    fault, :mod:`fedml_tpu.core.transport.chaos`): flips ``n_flips``
    bits anywhere past the version byte — the CRC field and the payload
    are both fair game, the version byte is not (corrupting it would
    exercise the skew path, which is a different failure class with its
    own fault)."""
    if len(frame) <= 1:
        return frame
    rng = random.Random(seed)
    buf = bytearray(frame)
    for _ in range(max(1, n_flips)):
        i = rng.randrange(1, len(buf))
        buf[i] ^= 1 << rng.randrange(8)
    return bytes(buf)

"""Chaos-injection transport: seeded, deterministic fault injection.

Real cross-device FL (the workload the reference was built for) is
defined by clients that crash, stall, and drop mid-round — but neither
the reference nor a clean-room simulator exercises those paths unless
faults can be injected ON DEMAND and REPRODUCIBLY. FedJAX (arxiv
2108.02117) makes the same argument for modelling client unreliability
deterministically inside the simulator; this module brings it to the
cross-process runtime: :class:`ChaosTransport` wraps any
:class:`~fedml_tpu.core.transport.base.BaseTransport` and perturbs its
traffic according to a :class:`FaultPolicy` whose every decision comes
from a seeded RNG — the same seed replays the same faults.

Fault model (send-side, plus crash-on-receive):

- **drop** — the message silently never leaves this rank (QoS-0 loss).
- **delay** — delivery deferred by a bounded random interval (congested
  WAN link).
- **duplicate** — the message is sent twice (at-least-once transports,
  MQTT QoS 1 re-delivery).
- **reorder** — the message is held back and ships after the NEXT send
  (multi-path routing).
- **corrupt** — seeded bit-flips in the message's SEALED wire frame
  (cosmic rays, failing NICs, buggy middleboxes). The transport codecs
  that seal frames (tcp, pubsub — :mod:`.wire`) compute the CRC first
  and flip after, so the receiver detects the damage, counts
  ``transport.corrupt_frames``, and drops the frame; the retry /
  straggler machinery heals it like a drop. The one wire failure class
  PR 1 left uncovered.
- **crash-at-round-N** — the first inbound message tagged with
  ``round_idx >= N`` kills this rank: either it goes silent (swallows
  all subsequent traffic, ``crash_mode="silent"``) or the whole process
  exits (``crash_mode="exit"``, exit code :data:`CHAOS_EXIT_CODE`) — the
  deterministic stand-in for ``kill -9`` mid-round.

``FINISH`` frames and the liveness/handshake plane (READY/ACK/HEARTBEAT)
are protected by default (``protect_types``): the former so a
zero-straggler-tolerance run still terminates, the latter so
timing-driven protocol traffic doesn't consume RNG draws and break the
work-message fault pattern's replayability. Chaos on those planes is
opt-in (``protect_types=()``).
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading

from fedml_tpu.core import telemetry
from fedml_tpu.core.message import (
    KEY_ROUND,
    MSG_TYPE_C2S_JOIN,
    MSG_TYPE_C2S_LEAVE,
    MSG_TYPE_C2S_READY,
    MSG_TYPE_FINISH,
    MSG_TYPE_HEARTBEAT,
    MSG_TYPE_S2C_ACK,
    MSG_TYPE_S2C_WELCOME,
    Message,
)
from fedml_tpu.core.transport.base import BaseTransport

#: Exit status of a rank killed by ``crash_mode="exit"`` — launchers and
#: tests can tell an injected crash from a genuine failure.
CHAOS_EXIT_CODE = 86


@dataclasses.dataclass(frozen=True)
class FaultPolicy:
    """Per-rank fault configuration. All probabilities are per-message;
    decisions are drawn from ``random.Random(seed ^ rank)`` in a fixed
    order, so a run is replayable given (seed, message sequence)."""

    seed: int = 0
    drop_prob: float = 0.0
    delay_prob: float = 0.0
    delay_min_s: float = 0.005
    delay_max_s: float = 0.05
    dup_prob: float = 0.0
    reorder_prob: float = 0.0
    # per-message probability of seeded bit-flips in the sealed wire
    # frame (detected + dropped by the CRC codecs; see module doc)
    corrupt_prob: float = 0.0
    crash_at_round: int | None = None
    crash_mode: str = "silent"  # "silent" | "exit"
    # protected by default: FINISH (so a zero-tolerance run still
    # terminates) and the liveness/handshake/recovery plane (READY/ACK/
    # HEARTBEAT/JOIN/WELCOME counts are timing-driven — re-announce
    # loops, monitor threads, supervised restarts — so letting them
    # consume RNG draws would make the WORK-message fault pattern
    # non-replayable across runs). Chaos on these planes is opt-in via
    # protect_types=(). Note crash_at_round is a RECEIVE-side trigger
    # and ignores this list: a WELCOME tagged round >= N still kills a
    # rank whose policy says so — restart argv should drop fault flags
    # (the Supervisor's restart_argv does).
    protect_types: tuple[int, ...] = (
        MSG_TYPE_FINISH,
        MSG_TYPE_C2S_READY,
        MSG_TYPE_S2C_ACK,
        MSG_TYPE_HEARTBEAT,
        MSG_TYPE_C2S_JOIN,
        MSG_TYPE_S2C_WELCOME,
        MSG_TYPE_C2S_LEAVE,
    )

    def __post_init__(self):
        if self.crash_mode not in ("silent", "exit"):
            raise ValueError(
                f"crash_mode must be 'silent' or 'exit', "
                f"got {self.crash_mode!r}"
            )

    def enabled(self) -> bool:
        return bool(
            self.drop_prob
            or self.delay_prob
            or self.dup_prob
            or self.reorder_prob
            or self.corrupt_prob
            or self.crash_at_round is not None
        )


class _InboundShim:
    """Sole observer of the inner transport: funnels its dispatch loop
    into the chaos layer's inbound fault check."""

    def __init__(self, outer: "ChaosTransport"):
        self.outer = outer

    def receive_message(self, msg_type: int, msg: Message) -> None:
        self.outer._on_inbound(msg)


class ChaosTransport(BaseTransport):
    """Fault-injecting wrapper. The manager talks to THIS transport; the
    wrapped transport does the real I/O on a background pump thread."""

    def __init__(self, inner: BaseTransport, policy: FaultPolicy):
        super().__init__(inner.rank)
        self.inner = inner
        self.policy = policy
        self._rng = random.Random(policy.seed ^ (inner.rank * 0x9E3779B9))
        self._rng_lock = threading.Lock()
        self.crashed = threading.Event()
        self._held: Message | None = None  # reorder buffer
        self._held_lock = threading.Lock()
        self._pump: threading.Thread | None = None
        # counters for diagnostics / tests ({fault -> count})
        self.stats = {
            "sent": 0, "dropped": 0, "delayed": 0, "duplicated": 0,
            "reordered": 0, "corrupted": 0,
        }
        # the inner transport still counts wire bytes at its decode
        # site, but deliver-time telemetry (trace marks, inbox gauge)
        # belongs to THIS transport — the one the actor drains
        inner._telemetry_deliver = False
        inner.add_observer(_InboundShim(self))

    def _stat(self, key: str, n: int = 1) -> None:
        """Bump a fault counter in both the local stats dict (tests)
        and the process metrics registry (docs/OBSERVABILITY.md)."""
        self.stats[key] += n
        telemetry.METRICS.inc("chaos." + key, n)

    # -- receive path ------------------------------------------------------
    def start(self) -> None:
        self.inner.start()
        if self._pump is None:
            # drain the inner transport's inbox through its dispatch loop
            # (which calls our shim) on a dedicated thread, so the outer
            # inbox — the one the actor blocks on — sees faulted traffic
            self._pump = threading.Thread(
                target=self.inner.handle_receive_message,
                daemon=True,
                name=f"chaos-pump-rank{self.rank}",
            )
            self._pump.start()

    def _crash(self) -> None:
        self.crashed.set()
        telemetry.METRICS.inc("chaos.crashes")
        telemetry.RECORDER.record(
            "chaos_crash", rank=self.rank, mode=self.policy.crash_mode
        )
        if self.policy.crash_mode == "exit":
            # the deterministic `kill -9`: no atexit, no cleanup, no
            # FINISH — exactly what a preempted spot VM looks like
            os._exit(CHAOS_EXIT_CODE)

    def _on_inbound(self, msg: Message) -> None:
        if self.crashed.is_set():
            return  # dead processes read nothing
        n = self.policy.crash_at_round
        if n is not None:
            rnd = msg.get(KEY_ROUND)
            if rnd is not None and int(rnd) >= n:
                self._crash()
                return  # the fatal message is never seen by the actor
        self.deliver(msg)

    # -- send path ---------------------------------------------------------
    def send_message(self, msg: Message) -> None:
        if self.crashed.is_set():
            return  # dead processes send nothing
        if msg.msg_type in self.policy.protect_types:
            self.inner.send_message(msg)
            return
        p = self.policy
        with self._rng_lock:
            # fixed draw order keeps the decision stream aligned across
            # runs even when an earlier fault short-circuits
            r_drop, r_dup, r_delay, r_reorder, r_u, r_corrupt = (
                self._rng.random() for _ in range(6)
            )
        if r_drop < p.drop_prob:
            self._stat("dropped")
            return
        if r_corrupt < p.corrupt_prob:
            # mark the message; the sealing codec (tcp/pubsub) flips
            # seeded bits AFTER computing the CRC, so the receiver's
            # checksum detects + drops the frame. The corruption seed
            # derives from the draw itself — no extra RNG consumption,
            # fully replayable. Composes with dup/delay (the marker
            # rides every copy).
            msg.chaos_corrupt = int(r_corrupt * (1 << 31))
            self._stat("corrupted")
        elif getattr(msg, "chaos_corrupt", None) is not None:
            # a RETRY re-sends the same Message object: clear a stale
            # marker so this send's draw decides its fate — otherwise a
            # once-corrupted message is re-corrupted on every retry and
            # the retry machinery can never heal the loss
            del msg.chaos_corrupt
        if r_reorder < p.reorder_prob:
            swap = None
            with self._held_lock:
                if self._held is None:
                    self._held = msg  # ships after the NEXT send
                    self._stat("reordered")
                    # a tail message must not be held forever if no
                    # successor ever comes
                    t = threading.Timer(0.25, self._flush_held)
                    t.daemon = True
                    t.start()
                    return
                swap = self._held
                self._held = None
            self._send_now(msg)  # overtakes the held one
            self._send_now(swap, swallow_errors=True)
            return
        delay = None
        if r_delay < p.delay_prob:
            delay = p.delay_min_s + r_u * (p.delay_max_s - p.delay_min_s)
        if r_dup < p.dup_prob:
            self._stat("duplicated")
            self._dispatch(msg, delay)
            self._dispatch(msg, delay)
            return
        self._dispatch(msg, delay)
        with self._held_lock:
            held, self._held = self._held, None
        if held is not None:
            self._send_now(held, swallow_errors=True)

    def _dispatch(self, msg: Message, delay: float | None) -> None:
        if delay is None:
            self._send_now(msg)
            return
        self._stat("delayed")
        t = threading.Timer(
            delay, self._send_now, args=(msg,), kwargs={
                "swallow_errors": True}
        )
        t.daemon = True
        t.start()

    def _flush_held(self) -> None:
        with self._held_lock:
            held, self._held = self._held, None
        if held is not None:
            self._send_now(held, swallow_errors=True)

    def _send_now(self, msg: Message, swallow_errors: bool = False) -> None:
        if self.crashed.is_set():
            return
        self._stat("sent")
        if not swallow_errors:
            self.inner.send_message(msg)
            return
        try:
            # async redeliveries (timer threads) degrade send failures to
            # drops — the fault-tolerance layer above must absorb loss
            # anyway, and a timer thread has no caller to raise into
            self.inner.send_message(msg)
        except Exception:
            self._stat("dropped")

    def stop(self) -> None:
        super().stop()
        self.inner.stop()

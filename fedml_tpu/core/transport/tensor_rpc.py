"""Tensor-native RPC transport (the TRPC-class backend).

Reference: ``fedml_core/distributed/communication/trpc/trpc_comm_manager.py``
— torch.distributed RPC over TensorPipe: tensor payloads ship without
pickling the tensor bytes into the control stream, and the file carries an
inline message-size micro-benchmark (``:147-209``, grep-able
"--Benchmark" lines).

TPU-native equivalent: the :class:`TcpTransport` socket machinery with a
wire format that puts the native C++ tensor frame FIRST and the (small)
pickled envelope after it, so the receiving side can hand the tensor
region to the zero-copy codec without scanning past python bytes — plus
:func:`benchmark_transport`, the reference's latency micro-benchmark as a
utility usable against ANY BaseTransport.
"""

from __future__ import annotations

import time

import numpy as np

from fedml_tpu.core.message import KEY_MODEL_PARAMS, Message
from fedml_tpu.core.transport.tcp import TcpTransport


class TensorRpcTransport(TcpTransport):
    """TCP + tensor-first framing. Functionally identical to TcpTransport
    (both ride the native codec through ``Message.encode``); kept as a
    named backend for parity with the reference's TRPC option and as the
    attachment point for the micro-benchmark."""


def benchmark_transport(
    a, b, sizes=(1_000, 100_000, 1_000_000), repeats: int = 5
) -> list[dict]:
    """Round-trip latency per payload size between two STARTED transports
    (reference ``trpc_comm_manager.py:147-209`` inline benchmark).
    ``a`` sends float32 tensors of each size to ``b``; returns
    [{"size_bytes", "mean_ms", "mbps"} ...]."""
    results = []
    for size in sizes:
        arr = np.arange(size, dtype=np.float32)
        t0 = time.perf_counter()
        for r in range(repeats):
            a.send_message(
                Message(900, a.rank, b.rank, {KEY_MODEL_PARAMS: arr,
                                              "seq": r})
            )
            got = b._inbox.get(timeout=30)
            assert got.get("seq") == r
        dt = (time.perf_counter() - t0) / repeats
        results.append(
            {
                "size_bytes": int(arr.nbytes),
                "mean_ms": round(dt * 1e3, 3),
                "mbps": round(arr.nbytes / dt / 1e6, 1),
            }
        )
    return results

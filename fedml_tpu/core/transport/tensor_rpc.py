"""Tensor-native RPC transport (the TRPC-class backend).

Reference: ``fedml_core/distributed/communication/trpc/trpc_comm_manager.py``
— torch.distributed RPC over TensorPipe: tensor payloads ship without
pickling the tensor bytes into the control stream, and the file carries an
inline message-size micro-benchmark (``:147-209``, grep-able
"--Benchmark" lines).

TPU-native equivalent: :class:`TcpTransport`'s socket machinery with a
TENSOR-FIRST wire format. Where TcpTransport ships one opaque
``Message.encode()`` buffer (meta pickle first, tensor frame after), this
transport frames the two regions separately::

    u64 frame_len || tensor frame || u64 meta_len || meta pickle

so the receiver streams the (large) tensor region straight into its own
buffer and hands it to the zero-copy native codec (``native/codec.cpp``)
without concatenating it behind python pickle bytes, then reads the
(small) envelope. That is TensorPipe's split: tensors on the payload
channel, control data on the descriptor channel. Also here:
:func:`benchmark_transport`, the reference's latency micro-benchmark as
a utility usable against ANY BaseTransport.
"""

from __future__ import annotations

import socket
import struct
import time

import numpy as np

from fedml_tpu.core.message import KEY_MODEL_PARAMS, Message
from fedml_tpu.core.transport.tcp import TcpTransport, _recv_exact

_HDR = struct.Struct(">Q")


def _recv_into(sock: socket.socket, buf: memoryview) -> bool:
    """Fill ``buf`` exactly from the socket (no intermediate concats —
    the point of tensor-first framing is that the bulk region lands in
    one preallocated buffer the codec can scan in place)."""
    while buf:
        n = sock.recv_into(buf)
        if n == 0:
            return False
        buf = buf[n:]
    return True


class TensorRpcTransport(TcpTransport):
    """TCP with tensor-first framing (see module docstring)."""

    def send_message(self, msg: Message) -> None:
        meta, frame = msg.encode_parts()
        rank = msg.receiver
        wire = (
            _HDR.pack(len(frame)) + frame + _HDR.pack(len(meta)) + meta
        )
        self.note_send(msg, len(wire))
        self._send_wire(rank, wire)

    def _read_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stopped.is_set():
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                (frame_len,) = _HDR.unpack(hdr)
                frame = bytearray(frame_len)
                if frame_len and not _recv_into(conn, memoryview(frame)):
                    return
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                (meta_len,) = _HDR.unpack(hdr)
                meta = _recv_exact(conn, meta_len)
                if meta is None:
                    return
                msg = Message.from_parts(meta, frame)
                self.note_receive(
                    2 * _HDR.size + frame_len + meta_len, msg.msg_type
                )
                self.deliver(msg)


def benchmark_transport(
    a, b, sizes=(1_000, 100_000, 1_000_000), repeats: int = 5
) -> list[dict]:
    """Round-trip latency per payload size between two STARTED transports
    (reference ``trpc_comm_manager.py:147-209`` inline benchmark).
    ``a`` sends float32 tensors of each size to ``b``; returns
    [{"size_bytes", "mean_ms", "mbps"} ...]."""
    results = []
    for size in sizes:
        arr = np.arange(size, dtype=np.float32)
        t0 = time.perf_counter()
        for r in range(repeats):
            a.send_message(
                Message(900, a.rank, b.rank, {KEY_MODEL_PARAMS: arr,
                                              "seq": r})
            )
            got = b._inbox.get(timeout=30)
            assert got.get("seq") == r
        dt = (time.perf_counter() - t0) / repeats
        results.append(
            {
                "size_bytes": int(arr.nbytes),
                "mean_ms": round(dt * 1e3, 3),
                "mbps": round(arr.nbytes / dt / 1e6, 1),
            }
        )
    return results

"""Retry with exponential backoff + jitter and per-op deadlines.

The real transports each grew an ad-hoc recovery loop (tcp's one-shot
dead-socket retry, the broker client's fixed 0.2 s connect poll, grpc's
none at all). This module replaces them with one policy: capped
exponential backoff, seeded jitter (so N clients restarting against the
same server don't reconnect in lockstep), an overall per-op deadline,
and cooperative abort via the transport's stop event.

The reference has no equivalent — its MQTT path leans on paho's internal
reconnect and its gRPC path simply raises (``grpc_comm_manager.py``);
crash-recovery there is "restart the run".
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Iterable

from fedml_tpu.core import telemetry


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule + deadline for one logical operation (a connect,
    a send). Delay for attempt k (0-based) is
    ``min(max_delay_s, base_delay_s * multiplier**k)`` stretched by up to
    ``jitter`` (fraction, seeded)."""

    max_attempts: int = 6
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: float = 15.0  # overall wall-clock budget for the op
    # attempts the deadline may NOT cut short: a single SLOW failed
    # attempt (a bulk frame that died mid-transfer after outliving the
    # deadline) must still get its one fresh-connection retry — one
    # transient fault on a long transfer is not a dead peer
    min_attempts: int = 2

    def delay(self, attempt: int, rng: random.Random) -> float:
        d = min(self.max_delay_s, self.base_delay_s * self.multiplier**attempt)
        return d * (1.0 + self.jitter * rng.random())


class RetryExhausted(ConnectionError):
    """All attempts failed (or the deadline/stop event cut them short).
    ``__cause__`` is the last underlying error."""


def call_with_retry(
    fn: Callable,
    *,
    policy: RetryPolicy,
    retry_on: tuple[type[BaseException], ...] = (OSError,),
    describe: str = "op",
    seed: int = 0,
    stop: threading.Event | None = None,
    cleanup: Callable[[], None] | None = None,
):
    """Run ``fn()`` under ``policy``. ``cleanup`` runs between attempts
    (evict a dead pooled socket / channel). ``stop`` aborts immediately
    when set — a stopping transport must not sit out a backoff sleep."""
    rng = random.Random(seed)
    deadline = time.monotonic() + policy.deadline_s
    last: BaseException | None = None
    attempts = 0
    for attempt in range(policy.max_attempts):
        if stop is not None and stop.is_set():
            break
        attempts += 1
        try:
            return fn()
        except retry_on as err:
            last = err
            telemetry.METRICS.inc("transport.retry_attempts")
            telemetry.RECORDER.record(
                "retry", op=describe, attempt=attempts, error=repr(err)
            )
            if cleanup is not None:
                cleanup()
            pause = policy.delay(attempt, rng)
            if (attempts >= policy.min_attempts
                    and time.monotonic() + pause >= deadline):
                break
            if stop is not None:
                if stop.wait(pause):
                    break
            else:
                time.sleep(pause)
    telemetry.METRICS.inc("transport.retry_exhausted")
    telemetry.RECORDER.record(
        "retry_exhausted", op=describe, attempts=attempts, error=repr(last)
    )
    raise RetryExhausted(
        f"{describe} failed after {attempts} attempts "
        f"(budget {policy.max_attempts} / {policy.deadline_s}s): {last!r}"
    ) from last


def iter_attempts(
    policy: RetryPolicy, *, seed: int = 0, stop: threading.Event | None = None
) -> Iterable[int]:
    """Generator form for call sites whose attempt body doesn't fit a
    closure (multi-step connect + handshake): yields attempt indices,
    sleeping the backoff between them, until attempts/deadline/stop run
    out. The caller breaks out on success."""
    rng = random.Random(seed)
    deadline = time.monotonic() + policy.deadline_s
    for attempt in range(policy.max_attempts):
        if stop is not None and stop.is_set():
            return
        yield attempt
        pause = policy.delay(attempt, rng)
        if time.monotonic() + pause >= deadline:
            return
        if stop is not None:
            if stop.wait(pause):
                return
        else:
            time.sleep(pause)

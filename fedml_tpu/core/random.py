"""RNG discipline and client sampling.

The reference seeds numpy with the round index before sampling clients
(``fedml_api/distributed/fedavg/FedAVGAggregator.py:90-98``:
``np.random.seed(round_idx); np.random.choice(..., replace=False)``), which
makes cohorts reproducible across server restarts. We mirror that with folded
JAX keys: every round's key is ``fold_in(root, round_idx)``, every client's
local-training key is ``fold_in(round_key, client_idx)`` — fully deterministic,
order-independent, and traceable under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def round_key(root: jax.Array, round_idx) -> jax.Array:
    return jax.random.fold_in(root, round_idx)


def client_key(rkey: jax.Array, client_idx) -> jax.Array:
    return jax.random.fold_in(rkey, client_idx)


def sample_clients(
    key: jax.Array, num_clients: int, clients_per_round: int
) -> jax.Array:
    """Sample a cohort without replacement (reference ``client_sampling``,
    ``FedAVGAggregator.py:90-98``). If the cohort covers the population,
    returns ``arange`` like the reference does.

    Jit-safe: shapes are static in both branches.
    """
    if clients_per_round >= num_clients:
        return jnp.arange(num_clients, dtype=jnp.int32)
    return jax.random.choice(
        key, num_clients, shape=(clients_per_round,), replace=False
    ).astype(jnp.int32)


def sample_stratum(
    key: jax.Array, stratum, stratum_size: int, cohort_per_stratum: int
) -> jax.Array:
    """One stratum's slice of a stratified cohort: sample
    ``cohort_per_stratum`` of the ``stratum_size`` clients owned by
    ``stratum`` (clients ``[stratum*size, (stratum+1)*size)``), returning
    LOCAL ids. Used by the mesh-sharded runtime where each ``clients``-axis
    shard owns a fixed block of the population and its samples — the TPU
    analog of the reference's data-stays-in-silo placement
    (``fedavg_cross_silo/DistWorker.py:31-54``)."""
    skey = jax.random.fold_in(key, stratum)
    if cohort_per_stratum >= stratum_size:
        return jnp.arange(stratum_size, dtype=jnp.int32)
    return jax.random.choice(
        skey, stratum_size, shape=(cohort_per_stratum,), replace=False
    ).astype(jnp.int32)


def sample_clients_stratified(
    key: jax.Array, num_clients: int, clients_per_round: int, n_strata: int
) -> jax.Array:
    """Host-mirror of the sharded runtime's per-shard sampling: the global
    cohort is the concatenation of each stratum's :func:`sample_stratum`
    choice (as GLOBAL ids). A single-device simulator using this sampler
    follows the exact same trajectory as :class:`ShardedFedAvg` — the basis
    of the sharded-equality tests."""
    assert num_clients % n_strata == 0
    assert clients_per_round % n_strata == 0
    size = num_clients // n_strata
    per = clients_per_round // n_strata
    return jnp.concatenate(
        [sample_stratum(key, s, size, per) + s * size for s in range(n_strata)]
    )

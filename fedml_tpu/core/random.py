"""RNG discipline and client sampling.

The reference seeds numpy with the round index before sampling clients
(``fedml_api/distributed/fedavg/FedAVGAggregator.py:90-98``:
``np.random.seed(round_idx); np.random.choice(..., replace=False)``), which
makes cohorts reproducible across server restarts. We mirror that with folded
JAX keys: every round's key is ``fold_in(root, round_idx)``, every client's
local-training key is ``fold_in(round_key, client_idx)`` — fully deterministic,
order-independent, and traceable under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def round_key(root: jax.Array, round_idx) -> jax.Array:
    return jax.random.fold_in(root, round_idx)


def client_key(rkey: jax.Array, client_idx) -> jax.Array:
    return jax.random.fold_in(rkey, client_idx)


def sample_clients(
    key: jax.Array, num_clients: int, clients_per_round: int
) -> jax.Array:
    """Sample a cohort without replacement (reference ``client_sampling``,
    ``FedAVGAggregator.py:90-98``). If the cohort covers the population,
    returns ``arange`` like the reference does.

    Jit-safe: shapes are static in both branches.
    """
    if clients_per_round >= num_clients:
        return jnp.arange(num_clients, dtype=jnp.int32)
    return jax.random.choice(
        key, num_clients, shape=(clients_per_round,), replace=False
    ).astype(jnp.int32)

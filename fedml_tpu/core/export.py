"""Live observability plane: OpenMetrics export, /statusz, fleet federation.

Until now every metric died in a per-rank file (``metrics_rank<r>.jsonl``
and the at-exit snapshot) readable only after the process exited, and
the server had no view of its clients' metrics at all. This module is
the live surface (docs/OBSERVABILITY.md "Live export and SLOs"):

- :class:`MetricsExporter` — a stdlib ``http.server`` daemon thread per
  rank (``--metrics_port`` / ``telemetry.configure(metrics_port=)``;
  port 0 binds an ephemeral port; off by default, so the
  zero-cost-when-off rule holds: no socket is opened and no per-message
  work is added) serving three endpoints on one listener:

  - ``/metrics`` — Prometheus/OpenMetrics text rendered from
    ``MetricsRegistry.snapshot()``, with REAL histogram bucket series
    (cumulative ``_bucket{le="..."}`` + ``_sum``/``_count``), not just
    the interpolated p50/p95/p99, name-sanitized and ``# TYPE``
    annotated so a stock Prometheus scrape parses it;
  - ``/statusz`` — a JSON run-introspection snapshot assembled from
    registered status sources (the live actors), holding no new locks
    across serialization;
  - ``/healthz`` — liveness + a degraded verdict when any status
    source reports a failure (docs/FAULT_TOLERANCE.md cross-links what
    "healthy" means mid-recovery).

- **fleet federation** — clients piggyback a compact, delta-encoded,
  size-bounded metric summary on the existing heartbeat path (a new
  OPTIONAL ``metrics`` field: old clients simply don't send it, and a
  malformed field is counted + dropped like any other receive-edge
  screen). The server folds each summary into fleet-level aggregates
  under the ``fleet.*`` namespace — per-metric count/sum/min/max plus
  the registry's fixed power-of-two bucket histogram — so ONE scrape of
  rank 0 answers "what is the p95 client round time across the cohort"
  without collecting 10k files. Tier worlds federate leaf→root the
  same way on the uplink heartbeats (a leaf's ``fleet.*`` aggregates
  forward with the prefix stripped, so the root's ``fleet.*`` covers
  the whole subtree).
"""

from __future__ import annotations

import http.server
import json
import math
import re
import threading
import time
import weakref
from typing import Any

from fedml_tpu.core import telemetry

# ---------------------------------------------------------------------------
# OpenMetrics rendering
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_SANITIZED: dict[str, str] = {}


def sanitize_metric_name(name: str) -> str:
    """Registry names are dotted; Prometheus names match
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``. Dots (and anything else illegal)
    become underscores; a leading digit gets a ``_`` prefix. Cached —
    the scrape path renders the same names every time."""
    s = _SANITIZED.get(name)
    if s is None:
        s = _NAME_OK.sub("_", name)
        if not s or s[0].isdigit():
            s = "_" + s
        _SANITIZED[name] = s
    return s


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", r"\\").replace('"', r"\"")
        .replace("\n", r"\n")
    )


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


def render_openmetrics(snapshot: dict[str, Any]) -> str:
    """Render one registry snapshot as Prometheus text exposition
    format. Histograms export their REAL power-of-two buckets as the
    cumulative ``_bucket{le=...}`` series (monotone by construction,
    terminated by ``+Inf`` == ``_count``) plus ``_sum``/``_count``;
    the interpolated p50/p95/p99 ride along as gauges under
    ``<name>_p50`` etc. so dashboards keep the simple form too."""
    lines: list[str] = []
    for name in sorted(snapshot.get("counters", {})):
        v = snapshot["counters"][name]
        s = sanitize_metric_name(name)
        lines.append(f"# TYPE {s} counter")
        lines.append(f"{s} {_fmt(v)}")
    for name in sorted(snapshot.get("gauges", {})):
        v = snapshot["gauges"][name]
        s = sanitize_metric_name(name)
        lines.append(f"# TYPE {s} gauge")
        lines.append(f"{s} {_fmt(v)}")
    for name in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][name]
        s = sanitize_metric_name(name)
        lines.append(f"# TYPE {s} histogram")
        # registry buckets are "le_2^k" exponent tags; the wire wants
        # cumulative counts by ascending upper bound
        items = sorted(
            (int(k.split("^", 1)[1]), c)
            for k, c in h.get("buckets", {}).items()
        )
        cum = 0
        for k, c in items:
            cum += c
            le = _escape_label(_fmt(2.0 ** k))
            lines.append(f'{s}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{s}_bucket{{le="+Inf"}} {h.get("count", 0)}')
        lines.append(f"{s}_sum {_fmt(h.get('sum', 0.0))}")
        lines.append(f"{s}_count {h.get('count', 0)}")
        for p in ("p50", "p95", "p99"):
            if p in h:
                lines.append(f"# TYPE {s}_{p} gauge")
                lines.append(f"{s}_{p} {_fmt(h[p])}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# status sources (/statusz, /healthz)
# ---------------------------------------------------------------------------

# name -> weakref to an object with .status() -> dict. Weak on purpose:
# a module-global strong ref would keep every actor a test ever built
# alive forever. Dead refs are skipped and pruned at snapshot time.
_STATUS_SOURCES: dict[str, "weakref.ref"] = {}
_RUN_STATE: dict[str, Any] = {}
_STATUS_LOCK = threading.Lock()


def register_status_source(name: str, obj: Any) -> None:
    """Register a live object exposing ``status() -> dict`` under
    ``name`` in the ``/statusz`` snapshot (last registration per name
    wins — a restarted actor supersedes its predecessor)."""
    with _STATUS_LOCK:
        _STATUS_SOURCES[name] = weakref.ref(obj)


def set_run_state(**fields: Any) -> None:
    """Cheap run-level fields (current round, run name, ...) for
    drivers without an actor object — the sim harness round loop."""
    with _STATUS_LOCK:
        _RUN_STATE.update(fields)


def status_snapshot() -> dict[str, Any]:
    """The ``/statusz`` document. Each source's ``status()`` builds its
    dict under the source's OWN existing locks (briefly) and returns
    plain data; serialization happens out here with no lock held."""
    with _STATUS_LOCK:
        sources = dict(_STATUS_SOURCES)
        run_state = dict(_RUN_STATE)
    out: dict[str, Any] = {
        "ts": time.time(),
        "rank": telemetry.RECORDER.rank,
    }
    if run_state:
        out["run"] = run_state
    dead = []
    for name, ref in sources.items():
        obj = ref()
        if obj is None:
            dead.append(name)
            continue
        try:
            out[name] = obj.status()
        except Exception as err:  # a statusz probe must never crash
            out[name] = {"error": repr(err)}
    if dead:
        with _STATUS_LOCK:
            for name in dead:
                if _STATUS_SOURCES.get(name) is not None and \
                        _STATUS_SOURCES[name]() is None:
                    del _STATUS_SOURCES[name]
    slo = telemetry.slo_engine()
    if slo is not None:
        out["slo"] = slo.verdicts()
    return out


def health_snapshot() -> tuple[int, dict[str, Any]]:
    """``/healthz``: 200 while every status source is failure-free, 503
    once any reports a ``failure`` (a quorum-lost abort, a wedged async
    world). A server mid-recovery — resumed from a checkpoint, barrier
    still assembling — is HEALTHY: recovery is the designed path, not a
    failure (docs/FAULT_TOLERANCE.md)."""
    status = status_snapshot()
    failures = {
        name: src["failure"]
        for name, src in status.items()
        if isinstance(src, dict) and src.get("failure")
    }
    if failures:
        return 503, {"status": "degraded", "failures": failures}
    return 200, {"status": "ok", "rank": status.get("rank", 0)}


def reset_status_sources() -> None:
    with _STATUS_LOCK:
        _STATUS_SOURCES.clear()
        _RUN_STATE.clear()


# ---------------------------------------------------------------------------
# the HTTP listener
# ---------------------------------------------------------------------------


class _Handler(http.server.BaseHTTPRequestHandler):
    # the exporter must never log scrapes to stderr
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = render_openmetrics(
                    telemetry.METRICS.snapshot()
                ).encode()
                self._send(
                    200, body,
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/statusz":
                body = json.dumps(
                    status_snapshot(), indent=2, default=repr
                ).encode()
                self._send(200, body, "application/json")
            elif path == "/healthz":
                code, doc = health_snapshot()
                self._send(
                    code, json.dumps(doc, default=repr).encode(),
                    "application/json",
                )
            elif path == "/tracez":
                # the round-anatomy ring (core/anatomy.py,
                # docs/OBSERVABILITY.md "Round anatomy") — lazily, so
                # a listener without the anatomy plane never imports
                # it; 404 while the plane is off (the
                # zero-cost-when-off rule: no section, not an empty
                # one)
                import sys as _sys

                _an = _sys.modules.get("fedml_tpu.core.anatomy")
                if _an is None or not _an.ANATOMY.enabled:
                    self._send(404, b"anatomy plane off\n",
                               "text/plain")
                else:
                    body = json.dumps(
                        _an.ANATOMY.tracez(
                            rank=telemetry.RECORDER.rank
                        ),
                        indent=2, default=repr,
                    ).encode()
                    self._send(200, body, "application/json")
            else:
                self._send(404, b"not found\n", "text/plain")
        except Exception as err:  # scrape must not kill the server
            try:
                self._send(500, repr(err).encode(), "text/plain")
            except Exception:
                pass


class MetricsExporter:
    """One daemon-thread HTTP listener per rank. ``port=0`` binds an
    ephemeral port (read it back from :attr:`port`). The listener only
    READS the registry at scrape time — it adds zero work to any
    metric write path.

    The endpoints are UNauthenticated (exporter convention) and
    ``/statusz`` exposes run introspection — membership, quarantine
    bans, failure diagnostics. The default bind serves any network
    peer so a remote Prometheus can scrape; on a shared or untrusted
    network restrict it with ``--metrics_host 127.0.0.1`` (or front it
    with your scrape proxy)."""

    def __init__(self, port: int, host: str = "0.0.0.0"):
        self._server = http.server.ThreadingHTTPServer(
            (host, port), _Handler
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"metrics-exporter:{self.port}",
        )
        self._thread.start()

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# fleet federation (heartbeat piggyback)
# ---------------------------------------------------------------------------

FLEET_VERSION = 1
#: client metrics worth federating (docs/OBSERVABILITY.md "Live export
#: and SLOs"): round wall + local-step time (histograms — bucket deltas
#: forward, so the server's fleet percentiles are computed over the
#: cohort's REAL distribution), WORK-payload wire bytes (counters —
#: deltas; deliberately the per-type result/sync counters, NOT the
#: transport totals: heartbeat frames count toward the totals, so
#: whitelisting those would make every beat's own bytes the "change"
#: that puts a summary on the next beat — a self-perpetuating payload
#: on an otherwise idle client), and compress ratio / residual /
#: staleness lag (gauges — each changed value is one fleet
#: observation).
FLEET_HISTS = (
    "perf.round_wall_s",
    "perf.local_step_s",
    # the anatomy plane's client-side phase attribution + a leaf
    # aggregator's subtree straggler wait (docs/OBSERVABILITY.md
    # "Round anatomy") — histograms like the round wall, so the root's
    # fleet percentiles cover the cohort's real distribution
    "perf.phase.local_s",
    "perf.straggler_wait_s",
)
FLEET_COUNTERS = (
    "transport.bytes_by_type.c2s_result",
    "transport.bytes_by_type.s2c_sync_model",
)
FLEET_GAUGES = (
    "compress.ratio",
    "compress.residual_norm",
    "async.staleness",
)
#: histogram families a summary may carry: the direct whitelist plus
#: the gauges' fleet twins — a LEAF's fold of its clients' gauge
#: observations lives as a ``fleet.<gauge>`` histogram, and it must
#: forward upstream or the root's fleet view silently loses every
#: gauge-family observation below the leaf tier
FLEET_HIST_FAMILIES = FLEET_HISTS + FLEET_GAUGES
#: receive-edge bound: a summary carrying more entries than every
#: whitelist combined is malformed by construction (size-bounding the
#: heartbeat payload is what keeps the piggyback safe at 10k clients)
MAX_FLEET_ENTRIES = 32
_FLEET_PREFIX = "fleet."


def fleet_snapshot(registry) -> dict[str, Any]:
    """Constant-size registry read of exactly the whitelisted families
    (bare + fleet.-prefixed) — what the heartbeat path feeds
    :func:`fleet_summary`, so a beat never pays an O(registry)
    deep-copy or any percentile interpolation."""
    both = lambda names: tuple(names) + tuple(
        _FLEET_PREFIX + n for n in names
    )
    return registry.read_selected(
        counters=both(FLEET_COUNTERS),
        gauges=FLEET_GAUGES,
        hists=both(FLEET_HIST_FAMILIES),
    )


def fleet_summary(
    snapshot: dict[str, Any], prev: dict[str, Any]
) -> dict[str, Any] | None:
    """Build one compact delta-encoded summary from a registry
    snapshot. ``prev`` is this sender's mutable carry (last values
    already shipped) — entries are emitted only when they CHANGED, so
    an idle client's heartbeat stays exactly as small as before this
    feature existed. Returns None when nothing changed.

    A leaf aggregator's own ``fleet.*`` aggregates federate upstream
    with the prefix stripped, so the root folds them into the same
    families its direct clients fill.

    Degenerate-topology note: in a SINGLE-process loopback world the
    "client" and "server" share one registry, so a fold lands in the
    very snapshot the next beat summarizes — each original observation
    re-forwards once per beat and the fleet counts grow with run
    length. Real deployments (and tier worlds) never share a registry
    across the heartbeat edge; loopback worlds are test rigs where the
    fleet view is not read for truth."""
    counters = snapshot.get("counters", {})
    gauges = snapshot.get("gauges", {})
    hists = snapshot.get("histograms", {})
    c_out: dict[str, float] = {}
    g_out: dict[str, float] = {}
    h_out: dict[str, dict] = {}
    for name in FLEET_COUNTERS:
        for key in (name, _FLEET_PREFIX + name):
            cur = counters.get(key)
            if cur is None:
                continue
            sent = prev.get(("c", key), 0.0)
            if cur != sent:
                # ACCUMULATE at the stripped key: a leaf aggregator
                # carries BOTH its own counter and the fleet.-prefixed
                # fold of its clients' — the upstream delta is their
                # sum, not whichever the loop visited last
                c_out[_strip(key)] = (
                    c_out.get(_strip(key), 0.0) + cur - sent
                )
                prev[("c", key)] = cur
    for name in FLEET_GAUGES:
        cur = gauges.get(name)
        if cur is not None and cur == cur and prev.get(("g", name)) != cur:
            g_out[name] = cur
            prev[("g", name)] = cur
    for name in FLEET_HIST_FAMILIES:
        # for the gauge families only the fleet.-prefixed twin can be
        # a histogram (a leaf's fold of its clients' observations);
        # the bare name misses hists and is handled by the gauge loop
        for key in (name, _FLEET_PREFIX + name):
            h = hists.get(key)
            if h is None:
                continue
            base = prev.get(("h", key))
            if base is not None and base.get("count") == h.get("count"):
                continue
            buckets = dict(h.get("buckets", {}))
            if base is not None:
                for bk, bv in base.get("buckets", {}).items():
                    buckets[bk] = buckets.get(bk, 0) - bv
                buckets = {k: v for k, v in buckets.items() if v > 0}
            entry = {
                "n": h.get("count", 0) - (
                    base.get("count", 0) if base else 0
                ),
                "s": h.get("sum", 0.0) - (
                    base.get("sum", 0.0) if base else 0.0
                ),
                "mn": h.get("min"),
                "mx": h.get("max"),
                "b": buckets,
            }
            seen = h_out.get(_strip(key))
            if seen is not None:
                # same accumulation rule as the counters: a leaf's own
                # histogram and its folded fleet.* twin MERGE at the
                # stripped key instead of overwriting each other
                seen["n"] += entry["n"]
                seen["s"] += entry["s"]
                seen["mn"] = min(seen["mn"], entry["mn"])
                seen["mx"] = max(seen["mx"], entry["mx"])
                for bk, bv in entry["b"].items():
                    seen["b"][bk] = seen["b"].get(bk, 0) + bv
            else:
                h_out[_strip(key)] = entry
            prev[("h", key)] = {
                "count": h.get("count", 0),
                "sum": h.get("sum", 0.0),
                "buckets": dict(h.get("buckets", {})),
            }
    if not (c_out or g_out or h_out):
        return None
    out: dict[str, Any] = {"v": FLEET_VERSION}
    if c_out:
        out["c"] = c_out
    if g_out:
        out["g"] = g_out
    if h_out:
        out["h"] = h_out
    return out


def _strip(name: str) -> str:
    return name[len(_FLEET_PREFIX):] if name.startswith(
        _FLEET_PREFIX) else name


def _finite(v) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)


def fold_fleet(payload: Any, registry=None) -> bool:
    """Receive-edge fold of one heartbeat summary into the ``fleet.*``
    aggregates. Version-tolerant (unknown version: ignored — a newer
    client against an older server degrades to plain heartbeats) and
    chaos-protected: any malformed shape is counted
    ``fleet.rejected`` and dropped — a poisoned heartbeat must never
    corrupt the fleet view. Returns True when the summary was folded."""
    m = registry if registry is not None else telemetry.METRICS
    if not m.enabled:
        return False
    if not isinstance(payload, dict):
        m.inc("fleet.rejected")
        return False
    if payload.get("v") != FLEET_VERSION:
        m.inc("fleet.version_skipped")
        return False
    c = payload.get("c", {})
    g = payload.get("g", {})
    h = payload.get("h", {})
    if not (isinstance(c, dict) and isinstance(g, dict)
            and isinstance(h, dict)):
        m.inc("fleet.rejected")
        return False
    if len(c) + len(g) + len(h) > MAX_FLEET_ENTRIES:
        m.inc("fleet.rejected")
        return False
    try:
        for name, delta in c.items():
            if name not in FLEET_COUNTERS or not _finite(delta) \
                    or delta < 0:
                raise ValueError(name)
        for name, value in g.items():
            if name not in FLEET_GAUGES or not _finite(value):
                raise ValueError(name)
        folds: list[tuple[str, dict]] = []
        for name, hd in h.items():
            if name not in FLEET_HIST_FAMILIES \
                    or not isinstance(hd, dict):
                raise ValueError(name)
            n = hd.get("n")
            s = hd.get("s")
            b = hd.get("b", {})
            if not (_finite(n) and n >= 0 and _finite(s)
                    and isinstance(b, dict)):
                raise ValueError(name)
            buckets = {}
            for bk, bv in b.items():
                k = int(str(bk).split("^", 1)[1])
                if not (-20 <= k <= 20) or not _finite(bv) or bv < 0:
                    raise ValueError(name)
                buckets[f"le_2^{k}"] = int(bv)
            if sum(buckets.values()) != int(n):
                # every registry observation lands in exactly one
                # bucket, so an honest summary's bucket deltas sum to
                # its count delta — a mismatch (e.g. n=0 with occupied
                # buckets) would fold a NON-MONOTONE histogram into
                # the /metrics exposition
                raise ValueError(name)
            mn, mx = hd.get("mn"), hd.get("mx")
            if int(n) > 0 and not (_finite(mn) and _finite(mx)):
                raise ValueError(name)
            folds.append((name, {
                "count": int(n), "sum": float(s),
                "min": mn, "max": mx, "buckets": buckets,
            }))
    except (ValueError, TypeError, AttributeError, IndexError):
        m.inc("fleet.rejected")
        return False
    for name, delta in c.items():
        m.inc(_FLEET_PREFIX + name, float(delta))
    for name, value in g.items():
        m.observe(_FLEET_PREFIX + name, float(value))
    for name, hd in folds:
        m.merge_histogram(_FLEET_PREFIX + name, hd)
    m.inc("fleet.heartbeat_summaries")
    return True

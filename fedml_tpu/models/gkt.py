"""Split models for Group Knowledge Transfer (FedGKT) and SplitNN.

Reference: ``fedml_api/model/cv/resnet56_gkt/`` — the ResNet-56 is cut
after the first residual stage: the client (edge) model is conv1 + stage-1
blocks and a small classifier head over the 16-channel feature maps
(``resnet_client.py:112``), the server model is stages 2-3 + the final head,
consuming the client's feature maps (``resnet_server.py:113``).

TPU notes: NHWC, BasicBlocks identical to the main zoo's ResNet; the split
boundary tensor is ``[B, 32, 32, 16]`` for CIFAR shapes — contiguous and
cheap to ship across a mesh/DCN boundary.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.vision import BasicBlock


class GKTClientResNet(nn.Module):
    """Edge-side model: stem + one stage of BasicBlocks; returns
    ``(features, logits)`` (reference ``resnet_client.py`` forward returns
    ``(extracted_features, logits)``)."""

    num_classes: int = 10
    num_blocks: int = 3  # reference resnet8_56: 3 blocks client-side
    width: int = 16
    norm: str = "bn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False)(x)
        h = nn.BatchNorm(use_running_average=not train)(h)
        h = nn.relu(h)
        for _ in range(self.num_blocks):
            h = BasicBlock(self.width, stride=1, norm=self.norm)(
                h, train=train
            )
        features = h  # [B, H, W, width]
        pooled = jnp.mean(h, axis=(1, 2))
        logits = nn.Dense(self.num_classes, name="head")(pooled)
        return features, logits


class GKTServerResNet(nn.Module):
    """Server-side model over client feature maps: stages 2-3 of the
    CIFAR ResNet + head (reference ``resnet_server.py:113``,
    ``resnet56_server`` = remaining 2x9 blocks at widths 32/64)."""

    num_classes: int = 10
    blocks_per_stage: Sequence[int] = (9, 9)
    widths: Sequence[int] = (32, 64)
    norm: str = "bn"

    @nn.compact
    def __call__(self, features, train: bool = False):
        h = features
        for stage, (n, w) in enumerate(
            zip(self.blocks_per_stage, self.widths)
        ):
            for b in range(n):
                h = BasicBlock(w, stride=2 if b == 0 else 1, norm=self.norm)(
                    h, train=train
                )
        h = jnp.mean(h, axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(h)


class SplitClientNet(nn.Module):
    """SplitNN lower stack (reference ``split_nn/client.py``: clients own
    the first layers up to the cut)."""

    features: Sequence[int] = (32, 64)

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x
        for f in self.features:
            h = nn.Conv(f, (3, 3), strides=(2, 2), padding="SAME")(h)
            h = nn.relu(h)
        return h


class SplitServerNet(nn.Module):
    """SplitNN upper stack (reference ``split_nn/server.py:40``: server owns
    the layers after the cut + loss)."""

    num_classes: int = 10
    hidden: int = 128

    @nn.compact
    def __call__(self, acts, train: bool = False):
        h = acts.reshape((acts.shape[0], -1))
        h = nn.relu(nn.Dense(self.hidden)(h))
        return nn.Dense(self.num_classes)(h)


class VFLLocalModel(nn.Module):
    """Per-party feature extractor for vertical FL (reference
    ``fedml_api/model/finance/vfl_models_standalone.py:36`` ``LocalModel``:
    a small MLP over the party's feature slice)."""

    out_dim: int = 32
    hidden: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.out_dim)(h)


class VFLDenseModel(nn.Module):
    """Party logit head (reference ``vfl_models_standalone.py:6``
    ``DenseModel``: one linear layer producing the party's logit
    contribution; the guest sums contributions)."""

    out_dim: int = 1
    use_bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(self.out_dim, use_bias=self.use_bias)(x)

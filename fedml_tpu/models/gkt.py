"""Split models for Group Knowledge Transfer (FedGKT) and SplitNN.

Reference: ``fedml_api/model/cv/resnet56_gkt/`` — the ResNet-56 is cut at
the STEM: the client (edge) model ``resnet8_56`` is conv1+bn+relu (whose
output IS the exchanged feature map, ``resnet_client.py:190-203``:
``extracted_features = x`` right after the stem) followed by 2 Bottleneck
blocks at planes 16 and an fc over 16*4 channels; the server model
``resnet56_server`` is the Bottleneck [6,6,6] trunk minus the stem
(``resnet_server.py:186-198``), consuming the client's 16-channel feature
maps and classifying from 64*4 channels.

TPU notes: NHWC; the split boundary tensor is ``[B, 32, 32, 16]`` for
CIFAR shapes — contiguous and cheap to ship across a mesh/DCN boundary.
Submodules carry explicit torch-style names (conv1/bn1/layer{i}_{b}/fc) so
the reference's pretrained checkpoints (``resnet56/best.pth``) can be
mapped in (:func:`load_torch_gkt_state`).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from fedml_tpu.ops.cohort_conv import Conv2D



class Bottleneck(nn.Module):
    """CIFAR Bottleneck (reference ``resnet_client.py:69-110``):
    1x1 reduce -> 3x3 (stride) -> 1x1 expand (x4), BN after each, projection
    shortcut when shape changes."""

    planes: int
    stride: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = self.planes * self.expansion
        bn = lambda name: nn.BatchNorm(
            use_running_average=not train, name=name
        )
        h = Conv2D(self.planes, (1, 1), use_bias=False, name="conv1")(x)
        h = nn.relu(bn("bn1")(h))
        h = Conv2D(
            self.planes, (3, 3), strides=(self.stride, self.stride),
            padding="SAME", use_bias=False, name="conv2",
        )(h)
        h = nn.relu(bn("bn2")(h))
        h = Conv2D(out_ch, (1, 1), use_bias=False, name="conv3")(h)
        h = bn("bn3")(h)
        identity = x
        if self.stride != 1 or x.shape[-1] != out_ch:
            identity = Conv2D(
                out_ch, (1, 1), strides=(self.stride, self.stride),
                use_bias=False, name="downsample_conv",
            )(x)
            identity = bn("downsample_bn")(identity)
        return nn.relu(h + identity)


class GKTClientResNet(nn.Module):
    """Edge-side ``resnet8_56`` (reference ``resnet_client.py:230-238``:
    ResNet(Bottleneck, [2, 2, 2]) with only layer1 active): stem ->
    *features* (the exchanged tensor, post-stem), then 2 Bottlenecks at
    planes 16 -> avgpool -> fc. Returns ``(features, logits)``."""

    num_classes: int = 10
    num_blocks: int = 2
    width: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = Conv2D(
            self.width, (3, 3), padding="SAME", use_bias=False, name="conv1"
        )(x)
        h = nn.BatchNorm(use_running_average=not train, name="bn1")(h)
        h = nn.relu(h)
        features = h  # [B, H, W, 16] — the split-boundary tensor
        for b in range(self.num_blocks):
            h = Bottleneck(self.width, stride=1, name=f"layer1_{b}")(
                h, train=train
            )
        pooled = jnp.mean(h, axis=(1, 2))
        logits = nn.Dense(self.num_classes, name="fc")(pooled)
        return features, logits


class GKTServerResNet(nn.Module):
    """Server-side ``resnet56_server`` (reference
    ``resnet_server.py:200-208``: ResNet(Bottleneck, [6, 6, 6]) minus the
    stem): three Bottleneck stages at planes (16, 32, 64), strides
    (1, 2, 2), over the client's post-stem feature maps; fc over 64*4."""

    num_classes: int = 10
    blocks_per_stage: Sequence[int] = (6, 6, 6)
    widths: Sequence[int] = (16, 32, 64)

    @nn.compact
    def __call__(self, features, train: bool = False):
        h = features
        for stage, (n, w) in enumerate(
            zip(self.blocks_per_stage, self.widths)
        ):
            for b in range(n):
                stride = 2 if (stage > 0 and b == 0) else 1
                h = Bottleneck(
                    w, stride=stride, name=f"layer{stage + 1}_{b}"
                )(h, train=train)
        h = jnp.mean(h, axis=(1, 2))
        return nn.Dense(self.num_classes, name="fc")(h)


def load_torch_gkt_state(path: str, variables, side: str = "server"):
    """Warm-start from the reference's pretrained torch checkpoint
    (``fedml_api/model/cv/pretrained/CIFAR10/resnet56/best.pth``, consumed
    by ``resnet56_server``/``resnet8_56`` via ``pretrained=True``).

    Maps the torch ``state_dict`` (``conv1.weight``, ``bn1.*``,
    ``layer{i}.{b}.conv{j}.weight`` / ``bn{j}.*`` / ``downsample.{0,1}.*``,
    ``fc.*``) onto this module's explicitly-named flax tree. Missing keys
    keep their current (fresh) values; returns the updated variables."""
    import torch

    ckpt = torch.load(path, map_location="cpu", weights_only=False)
    sd = ckpt.get("state_dict", ckpt)
    sd = {k.replace("module.", ""): v.numpy() for k, v in sd.items()}

    params = jax.tree_util.tree_map(lambda v: v, variables["params"])
    stats = jax.tree_util.tree_map(
        lambda v: v, variables.get("batch_stats", {})
    )

    def put_conv(dst, torch_key):
        if torch_key in sd:
            w = sd[torch_key]  # [O, I, kh, kw] -> [kh, kw, I, O]
            dst["kernel"] = np.transpose(w, (2, 3, 1, 0)).astype(np.float32)

    def put_bn(pdst, sdst, prefix):
        if f"{prefix}.weight" in sd:
            pdst["scale"] = sd[f"{prefix}.weight"].astype(np.float32)
            pdst["bias"] = sd[f"{prefix}.bias"].astype(np.float32)
            sdst["mean"] = sd[f"{prefix}.running_mean"].astype(np.float32)
            sdst["var"] = sd[f"{prefix}.running_var"].astype(np.float32)

    def put_dense(dst, prefix):
        if f"{prefix}.weight" in sd:
            dst["kernel"] = sd[f"{prefix}.weight"].T.astype(np.float32)
            dst["bias"] = sd[f"{prefix}.bias"].astype(np.float32)

    if side == "client" and "conv1" in params:
        put_conv(params["conv1"], "conv1.weight")
        put_bn(params["bn1"], stats["bn1"], "bn1")
    for name in list(params.keys()):
        if not name.startswith("layer"):
            continue
        stage_blk = name[len("layer"):]  # "{i}_{b}"
        i, b = stage_blk.split("_")
        tprefix = f"layer{i}.{b}"
        blk_p, blk_s = params[name], stats.get(name, {})
        for j in (1, 2, 3):
            put_conv(blk_p[f"conv{j}"], f"{tprefix}.conv{j}.weight")
            put_bn(blk_p[f"bn{j}"], blk_s[f"bn{j}"], f"{tprefix}.bn{j}")
        if "downsample_conv" in blk_p:
            put_conv(blk_p["downsample_conv"], f"{tprefix}.downsample.0.weight")
            put_bn(
                blk_p["downsample_bn"], blk_s["downsample_bn"],
                f"{tprefix}.downsample.1",
            )
    put_dense(params["fc"], "fc")
    out = dict(variables)
    out["params"] = params
    if stats:
        out["batch_stats"] = stats
    return out



class SplitClientNet(nn.Module):
    """SplitNN lower stack (reference ``split_nn/client.py``: clients own
    the first layers up to the cut)."""

    features: Sequence[int] = (32, 64)

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x
        for f in self.features:
            h = Conv2D(f, (3, 3), strides=(2, 2), padding="SAME")(h)
            h = nn.relu(h)
        return h


class SplitServerNet(nn.Module):
    """SplitNN upper stack (reference ``split_nn/server.py:40``: server owns
    the layers after the cut + loss)."""

    num_classes: int = 10
    hidden: int = 128

    @nn.compact
    def __call__(self, acts, train: bool = False):
        h = acts.reshape((acts.shape[0], -1))
        h = nn.relu(nn.Dense(self.hidden)(h))
        return nn.Dense(self.num_classes)(h)


class VFLLocalModel(nn.Module):
    """Per-party feature extractor for vertical FL (reference
    ``fedml_api/model/finance/vfl_models_standalone.py:36`` ``LocalModel``:
    a small MLP over the party's feature slice)."""

    out_dim: int = 32
    hidden: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.out_dim)(h)


class VFLDenseModel(nn.Module):
    """Party logit head (reference ``vfl_models_standalone.py:6``
    ``DenseModel``: one linear layer producing the party's logit
    contribution; the guest sums contributions)."""

    out_dim: int = 1
    use_bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(self.out_dim, use_bias=self.use_bias)(x)

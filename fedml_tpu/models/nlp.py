"""NLP models: the reference's federated LSTM pair (flax linen).

Reference ``fedml_api/model/nlp/rnn.py``:
- ``RNN_OriginalFedAvg`` (``:4``): shakespeare char LM — embed(8) -> 2x
  LSTM(256) -> dense(vocab), per-position logits.
- ``RNN_StackOverFlow`` (``:39``): next-word prediction — embed(96) ->
  LSTM(670) -> dense(96) -> dense(vocab).

LSTMs run as ``nn.RNN`` over ``OptimizedLSTMCell`` — an ``lax.scan`` under
the hood, so the whole sequence unrolls inside one XLA computation (static
shapes, MXU-friendly batched gates).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CharLSTM(nn.Module):
    """Shakespeare char-LM (reference ``RNN_OriginalFedAvg``,
    ``model/nlp/rnn.py:4``)."""

    vocab_size: int = 90
    embed_dim: int = 8
    hidden: int = 256

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(x)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(x)
        return nn.Dense(self.vocab_size)(x)  # [B, T, vocab]


class NWPLSTM(nn.Module):
    """StackOverflow next-word predictor (reference ``RNN_StackOverFlow``,
    ``model/nlp/rnn.py:39``)."""

    vocab_size: int = 10004  # 10k words + pad/bos/eos/oov
    embed_dim: int = 96
    hidden: int = 670

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(x)
        x = nn.Dense(self.embed_dim)(x)
        return nn.Dense(self.vocab_size)(x)


class TagLogisticRegression(nn.Module):
    """Multi-label bag-of-words tagger (stackoverflow_lr; reference
    multilabel trainer path ``fedml_core/trainer/model_trainer.py:57-112``)."""

    num_tags: int = 500

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_tags)(x)  # sigmoid applied in the loss

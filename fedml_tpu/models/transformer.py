"""Transformer LM with pluggable attention (full / flash / ring).

Capability the TPU build adds beyond the reference (whose NLP zoo is
2-layer LSTMs, ``fedml_api/model/nlp/rnn.py:4-70``): a causal transformer
whose attention implementation is injected, so the SAME module runs

- single-chip with the pallas flash kernel
  (:func:`fedml_tpu.ops.flash_attention.flash_attention`),
- sequence-parallel with ring attention under ``shard_map``
  (:func:`fedml_tpu.ops.ring_attention.ring_attention`) — embeddings, MLP,
  and layernorm are position-wise, so sharding the T axis only touches the
  attention collective; position ids are passed in so shards embed their
  GLOBAL positions.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.ops.ring_attention import full_attention

AttnFn = Callable[..., jax.Array]  # (q, k, v, causal=...) -> out

#: dense factory: (features, use_bias, name) -> nn.Module. None = stock
#: nn.Dense. The PEFT subsystem (fedml_tpu.peft.lora.dense_factory)
#: substitutes LoRA-wrapped projections for targeted names without
#: touching this module's structure or the attn_fn contract.
DenseFactory = Any


def _dense(factory: DenseFactory, features: int, use_bias: bool,
           name: str) -> nn.Module:
    if factory is None:
        return nn.Dense(features, use_bias=use_bias, name=name)
    return factory(features, use_bias, name)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    attn_fn: AttnFn = full_attention
    dense_cls: DenseFactory = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, t, c = x.shape
        h = nn.LayerNorm()(x)
        # separate q/k/v projections (explicitly named): under tensor
        # parallelism each is column-sharded on its own output dim, so
        # shards align with head boundaries (a fused 3c projection sharded
        # contiguously would cut across q/k/v and force extra resharding)
        q = _dense(self.dense_cls, c, False, "q_proj")(h)
        k = _dense(self.dense_cls, c, False, "k_proj")(h)
        v = _dense(self.dense_cls, c, False, "v_proj")(h)
        hd = c // self.num_heads

        def heads(z):
            return z.reshape(b, t, self.num_heads, hd)

        a = self.attn_fn(heads(q), heads(k), heads(v), causal=True)
        a = a.reshape(b, t, c)
        x = x + _dense(self.dense_cls, c, False, "attn_out")(a)
        h = nn.LayerNorm()(x)
        h = _dense(self.dense_cls, self.mlp_ratio * c, True, "mlp_up")(h)
        h = nn.gelu(h)
        x = x + _dense(self.dense_cls, c, True, "mlp_down")(h)
        return x


class TransformerLM(nn.Module):
    vocab_size: int
    num_layers: int = 2
    num_heads: int = 4
    embed_dim: int = 128
    max_len: int = 2048
    attn_fn: AttnFn = full_attention
    dense_cls: DenseFactory = None

    @nn.compact
    def __call__(self, tokens, train: bool = False, positions=None):
        """``tokens`` [B, T] int32; ``positions`` [B, T] global positions
        (defaults to 0..T-1 — pass explicitly under sequence parallelism,
        where a shard holds tokens t0..t0+T_local)."""
        b, t = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
        x = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        x = x + nn.Embed(self.max_len, self.embed_dim, name="pos_emb")(
            positions
        )
        for _ in range(self.num_layers):
            x = Block(
                self.num_heads, attn_fn=self.attn_fn,
                dense_cls=self.dense_cls,
            )(x, train=train)
        x = nn.LayerNorm()(x)
        # named so the PEFT partition (fedml_tpu.peft.partition) can
        # select the head subtree as densely-trainable by path
        return nn.Dense(self.vocab_size, use_bias=False, name="lm_head")(x)


def make_sequence_parallel_lm_step(
    model: TransformerLM, mesh, axis_name: str = "sp"
):
    """Compile a sequence-parallel causal-LM loss/grad step.

    The whole forward+backward runs inside one ``shard_map`` over the
    sequence axis: each device holds [B, T/p] tokens; the only cross-shard
    communication is ring attention's K/V rotation (plus the psum of the
    scalar loss and of parameter grads, which are replicated).
    """
    import functools

    from jax.sharding import PartitionSpec as P

    from fedml_tpu.core.compat import shard_map

    from fedml_tpu.ops.ring_attention import ring_attention

    sp_model = model.clone(
        attn_fn=functools.partial(ring_attention, axis_name=axis_name)
    )
    p = mesh.shape[axis_name]

    def local_step(params, tokens, targets):
        # tokens/targets: LOCAL [B, T/p] shards
        idx = jax.lax.axis_index(axis_name)
        b, t_local = tokens.shape
        positions = jnp.broadcast_to(
            idx * t_local + jnp.arange(t_local)[None], (b, t_local)
        )

        def loss_fn(params):
            logits = sp_model.apply(params, tokens, positions=positions)
            import optax

            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            )
            return jax.lax.pmean(jnp.mean(ce), axis_name)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.lax.pmean(grads, axis_name)
        return loss, grads

    tok_spec = P(None, axis_name)
    return shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), tok_spec, tok_spec),
        out_specs=(P(), P()),
    )


def tp_param_specs(params, tp_axis: str = "tp"):
    """Megatron-style tensor-parallel PartitionSpecs for TransformerLM
    params: per block, the qkv projection and MLP up-projection are
    COLUMN-parallel (output dim sharded over ``tp_axis``) and the attention
    output / MLP down-projection are ROW-parallel (input dim sharded), so
    each block needs exactly one all-reduce per sublayer — GSPMD inserts
    it from these annotations. Embeddings, layernorms, and the LM head are
    replicated."""
    from jax.sharding import PartitionSpec as P

    COLUMN = ("q_proj", "k_proj", "v_proj", "mlp_up")
    ROW = ("attn_out", "mlp_down")

    def spec_for(path, leaf):
        keys = [getattr(k, "key", str(k)) for k in path]
        module = keys[-2] if len(keys) >= 2 else ""
        if keys[-1] == "kernel":
            if module in COLUMN:
                return P(None, tp_axis)
            if module in ROW:
                return P(tp_axis, None)
        if keys[-1] == "bias" and module in COLUMN:
            return P(tp_axis)  # bias follows its column shard
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def make_tp_dp_lm_step(
    model: TransformerLM,
    mesh,
    tp_axis: str = "tp",
    dp_axis: str = "data",
    lr: float = 0.1,
):
    """Compile a tensor-parallel x data-parallel causal-LM SGD step via
    GSPMD sharding annotations (jit + NamedSharding — XLA inserts the
    per-sublayer all-reduces and the data-parallel gradient reduction).
    Heads must divide the tp axis size. Returns
    ``step(params, tokens, targets) -> (params, loss)`` with params
    sharded per :func:`tp_param_specs` and the batch over ``dp_axis``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert model.num_heads % mesh.shape[tp_axis] == 0, (
        model.num_heads, mesh.shape[tp_axis]
    )

    def loss_fn(params, tokens, targets):
        import optax

        logits = model.apply(params, tokens)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        )

    def step(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    def param_shardings(params):
        specs = tp_param_specs(params, tp_axis)
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda s: isinstance(s, P),
        )

    def shard_params(params):
        return jax.device_put(params, param_shardings(params))

    def compile_step(params):
        pshard = param_shardings(params)
        dshard = NamedSharding(mesh, P(dp_axis, None))
        return jax.jit(
            step,
            in_shardings=(pshard, dshard, dshard),
            out_shardings=(pshard, NamedSharding(mesh, P())),
        )

    return compile_step, shard_params

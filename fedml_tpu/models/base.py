"""Model wrapper: a uniform functional interface over flax modules.

The reference's single pluggable training abstraction is the ``ModelTrainer``
ABC (``fedml_core/trainer/model_trainer.py:7-41``) holding a mutable
``nn.Module``. The TPU-native equivalent is a *pure-function triple*: the
model is a flax module, the state is a variables pytree (``params`` +
optional ``batch_stats``), and train/eval applications are pure so they can
be vmapped across clients and jitted.

FedAvg aggregates the reference's full ``state_dict`` — including BatchNorm
running stats (``FedAVGAggregator.py:73-81``); we mirror that by treating the
whole variables pytree as the unit of aggregation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

Variables = Any


@dataclasses.dataclass(frozen=True)
class FedModel:
    """Functional handle on one architecture."""

    module: nn.Module
    input_shape: tuple[int, ...]
    has_batch_stats: bool = False
    has_dropout: bool = False
    # inputs may be int tokens (NLP) rather than floats
    input_dtype: Any = jnp.float32

    def init(self, rng: jax.Array) -> Variables:
        dummy = jnp.zeros((1,) + tuple(self.input_shape), self.input_dtype)
        return self.module.init({"params": rng}, dummy, train=False)

    def apply_train(
        self, variables: Variables, x: jax.Array, rng: jax.Array
    ) -> tuple[jax.Array, Variables]:
        """Forward in train mode; returns (logits, updated variables)."""
        rngs = {"dropout": rng} if self.has_dropout else None
        if self.has_batch_stats:
            logits, mutated = self.module.apply(
                variables, x, train=True, rngs=rngs, mutable=["batch_stats"]
            )
            return logits, {**variables, **mutated}
        logits = self.module.apply(variables, x, train=True, rngs=rngs)
        return logits, variables

    def apply_eval(self, variables: Variables, x: jax.Array) -> jax.Array:
        return self.module.apply(variables, x, train=False)

    # -- cohort-grouped fast path (see fedml_tpu.models.cohort) ------------

    def supports_cohort(self) -> bool:
        """Whether this architecture can run the whole sampled cohort as
        one cohort-grouped network (conv zoo modules expose a ``cohort``
        width-multiplier field). Dropout is excluded: the grouped form
        draws one mask over the widened activations, which changes the
        per-client noise stream vs the vmapped form."""
        return (
            getattr(self.module, "cohort", None) == 1
            and not self.has_dropout
        )

    def apply_cohort_train(
        self, stacked_vars: Variables, x: jax.Array, rng: jax.Array
    ) -> tuple[jax.Array, Variables]:
        """Train-mode forward of C clients at once in cohort-grouped form.

        ``stacked_vars`` has leading client axis C on every leaf; ``x`` is
        ``[C, B, H, W, cin]``. Returns (logits ``[C, B, K]``, updated
        stacked variables). Numerically identical to
        ``vmap(apply_train)`` — the grouped network IS the per-client
        network, re-laid-out (channel groups = clients)."""
        from fedml_tpu.models.cohort import fat_to_stack, stack_to_fat

        C = x.shape[0]
        if C == 1:
            # degenerate cohort (e.g. one client per mesh shard): the
            # widened network IS the base network; dense scopes store
            # stacked [1, f, o] kernels the base head can't consume, so
            # squeeze through the ordinary per-client apply instead
            squeezed = jax.tree.map(lambda v: v[0], stacked_vars)
            logits, new_vars = self.apply_train(squeezed, x[0], rng)
            return (
                logits[None],
                jax.tree.map(lambda v: v[None], new_vars),
            )
        module = self.module.clone(cohort=C)
        fat = stack_to_fat(stacked_vars, C)
        xg = jnp.moveaxis(x, 0, 3).reshape(x.shape[1:4] + (-1,))
        rngs = {"dropout": rng} if self.has_dropout else None
        if self.has_batch_stats:
            logits, mutated = module.apply(
                fat, xg, train=True, rngs=rngs, mutable=["batch_stats"]
            )
            return logits, {**stacked_vars, **fat_to_stack(mutated, C)}
        logits = module.apply(fat, xg, train=True, rngs=rngs)
        return logits, stacked_vars


LossFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]

"""EXACT space-to-depth execution layout for the standard CIFAR ResNet.

``resnet56_s2d`` (models/vision.py) is a different parameterization —
fast, but not weight-compatible with reference checkpoints. This module
is the missing parity bridge: the SAME function as the standard
``resnet56``, re-laid-out so stage 1 (the TPU-hostile 16-channel 32x32
stage) runs in space-to-depth space with 4x wider channels, computed
from a standard checkpoint by a pure weight transformation.

The embedding (classic TPU trick, e.g. the ResNet-50 s2d stem; derived
independently here for the CIFAR stage-1 case):

- input [B, 32, 32, c] -> s2d -> [B, 16, 16, 4c], channel order
  (phase-major): (u, v, ci) for phase (u, v) in {0,1}^2.
- a 3x3 stride-1 conv on the original grid equals a 3x3 conv on the s2d
  grid with kernel K'[di, dj, (u,v,ci), (a,b,co)]: output pixel
  (2i+a, 2j+b) reads original pixel (2i+a+s, 2j+b+t), which lives at s2d
  offset di = floor((a+s)/2) phase u = (a+s) mod 2 — each original tap
  (s, t) scatters to exactly one (di, u, dj, v) slot, so K' is 25% dense
  (the 4x FLOP inflation is the price of 4x wider, MXU-tileable
  channels).
- stage-1 BatchNorm needs PHASE-POOLED statistics: original per-channel
  moments pool over all spatial positions == over all 4 phases of the
  s2d layout (:class:`PhasePooledBatchNorm`); scale/bias/running stats
  replicate 4x on conversion, so eval-mode normalization is exactly the
  original affine.
- the stage-2 entry (3x3 stride-2 conv + 1x1 stride-2 shortcut) maps to
  a 2x2 (resp. 1x1) conv on the s2d grid that also RETURNS to the
  natural layout — stages 2-3 and the head then run the ORIGINAL
  weights unchanged.

``convert_resnet_checkpoint_to_s2d(variables, depth)`` maps a standard
``ResNetCIFAR`` variables tree to :class:`ResNetCIFARS2DExact`'s tree;
outputs match to f32 round-off in both eval and train mode
(tests/test_models.py::test_s2d_exact_*). Reference context: checkpoints
trained with ``fedml_api/model/cv/resnet.py`` port through
``models/gkt.py``'s torch mapping into ``resnet56`` and from there
through this converter into the TPU layout.

Measured on v5e: ~1.2x faster than the standard layout for SINGLE-model
training/eval (stage-1 channels 4x wider); in the cohort-grouped
federated round it is a wash (~49 vs 48 ms headline) — the grouped
convs dense-expand either way, so the 4x stage-1 FLOP inflation cancels
the width win. Use it for parity-preserving single-model work
(centralized training, evaluation, GKT-style warm starts); the bench's
default story remains ``resnet56_s2d``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.models.cohort import dense as _cohort_dense
from fedml_tpu.ops.cohort_conv import Conv2D


def s2d_rearrange(x: jax.Array, cohort: int = 1) -> jax.Array:
    """[B, H, W, C*c] -> [B, H/2, W/2, C*4c]: per-client channel blocks
    stay outermost (client-major), phases phase-major (u, v, ci) within
    each client — the layout the converted kernels expect."""
    b, h, w, cc = x.shape
    c = cc // cohort
    x = x.reshape(b, h // 2, 2, w // 2, 2, cohort, c)
    return x.transpose(0, 1, 3, 5, 2, 4, 6).reshape(
        b, h // 2, w // 2, cohort * 4 * c
    )


def convert_conv3x3_to_s2d(w: np.ndarray) -> np.ndarray:
    """[3, 3, ci, co] stride-1 SAME -> [3, 3, 4ci, 4co] on the s2d grid
    (exact; 25% dense)."""
    w = np.asarray(w)
    _, _, ci, co = w.shape
    out = np.zeros((3, 3, 4 * ci, 4 * co), w.dtype)
    for a in (0, 1):
        for b in (0, 1):
            for s in (-1, 0, 1):
                for t in (-1, 0, 1):
                    di, u = divmod(a + s, 2)
                    dj, v = divmod(b + t, 2)
                    out[
                        di + 1, dj + 1,
                        (2 * u + v) * ci:(2 * u + v + 1) * ci,
                        (2 * a + b) * co:(2 * a + b + 1) * co,
                    ] = w[s + 1, t + 1]
    return out


def convert_conv3x3_stride2_to_s2d(w: np.ndarray) -> np.ndarray:
    """[3, 3, ci, co] stride-2 SAME (32->16) -> [2, 2, 4ci, co] on the
    s2d grid, stride 1, output in the NATURAL (non-s2d) layout.

    XLA's SAME padding for kernel 3 stride 2 on even extent pads only at
    the high edge, so output pixel i reads original pixels 2i..2i+2:
    offset s in {0, 1, 2} -> s2d offset di = s // 2, phase u = s % 2."""
    w = np.asarray(w)
    _, _, ci, co = w.shape
    out = np.zeros((2, 2, 4 * ci, co), w.dtype)
    for s in (0, 1, 2):
        for t in (0, 1, 2):
            di, u = divmod(s, 2)
            dj, v = divmod(t, 2)
            out[di, dj, (2 * u + v) * ci:(2 * u + v + 1) * ci] += w[s, t]
    return out


def convert_conv1x1_stride2_to_s2d(w: np.ndarray) -> np.ndarray:
    """[1, 1, ci, co] stride-2 -> [1, 1, 4ci, co] stride-1 on the s2d
    grid (only phase (0, 0) contributes)."""
    w = np.asarray(w)
    _, _, ci, co = w.shape
    out = np.zeros((1, 1, 4 * ci, co), w.dtype)
    out[0, 0, :ci] = w[0, 0]
    return out


class PhasePooledBatchNorm(nn.Module):
    """BatchNorm whose batch statistics pool the ``phases`` s2d phase
    groups of each original channel — exactly the original per-channel
    moments. Parameters/stats are stored at the widened size (phase-
    replicated on conversion) so eval mode is a plain affine. With
    ``cohort`` > 1 channels are client-major blocks of ``phases * c``
    and stats pool phases WITHIN each client (per-client batch norm, as
    the cohort-grouped layout requires)."""

    phases: int = 4
    cohort: int = 1
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        cw = x.shape[-1]  # cohort * phases * c
        c = cw // (self.phases * self.cohort)
        scale = self.param("scale", nn.initializers.ones, (cw,))
        bias = self.param("bias", nn.initializers.zeros, (cw,))
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((cw,))
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((cw,))
        )
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xs = x.reshape(
                x.shape[:-1] + (self.cohort, self.phases, c)
            )
            red = tuple(range(xs.ndim - 3)) + (xs.ndim - 2,)
            mean_c = jnp.mean(xs.astype(jnp.float32), axis=red)
            var_c = jnp.mean(
                jnp.square(xs.astype(jnp.float32)), axis=red
            ) - jnp.square(mean_c)  # [cohort, c]
            rep = lambda m: jnp.broadcast_to(
                m[:, None, :], (self.cohort, self.phases, c)
            ).reshape(cw)
            mean, var = rep(mean_c), rep(var_c)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value
                    + (1.0 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value
                    + (1.0 - self.momentum) * var
                )
        y = (x - mean.astype(x.dtype)) * jax.lax.rsqrt(
            var.astype(x.dtype) + jnp.asarray(self.epsilon, x.dtype)
        )
        return y * scale.astype(x.dtype) + bias.astype(x.dtype)


def _bn(train: bool, phases: int | None, cohort: int = 1):
    if phases:
        return PhasePooledBatchNorm(
            phases=phases, cohort=cohort, use_running_average=not train
        )
    return nn.BatchNorm(use_running_average=not train, momentum=0.9)


class _S2DBasicBlock(nn.Module):
    """Stage-1 basic block in s2d space (channels constant, stride 1)."""

    widened: int  # 4 * original channels (per client)
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        co = self.cohort
        residual = x
        y = Conv2D(self.widened * co, (3, 3), padding="SAME",
                   use_bias=False, feature_group_count=co)(x)
        y = _bn(train, 4, co)(y)
        y = nn.relu(y)
        y = Conv2D(self.widened * co, (3, 3), padding="SAME",
                   use_bias=False, feature_group_count=co)(y)
        y = _bn(train, 4, co)(y)
        return nn.relu(y + residual)


class _TransitionBlock(nn.Module):
    """The stage-2 entry block: consumes s2d stage-1 output, produces
    the natural-layout stage-2 activation (conv kernels are the
    converted stride-2 forms; see module docstring)."""

    channels: int
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        co = self.cohort
        # converted 3x3-stride2 kernel: 2x2 VALID after a (0,1) pad on
        # the s2d grid (original SAME pads only the high edge)
        xp = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
        y = Conv2D(self.channels * co, (2, 2), padding="VALID",
                   use_bias=False, feature_group_count=co)(xp)
        y = _bn(train, None)(y)
        y = nn.relu(y)
        y = Conv2D(self.channels * co, (3, 3), padding="SAME",
                   use_bias=False, feature_group_count=co)(y)
        y = _bn(train, None)(y)
        residual = Conv2D(self.channels * co, (1, 1), padding="VALID",
                          use_bias=False, feature_group_count=co)(x)
        residual = _bn(train, None)(residual)
        return nn.relu(y + residual)


class _BasicBlock(nn.Module):
    """Standard basic block (stages 2-3 past the transition)."""

    channels: int
    stride: int = 1
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        co = self.cohort
        residual = x
        y = Conv2D(self.channels * co, (3, 3),
                   (self.stride, self.stride), padding="SAME",
                   use_bias=False, feature_group_count=co)(x)
        y = _bn(train, None)(y)
        y = nn.relu(y)
        y = Conv2D(self.channels * co, (3, 3), padding="SAME",
                   use_bias=False, feature_group_count=co)(y)
        y = _bn(train, None)(y)
        if residual.shape != y.shape:
            residual = Conv2D(self.channels * co, (1, 1),
                              (self.stride, self.stride),
                              use_bias=False, feature_group_count=co)(x)
            residual = _bn(train, None)(residual)
        return nn.relu(y + residual)


class ResNetCIFARS2DExact(nn.Module):
    """The standard CIFAR ResNet, stage 1 executed in s2d space.

    Same function as ``ResNetCIFAR(depth, norm="bn")`` under the weight
    conversion below; a different (TPU-friendlier) execution layout."""

    depth: int = 56
    num_classes: int = 10
    width: int = 16
    # cohort > 1 = cohort-grouped mode (see fedml_tpu.models.cohort)
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        n = (self.depth - 2) // 6
        w = self.width
        co = self.cohort
        x = s2d_rearrange(x, co)  # [B,16,16,C*4c_in]
        # stem conv (3x3 stride 1) in s2d space
        x = Conv2D(4 * w * co, (3, 3), padding="SAME", use_bias=False,
                   feature_group_count=co)(x)
        x = _bn(train, 4, co)(x)
        x = nn.relu(x)
        for _ in range(n):
            x = _S2DBasicBlock(4 * w, co)(x, train)
        x = _TransitionBlock(2 * w, co)(x, train)
        for _ in range(n - 1):
            x = _BasicBlock(2 * w, cohort=co)(x, train)
        for blk in range(n):
            x = _BasicBlock(4 * w, 2 if blk == 0 else 1, co)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        y = _cohort_dense(self.num_classes, co, "head")(x)
        return y.transpose(1, 0, 2) if co > 1 else y


def _tile4(v):
    return np.tile(np.asarray(v), 4)


def _bn_scopes(src_p, src_s, scope, pooled):
    p = {k: np.asarray(v) for k, v in src_p[scope].items()}
    s = {k: np.asarray(v) for k, v in src_s[scope].items()}
    if pooled:
        p = {k: _tile4(v) for k, v in p.items()}
        s = {k: _tile4(v) for k, v in s.items()}
    return (
        {k: jnp.asarray(v) for k, v in p.items()},
        {k: jnp.asarray(v) for k, v in s.items()},
    )


def convert_resnet_checkpoint_to_s2d(variables: dict,
                                     depth: int = 56) -> dict:
    """Standard ``ResNetCIFAR(depth, norm='bn')`` variables ->
    :class:`ResNetCIFARS2DExact` variables (exact; see module
    docstring). Scope mapping (both modules are @nn.compact, so flax
    auto-names follow call order deterministically):

    - stem ``Conv2D_0``/``BatchNorm_0`` -> s2d-converted stem
      (phase-pooled BN);
    - ``BasicBlock_0..n-1`` (stage 1) -> ``_S2DBasicBlock_i``;
    - ``BasicBlock_n`` (stage-2 entry, has shortcut) ->
      ``_TransitionBlock_0`` with stride-2 kernel conversions;
    - remaining blocks and the head copy through unchanged."""
    n = (depth - 2) // 6
    src_p = variables["params"]
    src_s = variables.get("batch_stats", {})
    out_p: dict = {}
    out_s: dict = {}

    # stem
    out_p["Conv2D_0"] = {
        "kernel": jnp.asarray(
            convert_conv3x3_to_s2d(src_p["Conv2D_0"]["kernel"])
        )
    }
    p, s = _bn_scopes(src_p, src_s, "BatchNorm_0", pooled=True)
    out_p["PhasePooledBatchNorm_0"] = p
    out_s["PhasePooledBatchNorm_0"] = s

    # stage 1: BasicBlock_0..n-1 -> _S2DBasicBlock_i
    for i in range(n):
        sb = src_p[f"BasicBlock_{i}"]
        ss = src_s[f"BasicBlock_{i}"]
        dst_p: dict = {}
        dst_s: dict = {}
        for j in (0, 1):
            dst_p[f"Conv2D_{j}"] = {
                "kernel": jnp.asarray(
                    convert_conv3x3_to_s2d(sb[f"Conv2D_{j}"]["kernel"])
                )
            }
            bp = {k: jnp.asarray(_tile4(v))
                  for k, v in sb[f"BatchNorm_{j}"].items()}
            bs = {k: jnp.asarray(_tile4(v))
                  for k, v in ss[f"BatchNorm_{j}"].items()}
            dst_p[f"PhasePooledBatchNorm_{j}"] = bp
            dst_s[f"PhasePooledBatchNorm_{j}"] = bs
        out_p[f"_S2DBasicBlock_{i}"] = dst_p
        out_s[f"_S2DBasicBlock_{i}"] = dst_s

    # stage-2 entry block -> transition
    sb = src_p[f"BasicBlock_{n}"]
    ss = src_s[f"BasicBlock_{n}"]
    out_p["_TransitionBlock_0"] = {
        "Conv2D_0": {
            "kernel": jnp.asarray(
                convert_conv3x3_stride2_to_s2d(sb["Conv2D_0"]["kernel"])
            )
        },
        "BatchNorm_0": {k: jnp.asarray(v)
                        for k, v in sb["BatchNorm_0"].items()},
        "Conv2D_1": {"kernel": jnp.asarray(sb["Conv2D_1"]["kernel"])},
        "BatchNorm_1": {k: jnp.asarray(v)
                        for k, v in sb["BatchNorm_1"].items()},
        "Conv2D_2": {
            "kernel": jnp.asarray(
                convert_conv1x1_stride2_to_s2d(sb["Conv2D_2"]["kernel"])
            )
        },
        "BatchNorm_2": {k: jnp.asarray(v)
                        for k, v in sb["BatchNorm_2"].items()},
    }
    out_s["_TransitionBlock_0"] = {
        k: {kk: jnp.asarray(vv) for kk, vv in v.items()}
        for k, v in ss.items()
    }

    # remaining blocks copy verbatim: BasicBlock_{n+1}.. -> _BasicBlock_i
    rest = [f"BasicBlock_{i}" for i in range(n + 1, 3 * n)]
    for i, scope in enumerate(rest):
        out_p[f"_BasicBlock_{i}"] = jax.tree.map(
            jnp.asarray, src_p[scope]
        )
        out_s[f"_BasicBlock_{i}"] = jax.tree.map(
            jnp.asarray, src_s[scope]
        )

    out_p["head"] = jax.tree.map(jnp.asarray, src_p["head"])
    return {"params": out_p, "batch_stats": out_s}

"""Cohort-grouped model application: the whole sampled cohort as ONE net.

The compiled FedAvg round trains every sampled client in parallel. The
naive form — ``vmap`` of the per-client model over stacked params — leaves
XLA with *batched-kernel* convolutions, which lower poorly on TPU at
CIFAR-class channel counts (see :mod:`fedml_tpu.ops.cohort_conv`); the
per-op grouped rewrite recovers part of it, but the layout shuffles it
must insert around every conv (cohort axis <-> channel groups) eat most
of the win at 32x32 activations.

This module takes the layout to its fixed point: the *model itself* runs
in cohort-grouped form end to end. A conv net over a cohort of C clients
is EXACTLY the same flax architecture with every conv width multiplied by
C and ``feature_group_count`` multiplied by C (group c = client c), BN/GN
over the widened channel axis (per-channel stats == per-client stats),
and a :class:`CohortDense` head contracting per-client feature blocks.
Activations stay ``[B, H, W, C*ch]`` throughout — zero per-layer
transposes — and every matmul/conv XLA sees is a single well-tiled
grouped op. Measured on v5e this runs the 10-client ResNet-56 local step
within ~1.5x of the shared-params conv floor, vs ~5.6x for the vmapped
form.

The zoo modules accept ``cohort=C`` and build this widened network from
the *same* code path as the per-client network (single source, no drift).
Parameters remain stored/aggregated in the stacked ``[C, ...]`` layout;
:func:`stack_to_fat` / :func:`fat_to_stack` are the (differentiable,
bitwise-invertible) adapters between the stacked trees and the widened
module's trees.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Pytree = Any


class CohortDense(nn.Module):
    """Per-client dense layer in cohort-grouped form.

    Accepts ``[B, C*f]`` (c-major channel blocks, e.g. pooled grouped
    activations) or ``[B, C, f]``; returns ``[B, C, features]``. Kernel is
    stored stacked ``[C, f, features]`` — identical to stacking C
    ``nn.Dense`` kernels — so the stacked<->fat adapters are identity for
    dense scopes."""

    cohort: int
    features: int

    @nn.compact
    def __call__(self, x):
        C = self.cohort
        if x.ndim == 2:
            x = x.reshape(x.shape[0], C, x.shape[1] // C)
        f = x.shape[-1]
        kernel = self.param(
            "kernel",
            # match nn.Dense default (lecun_normal over (f, features)),
            # drawn per client block
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (C, f, self.features),
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (C, self.features)
        )
        y = jnp.einsum("bcf,cfo->bco", x, kernel.astype(x.dtype))
        return y + bias.astype(y.dtype)


def cohort_flatten(x: jax.Array, cohort: int) -> jax.Array:
    """Per-client flatten of grouped activations ``[B, H, W, C*ch]`` (ch
    blocks c-major) to ``[B, C, H*W*ch]`` in the base model's (H, W, ch)
    flatten order — the bridge from a grouped conv trunk to
    :class:`CohortDense`. The c-major channel-block convention here MUST
    match :func:`stack_to_fat`'s kernel layout; keep it in one place."""
    if cohort == 1:
        return x.reshape((x.shape[0], -1))
    b, h, w, cch = x.shape
    x = x.reshape(b, h, w, cohort, cch // cohort)
    return x.transpose(0, 3, 1, 2, 4).reshape(b, cohort, -1)


def dense(features: int, cohort: int, name: str):
    """The head/dense factory zoo modules use in both modes, so the flax
    scope name (and thus the variables tree) is mode-independent."""
    if cohort == 1:
        return nn.Dense(features, name=name)
    return CohortDense(cohort=cohort, features=features, name=name)


# ---------------------------------------------------------------------------
# stacked [C, ...] <-> cohort-grouped ("fat") variable adapters
# ---------------------------------------------------------------------------


def _is_scope(d: dict) -> bool:
    return any(not isinstance(v, dict) for v in d.values())


def _map_scope(scope: dict, C: int, to_fat: bool) -> dict:
    kernel = scope.get("kernel")
    if kernel is not None and (kernel.ndim == 5 if to_fat else kernel.ndim == 4):
        # conv scope: stacked [C,kh,kw,ci,co] <-> grouped [kh,kw,ci,C*co];
        # bias [C,co] <-> [C*co] (grouped conv output channels are c-major)
        out = {}
        for k, v in scope.items():
            if k == "kernel":
                if to_fat:
                    c, kh, kw, ci, co = v.shape
                    out[k] = v.transpose(1, 2, 3, 0, 4).reshape(
                        kh, kw, ci, c * co
                    )
                else:
                    kh, kw, ci, cco = v.shape
                    out[k] = v.reshape(kh, kw, ci, C, cco // C).transpose(
                        3, 0, 1, 2, 4
                    )
            else:  # bias
                out[k] = (
                    v.reshape(-1) if to_fat else v.reshape(C, -1)
                )
        return out
    if kernel is not None:
        # dense scope (CohortDense stores stacked natively): identity
        return dict(scope)
    # norm params / batch_stats: [C, ch] <-> [C*ch]
    return {
        k: (v.reshape(-1) if to_fat else v.reshape(C, -1))
        for k, v in scope.items()
    }


def _walk(tree: Pytree, C: int, to_fat: bool) -> Pytree:
    if isinstance(tree, dict):
        if _is_scope(tree):
            return _map_scope(tree, C, to_fat)
        return {k: _walk(v, C, to_fat) for k, v in tree.items()}
    return tree


def stack_to_fat(stacked: Pytree, C: int) -> Pytree:
    """Stacked per-client variables -> the cohort-grouped module's tree.
    Differentiable (transposes/reshapes only), so grads w.r.t. stacked
    params flow through a fat-module apply unchanged."""
    return _walk(stacked, C, True)


def fat_to_stack(fat: Pytree, C: int) -> Pytree:
    """Inverse of :func:`stack_to_fat` (bitwise: pure layout)."""
    return _walk(fat, C, False)

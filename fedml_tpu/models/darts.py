"""DARTS search space for FedNAS.

TPU-native redesign of the reference DARTS stack
(``fedml_api/model/cv/darts/``: ``operations.py`` primitive ops,
``model_search.py:172`` ``Network`` with mixed ops,
``genotypes.py`` named architectures, ``model.py:111`` fixed network).

Architecture parameters (alphas) live in a separate flax collection
``"arch"`` so the bilevel optimizer can address weights and alphas
independently (the reference keeps ``arch_parameters`` apart from model
weights, ``model_search.py:230-240``). A MixedOp evaluates ALL candidate
ops and contracts them with softmax(alpha) — on TPU every candidate runs as
one fused batched graph, which XLA overlaps far better than the
reference's per-op python loop.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from fedml_tpu.ops.cohort_conv import Conv2D

PRIMITIVES = (
    "none",
    "skip_connect",
    "avg_pool_3x3",
    "max_pool_3x3",
    "sep_conv_3x3",
    "dil_conv_3x3",
)


def _op(name: str, channels: int, stride: int):
    """Primitive factory (reference ``operations.py`` OPS dict)."""

    class Zero(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            if stride > 1:
                x = x[:, ::stride, ::stride, :]
            return jnp.zeros_like(x[..., :channels]) if (
                x.shape[-1] != channels
            ) else jnp.zeros_like(x)

    class Skip(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            if stride == 1 and x.shape[-1] == channels:
                return x
            # factorized reduce (reference FactorizedReduce)
            h = Conv2D(channels, (1, 1), strides=(stride, stride),
                        use_bias=False)(x)
            return nn.BatchNorm(use_running_average=not train)(h)

    class Pool(nn.Module):
        kind: str

        @nn.compact
        def __call__(self, x, train=False):
            window = (1, 3, 3, 1)
            strides = (1, stride, stride, 1)
            if self.kind == "avg":
                h = jax.lax.reduce_window(
                    x, 0.0, jax.lax.add, window, strides, "SAME"
                ) / 9.0
            else:
                h = jax.lax.reduce_window(
                    x, -jnp.inf, jax.lax.max, window, strides, "SAME"
                )
            if h.shape[-1] != channels:
                h = Conv2D(channels, (1, 1), use_bias=False)(h)
            return h

    class SepConv(nn.Module):
        dilation: int = 1

        @nn.compact
        def __call__(self, x, train=False):
            h = nn.relu(x)
            h = Conv2D(
                x.shape[-1], (3, 3), strides=(stride, stride),
                padding="SAME", feature_group_count=x.shape[-1],
                kernel_dilation=(self.dilation, self.dilation),
                use_bias=False,
            )(h)
            h = Conv2D(channels, (1, 1), use_bias=False)(h)
            return nn.BatchNorm(use_running_average=not train)(h)

    return {
        "none": Zero,
        "skip_connect": Skip,
        "avg_pool_3x3": lambda: Pool(kind="avg"),
        "max_pool_3x3": lambda: Pool(kind="max"),
        "sep_conv_3x3": SepConv,
        "dil_conv_3x3": lambda: SepConv(dilation=2),
    }[name]()


class MixedOp(nn.Module):
    """softmax(alpha)-weighted sum over all primitives
    (reference ``model_search.py:34-50``)."""

    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, weights, train: bool = False):
        outs = [
            _op(p, self.channels, self.stride)(x, train=train)
            for p in PRIMITIVES
        ]
        stacked = jnp.stack(outs, axis=0)  # [P, B, H, W, C]
        return jnp.einsum("p,pbhwc->bhwc", weights, stacked)


class SearchCell(nn.Module):
    """DARTS cell: ``steps`` intermediate nodes, each summing mixed ops
    from all previous states (reference ``model_search.py:52-95``)."""

    channels: int
    steps: int = 4
    reduction: bool = False

    @nn.compact
    def __call__(self, s0, s1, weights, train: bool = False):
        # when the previous cell reduced, s0 (two cells back) is 2x the
        # spatial size of s1 — align first (reference FactorizedReduce,
        # operations.py)
        if s0.shape[1] != s1.shape[1]:
            s0 = s0[:, ::2, ::2, :]
        s0 = Conv2D(self.channels, (1, 1), use_bias=False)(s0)
        s1 = Conv2D(self.channels, (1, 1), use_bias=False)(s1)
        if self.reduction:
            s0 = s0[:, ::2, ::2, :]
            s1 = s1[:, ::2, ::2, :]
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            acc = 0.0
            for j, h in enumerate(states):
                acc = acc + MixedOp(self.channels, 1)(
                    h, weights[offset + j], train=train
                )
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.steps:], axis=-1)


def num_edges(steps: int) -> int:
    return sum(2 + i for i in range(steps))


class DARTSNetwork(nn.Module):
    """Searchable network (reference ``model_search.py:172``): stem ->
    [normal x N, reduction] cells -> classifier. Alphas: collection
    ``arch`` with ``alphas_normal`` / ``alphas_reduce``
    [num_edges, |PRIMITIVES|]."""

    num_classes: int = 10
    init_channels: int = 16
    layers: int = 4
    steps: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        e = num_edges(self.steps)
        a_n = self.param_or_arch("alphas_normal", e)
        a_r = self.param_or_arch("alphas_reduce", e)
        w_n = jax.nn.softmax(a_n, axis=-1)
        w_r = jax.nn.softmax(a_r, axis=-1)

        c = self.init_channels
        h = Conv2D(c, (3, 3), padding="SAME", use_bias=False)(x)
        h = nn.BatchNorm(use_running_average=not train)(h)
        s0 = s1 = h
        for layer in range(self.layers):
            reduction = layer in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                c *= 2
            out = SearchCell(c, self.steps, reduction)(
                s0, s1, w_r if reduction else w_n, train=train
            )
            s0, s1 = s1, out
        h = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(h)

    def param_or_arch(self, name: str, e: int):
        return self.variable(
            "arch", name,
            lambda: 1e-3 * jax.random.normal(
                self.make_rng("params"), (e, len(PRIMITIVES))
            ),
        ).value


def derive_genotype(arch_vars) -> dict:
    """argmax-derivation of the discrete architecture (reference
    ``model_search.py`` ``genotype()``): for each node keep the two
    strongest incoming edges with their best non-'none' op."""
    out = {}
    for key in ("alphas_normal", "alphas_reduce"):
        alphas = jax.nn.softmax(arch_vars["arch"][key], axis=-1)
        alphas = jax.device_get(alphas)
        gene = []
        offset = 0
        none_idx = PRIMITIVES.index("none")
        steps = 0
        n_in = 2
        e = alphas.shape[0]
        # recover steps from edge count
        while num_edges(steps) < e:
            steps += 1
        for i in range(steps):
            k = 2 + i
            rows = alphas[offset:offset + k]
            best_op = rows.copy()
            best_op[:, none_idx] = -1
            edge_strength = best_op.max(axis=-1)
            top2 = edge_strength.argsort()[-2:][::-1]
            for j in sorted(top2):
                op = int(best_op[j].argmax())
                gene.append((PRIMITIVES[op], int(j)))
            offset += k
        out[key] = gene
    return out

"""GAN model zoo: conditional/unconditional image generators and the ACGAN
discriminator family.

TPU-native re-design of the reference GAN models
(``fedml_api/model/cv/generator.py:29-145``, ``fedml_api/model/cv/mnist_gan.py:6-55``,
``fedml_api/model/cv/cnn_custom.py:8-60`` — the parameterised CNN whose
``discriminator=True`` call path returns (class_logits, validity)).

Design notes (TPU-first):
- NHWC layout throughout (XLA's preferred conv layout on TPU).
- ``ConvTranspose`` pyramids sized so every intermediate is a multiple of 8
  in the spatial dims where possible; channel counts are multiples of 64 by
  default (``ngf``), which tiles cleanly onto the MXU.
- The generator mirrors the reference's shape recipe: a label embedding is
  multiplied elementwise with the noise vector, projected by a dense layer
  to ``first_filters * init_size**2``, then upsampled by stride-2
  transposed convs with BatchNorm+ReLU, ending in tanh
  (``generator.py:72-125``).
- ``img_size`` need not be a power of two: we pick the largest number of
  doublings such that ``init_size = img_size >> n_ups`` stays >= 4 (so
  MNIST's 28 -> init 7, two upsamplings; CIFAR's 32 -> init 4, three).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from fedml_tpu.ops.cohort_conv import Conv2D, ConvTranspose2D


def _plan_upsampling(img_size: int, min_init: int = 4) -> tuple[int, int]:
    """Number of stride-2 upsamplings and the starting spatial size."""
    n_ups = 0
    size = img_size
    while size % 2 == 0 and size // 2 >= min_init:
        size //= 2
        n_ups += 1
    if n_ups == 0:
        raise ValueError(f"img_size {img_size} too small for a conv pyramid")
    return n_ups, size


class _GeneratorPyramid(nn.Module):
    """Shared DCGAN upsampling trunk: Dense projection -> reshape ->
    (ConvTranspose + BN + relu) x n_blocks -> ConvTranspose -> tanh
    (the common body of reference ``ImageGenerator`` and
    ``ConditionalImageGenerator``, ``generator.py:29-125``).

    ``cohort=C`` builds the cohort-grouped form (one widened network
    runs C clients' generators at once — the GAN analog of
    :mod:`fedml_tpu.models.cohort`): the projection becomes a stacked
    CohortDense, transposed convs widen xC with ``feature_group_count=C``
    (channel group c IS client c), BN per-channel stats stay per-client.
    Input is ``[B, C, nz]``; output is GROUPED ``[B, H, W, C*channels]``
    (callers ungroup). Scope names match the cohort=1 form, so stacked
    per-client trees map onto it via ``models.cohort.stack_to_fat``."""

    img_size: int
    channels: int
    ngf: int
    cohort: int = 1

    @nn.compact
    def __call__(self, gen_input, train: bool = False):
        from fedml_tpu.models.cohort import dense as cohort_dense

        C = self.cohort
        n_ups, init_size = _plan_upsampling(self.img_size)
        # final ConvTranspose is one of the upsamplings; inner blocks = rest
        n_blocks = n_ups - 1
        first_filters = self.ngf * (2 ** n_blocks)
        h = cohort_dense(
            first_filters * init_size * init_size, C, name="l1"
        )(gen_input)
        if C == 1:
            h = h.reshape((-1, init_size, init_size, first_filters))
        else:
            # [B, C, is*is*ff] -> grouped [B, is, is, C*ff] (c-major
            # channel blocks; inverse of models.cohort.cohort_flatten)
            b = h.shape[0]
            h = h.reshape(b, C, init_size, init_size, first_filters)
            h = h.transpose(0, 2, 3, 1, 4).reshape(
                b, init_size, init_size, C * first_filters
            )
        for i in range(n_blocks):
            feats = self.ngf * (2 ** (n_blocks - 1 - i))
            h = ConvTranspose2D(
                feats * C, (4, 4), strides=(2, 2), padding="SAME",
                use_bias=False, feature_group_count=C,
            )(h)
            h = nn.BatchNorm(use_running_average=not train)(h)
            h = nn.relu(h)
        h = ConvTranspose2D(
            self.channels * C, (4, 4), strides=(2, 2), padding="SAME",
            use_bias=False, feature_group_count=C,
        )(h)
        return jnp.tanh(h)


class ConditionalImageGenerator(nn.Module):
    """Label-conditioned DCGAN-style generator
    (reference ``ConditionalImageGenerator``, ``generator.py:72-125``).

    ``__call__(z, labels)`` with ``z`` [B, nz] float and ``labels`` [B] int
    returns images [B, H, W, C] in (-1, 1) (tanh).
    """

    num_classes: int
    img_size: int = 32
    channels: int = 3
    nz: int = 100
    ngf: int = 64

    @nn.compact
    def __call__(self, z, labels, train: bool = False):
        emb = nn.Embed(self.num_classes, self.nz, name="label_emb")(labels)
        return _GeneratorPyramid(
            self.img_size, self.channels, self.ngf, name="pyramid"
        )(z * emb, train=train)


class ImageGenerator(nn.Module):
    """Unconditional DCGAN generator (reference ``ImageGenerator``,
    ``generator.py:29-69``)."""

    img_size: int = 32
    channels: int = 3
    nz: int = 100
    ngf: int = 64

    @nn.compact
    def __call__(self, z, train: bool = False):
        return _GeneratorPyramid(
            self.img_size, self.channels, self.ngf, name="pyramid"
        )(z, train=train)


class ACGANDiscriminator(nn.Module):
    """Conv classifier with an auxiliary validity head — the shape of the
    fork's client models (``cnn_custom.py:8-60``): a strided-conv trunk, a
    class-logits head, and a ``discriminator`` head producing one
    real/fake logit. We return the validity as a LOGIT (the reference
    applies an in-module Sigmoid and BCELoss; sigmoid+BCE == BCE-with-logits).

    ``__call__(x, train)`` -> class_logits [B, K]
    ``__call__(x, train, discriminator=True)`` -> (class_logits, validity [B, 1])
    """

    num_classes: int
    features: Sequence[int] = (32, 64, 128)
    dropout: float = 0.25

    @nn.compact
    def __call__(self, x, train: bool = False, discriminator: bool = False):
        h = x
        for f in self.features:
            h = Conv2D(f, (3, 3), strides=(2, 2), padding="SAME",
                        use_bias=False)(h)
            h = nn.leaky_relu(h, 0.2)
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
            h = nn.BatchNorm(use_running_average=not train)(h)
        h = h.reshape((h.shape[0], -1))
        trunk = h
        cls = nn.Dense(128, name="cls_hidden")(trunk)
        cls = nn.Dense(self.num_classes, name="cls_out")(cls)
        if not discriminator:
            return cls
        val = nn.Dense(128, name="disc_hidden")(trunk)
        val = nn.Dense(1, name="disc_out")(val)
        return cls, val


@dataclasses.dataclass(frozen=True)
class GanModel:
    """Functional handle on a generator module (conditional or not), the GAN
    analog of :class:`fedml_tpu.models.base.FedModel`."""

    module: nn.Module
    nz: int
    num_classes: int
    conditional: bool = True

    def init(self, rng: jax.Array) -> Any:
        z = jnp.zeros((1, self.nz), jnp.float32)
        if self.conditional:
            return self.module.init(
                {"params": rng}, z, jnp.zeros((1,), jnp.int32), train=False
            )
        return self.module.init({"params": rng}, z, train=False)

    def apply_train(self, variables, z, labels=None):
        args = (z, labels) if self.conditional else (z,)
        imgs, mutated = self.module.apply(
            variables, *args, train=True, mutable=["batch_stats"]
        )
        return imgs, {**variables, **mutated}

    def apply_eval(self, variables, z, labels=None):
        args = (z, labels) if self.conditional else (z,)
        return self.module.apply(variables, *args, train=False)

    def supports_cohort(self) -> bool:
        """Cohort-grouped apply needs the pyramid-shaped generator (the
        zoo's Image/ConditionalImageGenerator)."""
        return isinstance(
            self.module, (ImageGenerator, ConditionalImageGenerator)
        )

    def apply_cohort_train(self, stacked_vars, z, labels=None):
        """Train-mode forward of C clients' generators at once in
        cohort-grouped form (the GAN analog of
        ``FedModel.apply_cohort_train``): label embeddings are looked up
        per client in stacked form (elementwise, cheap), then the
        pyramid runs as ONE widened grouped network. ``stacked_vars``
        has leading client axis C on every leaf; ``z`` is [C, B, nz],
        ``labels`` [C, B]. Returns (fakes [C, B, H, W, ch], updated
        stacked vars). Numerically the per-client network re-laid-out —
        same equality class as the classifier cohort path."""
        from fedml_tpu.models.cohort import fat_to_stack, stack_to_fat

        C = z.shape[0]
        if C == 1:
            squeezed = jax.tree.map(lambda v: v[0], stacked_vars)
            fakes, new_vars = self.apply_train(
                squeezed, z[0], labels[0] if labels is not None else None
            )
            return fakes[None], jax.tree.map(lambda v: v[None], new_vars)
        p = stacked_vars["params"]
        if self.conditional:
            emb = jax.vmap(lambda table, lbl: table[lbl])(
                p["label_emb"]["embedding"], labels
            )  # [C, B, nz]
            gen_input = z * emb
        else:
            gen_input = z
        fat = {"params": stack_to_fat(p["pyramid"], C)}
        if "batch_stats" in stacked_vars:
            fat["batch_stats"] = stack_to_fat(
                stacked_vars["batch_stats"]["pyramid"], C
            )
        pyramid = _GeneratorPyramid(
            self.module.img_size, self.module.channels, self.module.ngf,
            cohort=C,
        )
        out, mutated = pyramid.apply(
            fat, gen_input.transpose(1, 0, 2), train=True,
            mutable=["batch_stats"],
        )
        b, hh, ww, cch = out.shape
        fakes = out.reshape(b, hh, ww, C, cch // C).transpose(
            3, 0, 1, 2, 4
        )
        new_vars = stacked_vars
        if "batch_stats" in stacked_vars:
            new_vars = {
                **stacked_vars,
                "batch_stats": {
                    **stacked_vars["batch_stats"],
                    "pyramid": fat_to_stack(mutated["batch_stats"], C),
                },
            }
        return fakes, new_vars

    def sample_noise(self, rng: jax.Array, n: int) -> jax.Array:
        """Gaussian latent (reference ``generate_noise_vector``,
        ``generator.py:120-121``)."""
        return jax.random.normal(rng, (n, self.nz))

    def sample_labels(self, rng: jax.Array, n: int) -> jax.Array:
        return jax.random.randint(rng, (n,), 0, self.num_classes)

    def balanced_labels(self, n: int) -> jax.Array:
        """Near-uniform label vector (reference ``generate_balanced_labels``,
        ``generator.py:129-145``): class c appears ceil/floor(n/K) times."""
        return jnp.arange(n, dtype=jnp.int32) % self.num_classes


def create_conditional_generator(
    num_classes: int,
    img_size: int = 32,
    channels: int = 3,
    nz: int = 100,
    ngf: int = 64,
) -> GanModel:
    return GanModel(
        module=ConditionalImageGenerator(
            num_classes=num_classes, img_size=img_size, channels=channels,
            nz=nz, ngf=ngf,
        ),
        nz=nz,
        num_classes=num_classes,
        conditional=True,
    )


def generator_from_config(
    gan_cfg, num_classes: int, img_size: int, channels: int,
    conditional: bool = True,
) -> GanModel:
    """Build a generator from :class:`fedml_tpu.config.GanConfig` so the
    ``nz``/``ngf`` knobs in experiment configs are authoritative (reference
    ``--nz``/``--ngf`` args, ``main_fedgdkd.py:29-36``)."""
    if conditional:
        return create_conditional_generator(
            num_classes, img_size, channels, nz=gan_cfg.nz, ngf=gan_cfg.ngf
        )
    return create_generator(img_size, channels, nz=gan_cfg.nz, ngf=gan_cfg.ngf)


def create_generator(
    img_size: int = 32, channels: int = 3, nz: int = 100, ngf: int = 64
) -> GanModel:
    return GanModel(
        module=ImageGenerator(
            img_size=img_size, channels=channels, nz=nz, ngf=ngf
        ),
        nz=nz,
        num_classes=0,
        conditional=False,
    )

"""Segmentation model: DeepLab-lite (encoder + ASPP + bilinear decoder).

TPU-native stand-in for the reference's DeepLabV3+ with
MobileNet/ResNet backbones (``fedml_api/distributed/fedseg/FedSegAPI.py``,
``fedml_api/model/cv/batchnorm_utils.py`` sync-BN): a strided-conv encoder,
an atrous-spatial-pyramid-pooling head (dilated 3x3 convs — XLA lowers
dilated convs onto the MXU directly), and a bilinear-resize decoder to
per-pixel class logits. Sync-BN across the data axis is provided by the
trainer's batch-stats pmean (``fedml_tpu/algorithms/base.py``), replacing
``SynchronizedBatchNorm2d`` (``batchnorm_utils.py:292``).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from fedml_tpu.ops.cohort_conv import Conv2D


class DeepLabLite(nn.Module):
    num_classes: int = 21
    encoder_features: Sequence[int] = (32, 64, 128)
    aspp_rates: Sequence[int] = (1, 2, 4)
    aspp_features: int = 128

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x
        for f in self.encoder_features:
            h = Conv2D(f, (3, 3), strides=(2, 2), padding="SAME",
                        use_bias=False)(h)
            h = nn.BatchNorm(use_running_average=not train)(h)
            h = nn.relu(h)
        # ASPP: parallel dilated branches + global context
        branches = []
        for r in self.aspp_rates:
            b = Conv2D(
                self.aspp_features, (3, 3), padding="SAME",
                kernel_dilation=(r, r), use_bias=False,
            )(h)
            b = nn.BatchNorm(use_running_average=not train)(b)
            branches.append(nn.relu(b))
        gp = jnp.mean(h, axis=(1, 2), keepdims=True)
        gp = Conv2D(self.aspp_features, (1, 1), use_bias=False)(gp)
        gp = jnp.broadcast_to(
            gp, (h.shape[0],) + h.shape[1:3] + (self.aspp_features,)
        )
        branches.append(gp)
        h = jnp.concatenate(branches, axis=-1)
        h = Conv2D(self.aspp_features, (1, 1), use_bias=False)(h)
        h = nn.BatchNorm(use_running_average=not train)(h)
        h = nn.relu(h)
        logits = Conv2D(self.num_classes, (1, 1))(h)
        # bilinear upsample back to input resolution
        return jax.image.resize(
            logits,
            (x.shape[0], x.shape[1], x.shape[2], self.num_classes),
            method="bilinear",
        )

"""Vision model zoo (flax linen).

TPU-native re-designs of the reference zoo (``fedml_api/model/cv``,
SURVEY.md §2.4): logistic regression, the FedAvg-paper CNNs, CIFAR ResNets
(BatchNorm), ResNet-18 with GroupNorm, MobileNet(V1), VGG, and the fork's
parameterised small/medium/large CNNs. All use NHWC layout and default to
``float32`` params with matmuls free to run bfloat16 on the MXU via jax
default precision.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.models.cohort import cohort_flatten, dense as _cohort_dense
from fedml_tpu.ops.cohort_conv import Conv2D


class LogisticRegression(nn.Module):
    """Flatten -> dense (reference ``fedml_api/model/linear/lr.py:4``)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)


class CNNOriginalFedAvg(nn.Module):
    """2x(conv5x5 + maxpool) + dense-512 CNN from the FedAvg paper
    (reference ``fedml_api/model/cv/cnn.py:5``)."""

    num_classes: int = 62
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        co = self.cohort
        x = Conv2D(32 * co, (5, 5), padding="SAME",
                   feature_group_count=co)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), (2, 2))
        x = Conv2D(64 * co, (5, 5), padding="SAME",
                   feature_group_count=co)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), (2, 2))
        x = cohort_flatten(x, co)
        x = nn.relu(_cohort_dense(512, co, "fc1")(x))
        y = _cohort_dense(self.num_classes, co, "head")(x)
        return y.transpose(1, 0, 2) if co > 1 else y


class CNNDropOut(nn.Module):
    """Conv net with dropout (reference ``fedml_api/model/cv/cnn.py:74``)."""

    num_classes: int = 62

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(Conv2D(32, (3, 3), padding="VALID")(x))
        x = nn.relu(Conv2D(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), (2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class CNNParameterised(nn.Module):
    """Configurable conv stack — the fork's heterogeneous-client models
    (reference ``fedml_api/model/cv/cnn_custom.py:8`` with
    CNNSmall/Medium/Large builders)."""

    num_classes: int = 10
    conv_channels: Sequence[int] = (32, 64)
    dense_sizes: Sequence[int] = (128,)
    dropout: float = 0.0
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        co = self.cohort
        for ch in self.conv_channels:
            x = nn.relu(
                Conv2D(ch * co, (3, 3), padding="SAME",
                       feature_group_count=co)(x)
            )
            x = nn.max_pool(x, (2, 2), (2, 2))
        x = cohort_flatten(x, co)
        for i, d in enumerate(self.dense_sizes):
            x = nn.relu(_cohort_dense(d, co, f"fc{i + 1}")(x))
            if self.dropout > 0:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        y = _cohort_dense(self.num_classes, co, "head")(x)
        return y.transpose(1, 0, 2) if co > 1 else y


def _norm(kind: str, train: bool, cohort: int = 1):
    if kind == "bn":
        # cohort-grouped layout: per-channel stats are already per-client
        return nn.BatchNorm(use_running_average=not train, momentum=0.9)
    if kind == "gn":
        # widened channels are c-major, so scaling the group count keeps
        # every group inside one client's block (groups must not mix
        # clients)
        return nn.GroupNorm(num_groups=2 * cohort)
    if kind.startswith("syncbn"):
        # "syncbn:<axis>" = exact cross-shard BN over that mesh axis
        # (reference SynchronizedBatchNorm; see SyncBatchNorm below).
        # The axis is REQUIRED — an axis-less syncbn would silently be
        # per-shard BN, the exact bug the kind exists to prevent.
        if not kind.startswith("syncbn:") or not kind.split(":", 1)[1]:
            raise ValueError(
                f"{kind!r}: use 'syncbn:<mesh_axis>' (e.g. 'syncbn:data')"
            )
        return _SyncBNShim(axis_name=kind.split(":", 1)[1], train=train)
    raise ValueError(kind)


class _SyncBNShim(nn.Module):
    """Adapter so SyncBatchNorm drops into the _norm(...)(y) call shape
    (the other norms take train at construction or ignore it)."""

    axis_name: str | None
    train: bool

    @nn.compact
    def __call__(self, x):
        return SyncBatchNorm(axis_name=self.axis_name)(x, train=self.train)


class BasicBlock(nn.Module):
    """CIFAR ResNet basic block (reference
    ``fedml_api/model/cv/resnet.py:30``; GN variant ``resnet_gn.py``)."""

    channels: int
    stride: int = 1
    norm: str = "bn"
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        ch, co = self.channels * self.cohort, self.cohort
        residual = x
        y = Conv2D(ch, (3, 3), (self.stride, self.stride),
                   padding="SAME", use_bias=False,
                   feature_group_count=co)(x)
        y = _norm(self.norm, train, co)(y)
        y = nn.relu(y)
        y = Conv2D(ch, (3, 3), padding="SAME", use_bias=False,
                   feature_group_count=co)(y)
        y = _norm(self.norm, train, co)(y)
        if residual.shape != y.shape:
            residual = Conv2D(ch, (1, 1),
                              (self.stride, self.stride),
                              use_bias=False, feature_group_count=co)(x)
            residual = _norm(self.norm, train, co)(residual)
        return nn.relu(y + residual)


class ResNetCIFAR(nn.Module):
    """3-stage CIFAR ResNet: depth = 6n+2 (resnet56 => n=9; reference
    ``fedml_api/model/cv/resnet.py:113``).

    ``space_to_depth=True`` is the TPU-optimized layout ("<name>_s2d" in
    the model factory): inputs are rearranged [H,W,C] -> [H/2,W/2,4C] and
    stage widths become (4w, 2w, 4w) with strides (1,1,2), preserving the
    per-stage output resolutions of stages 2-3 and total depth. CIFAR
    widths (16 channels at 32x32) use ~12.5% of the VPU's 128 lanes; the
    s2d form runs the same FLOP-class network ~1.5x faster on v5e
    (measured on the vmapped FedAvg local step, bf16). It is a different
    parameterization — use it when TPU throughput matters more than
    checkpoint compatibility with the reference."""

    depth: int = 56
    num_classes: int = 10
    norm: str = "bn"
    width: int = 16
    space_to_depth: bool = False
    # cohort > 1 = cohort-grouped mode (see fedml_tpu.models.cohort):
    # input [B, H, W, C*cin] with client blocks c-major, output [C, B, K]
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        n = (self.depth - 2) // 6
        co = self.cohort
        if self.space_to_depth:
            b, h, w, cc = x.shape
            c = cc // co
            # keep client blocks outermost in the channel dim so grouped
            # convs stay client-aligned: [..., C, 2, 2, c] -> C*(4c)
            x = x.reshape(b, h // 2, 2, w // 2, 2, co, c)
            x = x.transpose(0, 1, 3, 5, 2, 4, 6).reshape(
                b, h // 2, w // 2, co * 4 * c
            )
            widths = (4 * self.width, 2 * self.width, 4 * self.width)
            strides = (1, 1, 2)
        else:
            widths = (self.width, 2 * self.width, 4 * self.width)
            strides = (1, 2, 2)
        x = Conv2D(widths[0] * co, (3, 3), padding="SAME", use_bias=False,
                   feature_group_count=co)(x)
        x = _norm(self.norm, train, co)(x)
        x = nn.relu(x)
        for stage, (ch, st) in enumerate(zip(widths, strides)):
            for blk in range(n):
                stride = st if (stage > 0 and blk == 0) else 1
                x = BasicBlock(ch, stride, self.norm, co)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        y = _cohort_dense(self.num_classes, co, "head")(x)
        return y.transpose(1, 0, 2) if co > 1 else y


class ResNet18GN(nn.Module):
    """ImageNet-style ResNet-18 with GroupNorm, used by fed_cifar100
    (reference ``fedml_api/model/cv/resnet_gn.py:108``)."""

    num_classes: int = 100
    cohort: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        co = self.cohort
        x = Conv2D(64 * co, (3, 3), padding="SAME", use_bias=False,
                   feature_group_count=co)(x)
        x = nn.GroupNorm(num_groups=2 * co)(x)
        x = nn.relu(x)
        for stage, ch in enumerate((64, 128, 256, 512)):
            for blk in range(2):
                stride = 2 if (stage > 0 and blk == 0) else 1
                x = BasicBlock(ch, stride, norm="gn", cohort=co)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        y = _cohort_dense(self.num_classes, co, "head")(x)
        return y.transpose(1, 0, 2) if co > 1 else y


class DepthwiseSeparable(nn.Module):
    channels: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = Conv2D(in_ch, (3, 3), (self.stride, self.stride),
                    padding="SAME", feature_group_count=in_ch,
                    use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.relu(x)
        x = Conv2D(self.channels, (1, 1), use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        return nn.relu(x)


class MobileNet(nn.Module):
    """MobileNetV1 (reference ``fedml_api/model/cv/mobilenet.py:60``)."""

    num_classes: int = 10
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        def c(ch):
            return max(8, int(ch * self.width_mult))

        x = Conv2D(c(32), (3, 3), (1, 1), padding="SAME", use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.relu(x)
        plan = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
                *[(512, 1)] * 5, (1024, 2), (1024, 1)]
        for ch, s in plan:
            x = DepthwiseSeparable(c(ch), s)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class VGG(nn.Module):
    """VGG-11/16 style stack (reference ``fedml_api/model/cv/vgg.py:13``)."""

    num_classes: int = 10
    plan: Sequence[Any] = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M",
                           512, 512, "M")

    @nn.compact
    def __call__(self, x, train: bool = False):
        for p in self.plan:
            if p == "M":
                x = nn.max_pool(x, (2, 2), (2, 2))
            else:
                x = nn.relu(Conv2D(int(p), (3, 3), padding="SAME")(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(self.num_classes)(x)


class SyncBatchNorm(nn.Module):
    """EXACT cross-shard BatchNorm (reference ``SynchronizedBatchNorm2d``,
    ``fedml_api/model/cv/batchnorm_utils.py:292`` — used by fedseg for
    DDP-correct batch statistics). Batch mean/variance are computed from
    psum-reduced (count, sum, sum-of-squares) over ``axis_name``, so the
    normalization equals single-device BN on the concatenated global batch
    — not the per-shard approximation. Use inside ``shard_map`` over a
    data axis; with ``axis_name=None`` it degrades to plain BN.

    Parity note: train-time normalization is exact vs full-batch BN. The
    running-var EMA stores the BIASED batch variance — the flax
    ``nn.BatchNorm`` convention used throughout this zoo — whereas torch's
    SynchronizedBatchNorm stores the unbiased (n/(n-1)) estimator; eval
    outputs differ from torch by that factor's sqrt per update."""

    axis_name: str | None = None
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False):
        ch = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (ch,))
        bias = self.param("bias", nn.initializers.zeros, (ch,))
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((ch,))
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((ch,))
        )
        if train:
            red = tuple(range(x.ndim - 1))
            n = jnp.asarray(
                np.prod([x.shape[i] for i in red]), jnp.float32
            )
            s = jnp.sum(x, axis=red)
            ss = jnp.sum(jnp.square(x), axis=red)
            if self.axis_name is not None:
                n = jax.lax.psum(n, self.axis_name)
                s = jax.lax.psum(s, self.axis_name)
                ss = jax.lax.psum(ss, self.axis_name)
            mean = s / n
            var = jnp.maximum(ss / n - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value
                    + (1.0 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value
                    + (1.0 - self.momentum) * var
                )
        else:
            mean, var = ra_mean.value, ra_var.value
        y = (x - mean) / jnp.sqrt(var + self.epsilon)
        return y * scale + bias

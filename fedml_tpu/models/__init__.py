"""Model factory.

Mirrors the reference ``create_model(args, model_name, output_dim)``
dispatch (``fedml_experiments/distributed/fedavg/main_fedavg.py:354-389``)
but returns a functional :class:`~fedml_tpu.models.base.FedModel`.
"""

from __future__ import annotations

import jax.numpy as jnp

from fedml_tpu.config import ModelConfig
from fedml_tpu.models.base import FedModel
from fedml_tpu.models import nlp, vision
from fedml_tpu.models.vision import (
    CNNDropOut,
    CNNOriginalFedAvg,
    CNNParameterised,
    LogisticRegression,
    MobileNet,
    ResNet18GN,
    ResNetCIFAR,
    VGG,
)
from fedml_tpu.models.nlp import CharLSTM, NWPLSTM, TagLogisticRegression


def create_model(cfg: ModelConfig) -> FedModel:
    name = cfg.name.lower()
    nc = cfg.num_classes
    extra = cfg.extra_dict()
    if name == "lr":
        return FedModel(LogisticRegression(nc), cfg.input_shape)
    if name == "cnn":  # reference "cnn" == CNN_DropOut (main_fedavg.py:360)
        return FedModel(CNNDropOut(nc), cfg.input_shape, has_dropout=True)
    if name == "cnn_fedavg":
        return FedModel(CNNOriginalFedAvg(nc), cfg.input_shape)
    if name == "cnn_custom":
        # fork's parameterised CNN with conv widths from the client config
        # ("layers" entries in experiment_client_configs/*.json;
        # model/cv/cnn_custom.py:8)
        return FedModel(
            CNNParameterised(
                nc,
                tuple(extra.get("convs", (16, 32))),
                tuple(extra.get("denses", (128,))),
                extra.get("dropout", 0.0),
            ),
            cfg.input_shape,
            has_dropout=extra.get("dropout", 0.0) > 0,
        )
    if name in ("cnn_small", "cnn_medium", "cnn_large"):
        plans = {
            "cnn_small": ((16, 32), (64,)),
            "cnn_medium": ((32, 64), (128,)),
            "cnn_large": ((64, 128, 256), (256,)),
        }
        convs, denses = plans[name]
        return FedModel(
            CNNParameterised(nc, convs, denses, extra.get("dropout", 0.0)),
            cfg.input_shape,
            has_dropout=extra.get("dropout", 0.0) > 0,
        )
    if name.startswith("resnet"):
        if name == "resnet18_gn":
            if "norm" in extra:
                raise ValueError(
                    "resnet18_gn is the fixed GroupNorm ImageNet-style "
                    "model (reference resnet_gn.py); a norm override does "
                    "not apply — use resnet<depth> with extra norm instead"
                )
            return FedModel(ResNet18GN(nc), cfg.input_shape)
        # name grammar: resnet<depth>[_gn][_s2d]; the norm default comes
        # from the suffix, and extra=(("norm", "syncbn:data"),) overrides
        # it for EVERY resnet variant (exact cross-shard BN on the named
        # mesh axis — models.vision.SyncBatchNorm)
        base = name[len("resnet"):]
        if base.endswith("_s2d_exact"):
            # EXACT s2d execution layout of the standard (BN) ResNet:
            # weight-compatible with resnet<depth> checkpoints through
            # models.s2d_exact.convert_resnet_checkpoint_to_s2d
            from fedml_tpu.models.s2d_exact import ResNetCIFARS2DExact

            depth = int(base[: -len("_s2d_exact")])
            return FedModel(
                ResNetCIFARS2DExact(depth, nc), cfg.input_shape,
                has_batch_stats=True,
            )
        s2d = base.endswith("_s2d")
        if s2d:
            base = base[: -len("_s2d")]
        gn = base.endswith("_gn")
        if gn:
            base = base[: -len("_gn")]
        depth = int(base)
        norm = extra.get("norm", "gn" if gn else "bn")
        return FedModel(
            ResNetCIFAR(depth, nc, norm=norm, space_to_depth=s2d),
            cfg.input_shape,
            has_batch_stats=norm != "gn",
        )
    if name == "mobilenet":
        return FedModel(
            MobileNet(nc, extra.get("width_mult", 1.0)),
            cfg.input_shape,
            has_batch_stats=True,
        )
    if name == "vgg11":
        return FedModel(VGG(nc), cfg.input_shape)
    if name == "mobilenet_v3":
        from fedml_tpu.models.vision_extra import MobileNetV3

        return FedModel(
            MobileNetV3(nc, extra.get("width_mult", 1.0)),
            cfg.input_shape, has_batch_stats=True,
        )
    if name.startswith("efficientnet"):
        from fedml_tpu.models.vision_extra import EfficientNet

        # efficientnet-b0..b7 compound coefficients
        # (reference efficientnet_utils.py efficientnet_params)
        params = {
            "b0": (1.0, 1.0), "b1": (1.0, 1.1), "b2": (1.1, 1.2),
            "b3": (1.2, 1.4), "b4": (1.4, 1.8), "b5": (1.6, 2.2),
            "b6": (1.8, 2.6), "b7": (2.0, 3.1),
        }
        suffix = name[len("efficientnet"):].lstrip("-_") or "b0"
        if suffix not in params:
            raise ValueError(
                f"unknown efficientnet variant: {cfg.name} (use "
                f"efficientnet-b0 .. efficientnet-b7)"
            )
        w, d = params[suffix]
        return FedModel(
            EfficientNet(nc, w, d), cfg.input_shape, has_batch_stats=True
        )
    if name == "lenet":
        from fedml_tpu.models.vision_extra import LeNet

        return FedModel(LeNet(nc), cfg.input_shape)
    if name in ("rnn", "char_lstm"):  # shakespeare
        return FedModel(
            CharLSTM(vocab_size=extra.get("vocab_size", 90)),
            cfg.input_shape,
            input_dtype=jnp.int32,
        )
    if name in ("rnn_stackoverflow", "nwp_lstm"):
        return FedModel(
            NWPLSTM(vocab_size=extra.get("vocab_size", 10004)),
            cfg.input_shape,
            input_dtype=jnp.int32,
        )
    if name in ("tag_lr", "stackoverflow_lr"):
        return FedModel(TagLogisticRegression(nc), cfg.input_shape)
    if name in ("transformer", "transformer_lm"):
        from fedml_tpu.models.transformer import TransformerLM

        # vocab defaults to num_classes so the CLI's --num_classes is
        # sufficient for token datasets (an under-sized embed table
        # silently corrupts every out-of-range lookup)
        return FedModel(
            TransformerLM(
                vocab_size=extra.get("vocab_size", nc),
                num_layers=extra.get("num_layers", 2),
                num_heads=extra.get("num_heads", 4),
                embed_dim=extra.get("embed_dim", 128),
                max_len=extra.get("max_len", 512),
            ),
            cfg.input_shape,
            input_dtype=jnp.int32,
        )
    if name in ("deeplab", "deeplab_lite"):  # fedseg (FedSegAPI.py:19)
        from fedml_tpu.models.segmentation import DeepLabLite

        return FedModel(
            DeepLabLite(
                nc,
                encoder_features=extra.get("encoder_features", (32, 64, 128)),
            ),
            cfg.input_shape,
            has_batch_stats=True,
        )
    raise ValueError(f"unknown model: {cfg.name}")

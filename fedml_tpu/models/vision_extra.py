"""Model zoo extensions: MobileNetV3, EfficientNet, LeNet.

References: ``fedml_api/model/cv/mobilenet_v3.py:137`` (MobileNetV3 with
SE + h-swish bottlenecks), ``fedml_api/model/cv/efficientnet.py:138``
(EfficientNet with MBConv blocks, ``:36``, and compound width/depth
scaling), ``fedml_api/model/mobile/lenet.py`` (the mobile LeNet used by the
MNN converter path).

TPU notes: NHWC; squeeze-excite is two tiny dense layers around a global
mean — XLA fuses it into the surrounding elementwise ops; h-swish is
``x * relu6(x + 3) / 6`` which lowers to a fused multiply.
"""

from __future__ import annotations

import math
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
from fedml_tpu.ops.cohort_conv import Conv2D


def hswish(x):
    return x * nn.relu6(x + 3.0) / 6.0


def hsigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


class SqueezeExcite(nn.Module):
    reduce: int = 4

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(c // self.reduce, 8))(s))
        s = hsigmoid(nn.Dense(c)(s))
        return x * s[:, None, None, :]


class MBConv(nn.Module):
    """Mobile inverted bottleneck (EfficientNet ``MBConvBlock``,
    ``efficientnet.py:36``; also the V3 bottleneck with SE)."""

    out_channels: int
    expand: int = 4
    kernel: int = 3
    stride: int = 1
    use_se: bool = True
    act: str = "swish"  # "swish" (EfficientNet) | "hswish" | "relu" (V3)

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = {"swish": nn.swish, "hswish": hswish, "relu": nn.relu}[self.act]
        cin = x.shape[-1]
        h = x
        mid = cin * self.expand
        if self.expand != 1:
            h = Conv2D(mid, (1, 1), use_bias=False)(h)
            h = nn.BatchNorm(use_running_average=not train)(h)
            h = act(h)
        h = Conv2D(
            mid, (self.kernel, self.kernel),
            strides=(self.stride, self.stride), padding="SAME",
            feature_group_count=mid, use_bias=False,
        )(h)
        h = nn.BatchNorm(use_running_average=not train)(h)
        h = act(h)
        if self.use_se:
            h = SqueezeExcite()(h)
        h = Conv2D(self.out_channels, (1, 1), use_bias=False)(h)
        h = nn.BatchNorm(use_running_average=not train)(h)
        if self.stride == 1 and cin == self.out_channels:
            h = h + x
        return h


class MobileNetV3(nn.Module):
    """MobileNetV3-small-style network (reference ``mobilenet_v3.py:137``;
    the full large config is a matter of the ``blocks`` table)."""

    num_classes: int = 10
    width_mult: float = 1.0
    # (out, expand, kernel, stride, use_se, act)
    blocks: Sequence[tuple] = (
        (16, 1, 3, 2, True, "relu"),
        (24, 4, 3, 2, False, "relu"),
        (24, 3, 3, 1, False, "relu"),
        (40, 3, 5, 2, True, "hswish"),
        (40, 3, 5, 1, True, "hswish"),
        (48, 3, 5, 1, True, "hswish"),
        (96, 6, 5, 2, True, "hswish"),
    )

    @nn.compact
    def __call__(self, x, train: bool = False):
        def c(ch):
            return max(8, int(ch * self.width_mult))

        h = Conv2D(c(16), (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False)(x)
        h = nn.BatchNorm(use_running_average=not train)(h)
        h = hswish(h)
        for out, exp, k, s, se, act in self.blocks:
            h = MBConv(c(out), exp, k, s, se, act)(h, train=train)
        h = Conv2D(c(288), (1, 1), use_bias=False)(h)
        h = nn.BatchNorm(use_running_average=not train)(h)
        h = hswish(h)
        h = jnp.mean(h, axis=(1, 2))
        h = hswish(nn.Dense(c(1024))(h))
        return nn.Dense(self.num_classes)(h)


class EfficientNet(nn.Module):
    """EfficientNet-B<k> via compound scaling (reference
    ``efficientnet.py:138`` + ``efficientnet_utils.py`` round_filters /
    round_repeats)."""

    num_classes: int = 10
    width_coef: float = 1.0
    depth_coef: float = 1.0
    # B0 stage table: (out, expand, kernel, stride, repeats)
    stages: Sequence[tuple] = (
        (16, 1, 3, 1, 1),
        (24, 6, 3, 2, 2),
        (40, 6, 5, 2, 2),
        (80, 6, 3, 2, 3),
        (112, 6, 5, 1, 3),
        (192, 6, 5, 2, 4),
        (320, 6, 3, 1, 1),
    )

    @nn.compact
    def __call__(self, x, train: bool = False):
        def width(ch):
            ch = ch * self.width_coef
            new = max(8, int(ch + 4) // 8 * 8)
            if new < 0.9 * ch:
                new += 8
            return int(new)

        def depth(r):
            return int(math.ceil(r * self.depth_coef))

        h = Conv2D(width(32), (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False)(x)
        h = nn.BatchNorm(use_running_average=not train)(h)
        h = nn.swish(h)
        for out, exp, k, s, reps in self.stages:
            for r in range(depth(reps)):
                h = MBConv(
                    width(out), exp, k, s if r == 0 else 1, True, "swish"
                )(h, train=train)
        h = Conv2D(width(1280), (1, 1), use_bias=False)(h)
        h = nn.BatchNorm(use_running_average=not train)(h)
        h = nn.swish(h)
        h = jnp.mean(h, axis=(1, 2))
        return nn.Dense(self.num_classes)(h)


class LeNet(nn.Module):
    """Mobile LeNet (reference ``fedml_api/model/mobile/lenet.py`` — the
    architecture shipped to the MNN mobile runtime)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = Conv2D(20, (5, 5))(x)
        h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = nn.relu(h)
        h = Conv2D(50, (5, 5))(h)
        h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = nn.relu(h)
        h = h.reshape((h.shape[0], -1))
        h = nn.relu(nn.Dense(500)(h))
        return nn.Dense(self.num_classes)(h)

"""Asynchronous + hierarchical aggregation actors.

Two new server-side shapes over the same message-passing runtime as
:mod:`fedml_tpu.algorithms.distributed_fedavg` (docs/FAULT_TOLERANCE.md
"Async + tiered worlds"):

- :class:`AsyncFedAvgServerActor` — the FedBuff-style buffered-async
  server (ROADMAP item 1a): every arriving screened delta folds into a
  staleness-weighted :class:`~fedml_tpu.core.async_agg.AsyncBuffer`
  tagged with the model VERSION it trained against, a new model emits
  every ``--async_buffer_k`` arrivals through the unchanged
  ``server_update`` body, and the sender is re-synced INDIVIDUALLY the
  moment its result lands — no round barrier, a slow client never
  blocks a fast one (Server Averaging for FL, arxiv 2103.11619).
- :class:`TierAggregatorActor` (leaf) + :class:`TierRootActor` /
  :class:`AsyncTierRootActor` (root) — the multi-tier aggregator tree
  (ROADMAP item 1b; the Smart-NIC partial-reduction shape, arxiv
  2307.06561): each leaf terminates its clients' transports in its own
  deployment world, runs decompress -> validate -> clip -> partial-sum
  near the wire reusing the PR 7 codec and the receive-edge screens,
  and forwards ONE typed ``[sum, n, count]`` partial per flush
  upstream; the root folds one row per leaf through the same
  ``server_update`` / ``DefensePipeline`` body, so the tree changes
  WHERE reduction happens, not what is computed. Each tier runs its
  own ``MembershipLedger``, ``LivenessMonitor``, and reputation scope
  — a leaf's Byzantine client is quarantined AT ITS LEAF and never
  pollutes a sibling leaf's (or the root's) reputation plane.

Both modes ride the existing sealed wire frames, checkpoint their
buffer/version state through ``RoundCheckpointer`` (a SIGKILLed async
root resumes its buffer, not just its params), and are strictly
opt-in: with ``--async_buffer_k 0`` and no ``--tier_spec`` the deploy
path constructs the untouched :class:`FedAvgServerActor` — the
synchronous world stays byte-identical (pinned in tests/test_async.py).
"""

from __future__ import annotations

import math
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import async_agg as AA
from fedml_tpu.core import compress as CMP
from fedml_tpu.core import telemetry
from fedml_tpu.core import tier as TIER
from fedml_tpu.core import tree as T
from fedml_tpu.core import random as RND
from fedml_tpu.core.manager import Manager
from fedml_tpu.core.message import (
    KEY_CLIENT_INDEX,
    KEY_MODEL_PARAMS,
    KEY_NUM_SAMPLES,
    KEY_ROUND,
    MSG_TYPE_C2S_RESULT,
    MSG_TYPE_L2R_PARTIAL,
    MSG_TYPE_S2C_SYNC_MODEL,
    Message,
)
from fedml_tpu.algorithms.distributed_fedavg import (
    FedAvgServerActor,
    _result_is_finite,
)
from fedml_tpu.algorithms.fedavg import local_reducer, server_update


def check_async_compat(cfg: ExperimentConfig) -> None:
    """Surface contradictions at construction/parse time, before a
    supervised server can crash-loop its restart budget away."""
    acfg = AA.AsyncConfig.from_fed(cfg.fed)
    if not acfg.enabled():
        return
    if cfg.fed.algorithm == "fednova":
        raise ValueError(
            "async_buffer_k is incompatible with fednova: the async "
            "emit is ONE staleness-folded row, but tau-normalization "
            "needs per-client step counts — run fednova synchronously"
        )
    if cfg.fed.shard_aggregation:
        raise ValueError(
            "async_buffer_k is incompatible with --shard_aggregation: "
            "the async emit aggregates one folded row — there is no "
            "client axis left to shard (the fan-out lives in the tier "
            "tree instead, --tier_spec)"
        )


class AsyncFedAvgServerActor(FedAvgServerActor):
    """Buffered-async rank-0 aggregator. Inherits the membership
    ledger, liveness routing, reputation plane, compression screens,
    and Byzantine defense body from :class:`FedAvgServerActor`;
    replaces the round BARRIER with per-arrival folds + per-K
    emissions. ``round_idx`` tracks the model VERSION (the emit
    counter) so every inherited helper that reads it — membership
    activation, WELCOME replay, summaries — keeps working."""

    def __init__(self, *args, checkpointer=None, **kwargs):
        # the base restore ties orbax steps to closed ROUNDS and
        # round-checks the restored counter; async steps are FOLDS and
        # the buffer rides the payload — so this subclass owns the
        # whole checkpoint story (see _restore_async below)
        super().__init__(*args, checkpointer=None, **kwargs)
        self._acfg = AA.AsyncConfig.from_fed(self.cfg.fed)
        if not self._acfg.enabled():
            raise ValueError(
                "AsyncFedAvgServerActor needs fed.async_buffer_k >= 1 "
                "(with 0, construct the synchronous FedAvgServerActor)"
            )
        check_async_compat(self.cfg)
        self._buffer = AA.AsyncBuffer(self._acfg, self.state.variables)
        # model snapshots per still-foldable version: a dense result is
        # a FULL variables tree, so its delta needs the exact model it
        # trained against (compressed results and tier partials carry
        # deltas and never consult the history)
        self._history_depth = max(8, 2 * self._acfg.buffer_k)
        self._history: dict[int, dict] = {}
        # (rank -> folded versions) dedup: chaos dup / WELCOME replay
        self._folded: dict[int, set[int]] = {}
        # FedBuff concurrency control: a member whose result already
        # went into the CURRENT version parks here (re-syncing it with
        # the same model would only provoke the same deterministic
        # result again); every emission drains the set
        self._parked: set[int] = set()
        self._folds = 0
        # orbax save step: strictly monotonic and DISTINCT from the
        # fold count — a forced emission must persist too, and saving
        # twice at one fold count would be a silent orbax no-op
        self._save_step = 0
        self.restored_folds = 0
        self._ckpt = checkpointer
        if checkpointer is not None:
            self._restore_async(checkpointer)

    def status(self) -> dict:
        """``/statusz``: the sync snapshot plus the async plane —
        buffer fold/version occupancy, parked members, restore state
        (docs/OBSERVABILITY.md "Live export and SLOs")."""
        st = super().status()
        with self._lock:
            st["async"] = {
                "buffer_k": self._acfg.buffer_k,
                "buffer_count": self._buffer.count,
                "version": self._buffer.version,
                "folds": self._folds,
                "parked": sorted(self._parked),
                "restored_folds": self.restored_folds,
            }
        return st

    # -- checkpoint (docs/FAULT_TOLERANCE.md "Async + tiered worlds") ------

    def _restore_async(self, ckpt) -> None:
        """Composite restore with async semantics: orbax steps are
        FOLD counts (monotonic across emissions), the ``"async"``
        payload carries the buffer mid-accumulation, and
        ``resumed_from`` reports the restored VERSION. A pre-async
        checkpoint (no ``"async"`` key) restores params and starts the
        buffer empty."""
        from fedml_tpu.utils.checkpoint import from_savable

        raw, start = ckpt.restore_raw()
        if raw is None:
            return
        if not (isinstance(raw, dict) and "server" in raw):
            raise ValueError(
                "async server found a non-composite checkpoint in its "
                "run dir — wrong run directory? (the async path always "
                "writes {'server', ..., 'async'} composites)"
            )
        self.state = from_savable(self.state, raw["server"])
        if "reputation" in raw:
            self._reputation.load_arrays(raw["reputation"])
        if "membership" in raw:
            self._ledger.load_arrays(raw["membership"])
        if "async" in raw:
            self._buffer.load_arrays(raw["async"])
            self.restored_folds = self._buffer.count
        else:
            self._buffer.version = int(self.state.round)
            import warnings

            warnings.warn(
                "restored a pre-async checkpoint (no buffer payload); "
                "the staleness buffer starts empty",
                stacklevel=2,
            )
        self._folds = 0  # cadence restarts; the SAVE step must not
        self._save_step = start
        self.round_idx = self._buffer.version
        self.resumed_from = self._buffer.version
        telemetry.METRICS.inc("recovery.resumes")
        telemetry.METRICS.gauge("recovery.resumed_from_round",
                                self.resumed_from)
        telemetry.METRICS.gauge("recovery.async_buffer_restored",
                                self.restored_folds)
        telemetry.RECORDER.record(
            "resume", round=self.resumed_from, mode="async",
            buffer_count=self.restored_folds,
        )

    def _save_checkpoint(self) -> None:
        if self._ckpt is None:
            return
        self._save_step += 1
        self._ckpt.save(self._save_step, {
            "server": self.state,
            "reputation": self._reputation.state_arrays(),
            "membership": self._ledger.state_arrays(),
            "async": self._buffer.state_arrays(),
        })
        telemetry.METRICS.inc("recovery.checkpoints")
        telemetry.RECORDER.record("checkpoint", round=self.round_idx,
                                  folds=self._folds)
        telemetry.flush_metrics()

    # -- version bookkeeping -----------------------------------------------

    def _assignment(self, rank: int) -> int:
        """Async cohort assignment: every member trains its
        ledger-stable client id every version (there is no sampled
        round cohort to deal out — the open loop IS the cohort)."""
        return self._ledger.client_id(rank)

    def _stash_sync_locked(self, host_vars) -> None:
        """Refresh the WELCOME-replay snapshot + dense-delta history
        for the current version. Caller holds ``self._lock``."""
        members = self._member_workers()
        cohort = np.asarray(
            [self._assignment(r) for r in members]
            or [0], np.int32,
        )
        slots = {r: i for i, r in enumerate(members)}
        self._round_sync = (self.round_idx, host_vars, cohort, slots)
        self._history[self.round_idx] = host_vars
        floor = self.round_idx - self._history_depth
        for v in [v for v in self._history if v < floor]:
            del self._history[v]
        for r, seen in self._folded.items():
            self._folded[r] = {v for v in seen if v >= floor}

    def start_round(self) -> None:
        """Kick off (or resume) the open loop: broadcast the current
        version to every live member. Called once at the readiness
        barrier — afterward the loop is arrival-driven (per-sender
        resyncs), never re-broadcast."""
        if self.round_idx >= self.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
            return
        self._round_t0 = time.monotonic()
        host_vars = jax.tree.map(np.asarray, self.variables)
        with self._lock:
            self._stash_sync_locked(host_vars)
            ranks = self._live_workers()
            # --round_deadline becomes a PROGRESS deadline in the
            # async world: with heartbeats off there is no other
            # backstop, and an accepted-but-inert flag would revive
            # the crashed-client-wedges-the-world hang PR 1 removed
            self._arm_progress_deadline_locked()
        self.broadcast(
            MSG_TYPE_S2C_SYNC_MODEL,
            lambda r: {
                KEY_MODEL_PARAMS: host_vars,
                KEY_CLIENT_INDEX: self._assignment(r),
                KEY_ROUND: self.round_idx,
            },
            ranks=ranks,
            on_send_error=self._on_sync_send_failed,
        )

    def _resync(self, rank: int) -> None:
        """The async contract's core move: the instant a member's
        result is handled, IT ALONE is synced with the current model —
        fast clients loop fast, slow clients loop slow, nobody
        waits."""
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            sync = self._round_sync
        if sync is None:
            return
        version, host_vars = sync[0], sync[1]
        try:
            self.send_message(Message(
                MSG_TYPE_S2C_SYNC_MODEL, self.rank, rank,
                {
                    KEY_MODEL_PARAMS: host_vars,
                    KEY_CLIENT_INDEX: self._assignment(rank),
                    KEY_ROUND: version,
                },
            ))
        except Exception:
            self.on_peer_dead(rank)

    def on_peer_join(self, rank: int) -> str | None:
        verdict = super().on_peer_join(rank)
        if verdict == "admitted":
            # no next-round broadcast will ever cover a mid-run
            # admission — serve it the current version immediately
            # (there is no in-flight quorum an admission could skew)
            self._resync(rank)
        return verdict

    # -- the arrival path --------------------------------------------------

    def _handle_result(self, msg: Message) -> None:
        n_raw = msg.get(KEY_NUM_SAMPLES)
        msg_round = msg.get(KEY_ROUND)
        sender = msg.sender
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            if sender in self.dead_peers:
                return
            if self._ledger.status(sender) == "evicted":
                return
            if msg_round is None:
                return  # async results are always version-tagged
            v = int(msg_round)
            if v in self._folded.get(sender, ()):
                telemetry.METRICS.inc("round.duplicate_results")
                return
            version = self.round_idx
        lag = version - v
        if lag < 0:
            # a version from the future: config skew or a corrupted
            # tag — unusable either way
            telemetry.METRICS.inc("async.too_stale")
            self._resync(sender)
            return
        n_k = float(n_raw) if n_raw is not None else float("nan")
        delta = None
        if self._cspec.enabled():
            payload = self._screen_compressed(msg)
            if payload is not None and math.isfinite(n_k):
                # compressed payloads ARE deltas: decompression needs
                # only the shapes, never the historical model
                delta = CMP.decompress_tree(
                    self._cspec, payload, self.state.variables
                )
            elif payload is not None:
                telemetry.METRICS.inc("robust.nonfinite_rejected")
        else:
            params = msg.get(KEY_MODEL_PARAMS)
            if params is not None and _result_is_finite(params, n_k):
                base = self._history.get(v)
                if base is None:
                    # the model it trained against aged out of the
                    # history ring: the delta is unrecoverable —
                    # folded it would be garbage, so count + drop
                    # (the resync below puts the client back to work)
                    telemetry.METRICS.inc("async.too_stale")
                else:
                    delta = jax.tree.map(
                        lambda p, b: jnp.asarray(p) - jnp.asarray(b),
                        params, base,
                    )
            elif params is not None:
                telemetry.METRICS.inc("robust.nonfinite_rejected")
                telemetry.RECORDER.record(
                    "nonfinite_rejected", peer=sender, round=v,
                )
        if delta is not None:
            self._fold(sender, delta, n_k, v, lag)
        self._after_result(sender, v)

    def _fold(self, sender: int, delta, n_k: float, v: int,
              lag: int) -> None:
        """Screened delta -> defense-preprocess -> staleness-weighted
        fold -> maybe emit. The fold is the only stateful step and
        runs under the server lock (arrivals are serialized by the
        dispatch thread anyway; the lock also fences LEAVE/evict)."""
        m = telemetry.METRICS
        if self._reputation.is_quarantined(sender):
            # quarantined ranks stay served (they can earn back in a
            # sync world); in the async world their folds are simply
            # excluded — the ban rides the restored checkpoint
            m.inc("defense.excluded")
            return
        # per-arrival defense preprocessing (clip) — the "(decompressed,
        # screened, defense-preprocessed) delta" of the contract; the
        # emit re-applies postprocess/noise on the aggregate
        clipped = jax.tree.map(
            lambda x: x[0],
            self._pipeline.preprocess(
                jax.tree.map(lambda x: x[None], delta)
            ),
        )
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            self._folded.setdefault(sender, set()).add(v)
            w = self._buffer.fold(clipped, n_k, lag)
            self._folds += 1
            folds = self._folds
            if m.enabled:
                m.inc("async.folds")
                m.gauge("async.buffer_depth", self._buffer.count)
                m.gauge("async.staleness", lag)
                m.gauge("async.staleness_weight", w)
                if lag > 0:
                    m.inc("async.stale_folds")
        if not self._maybe_emit() and (
                folds % self.checkpoint_every == 0):
            self._save_checkpoint()

    def _arm_progress_deadline_locked(self) -> None:
        """(Re-)arm the async progress watchdog — the round deadline's
        meaning here: every configured window must see an EMISSION.
        Caller holds ``self._lock``. Generation-stamped exactly like
        the base's round timers (cancel() cannot stop a timer whose
        callback is already blocked on the lock)."""
        if self.round_policy.round_deadline_s is None:
            return
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        self._deadline_gen += 1
        t = threading.Timer(
            self.round_policy.round_deadline_s,
            self._on_progress_deadline,
            args=(self._deadline_gen,),
        )
        t.daemon = True
        self._deadline_timer = t
        t.start()

    def _on_progress_deadline(self, gen: int) -> None:
        """No emission for a whole deadline window: force out whatever
        the buffer holds (progress beats a wedged world), or — with an
        empty buffer — abort loudly: whoever was supposed to fill it
        is gone, and without heartbeats this watchdog is the only
        thing standing between the run and an infinite hang."""
        with self._lock:
            if (self.done.is_set() or self.failure is not None
                    or gen != self._deadline_gen):
                return
            pending = self._buffer.count
            if not pending:
                self.failure = (
                    f"no emission within the "
                    f"{self.round_policy.round_deadline_s}s progress "
                    f"deadline at version {self.round_idx} with an "
                    f"empty buffer (members "
                    f"{self._member_workers()}, dead peers "
                    f"{sorted(self.dead_peers)}, parked "
                    f"{sorted(self._parked)})"
                )
        if pending:
            telemetry.METRICS.inc("async.forced_emits")
            self._maybe_emit(force=True)  # re-arms the watchdog itself
            return
        telemetry.METRICS.inc("round.quorum_lost_aborts")
        telemetry.flight_dump(
            "quorum_lost", detail=self.failure, round=self.round_idx,
        )
        self.finish_all()

    def _maybe_emit(self, force: bool = False) -> bool:
        """Emit when the buffer holds K folds (``force``: any folds —
        the stalled-world safety valve), then run the post-emit
        protocol: checkpoint, completion check, and the re-sync of
        every parked member with the NEW version."""
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return False
            if not (self._buffer.ready()
                    or (force and self._buffer.count > 0)):
                return False
            self._emit_locked()
            self._arm_progress_deadline_locked()
            parked = sorted(self._parked)
            self._parked.clear()
        self._save_checkpoint()
        if self.on_round_done is not None:
            self.on_round_done(self.round_idx, {"async": True})
        if self.round_idx >= self.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
            return True
        for r in parked:
            self._resync(r)
        return True

    def _after_result(self, sender: int, v: int) -> None:
        """Route the sender after its result was handled: a member
        whose contribution (or unusable attempt) was for the CURRENT
        version parks until the next emission — its model has not
        changed, so putting it back to work would only reproduce the
        same bytes; a member behind the current version goes straight
        back to work on the new model. This is what 'a slow client
        never blocks a fast one' costs: fast movers fill the buffer,
        parked movers wait out exactly one emission."""
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            park = v >= self.round_idx
            if park:
                self._parked.add(sender)
        if park:
            self._recover_if_stalled()
        else:
            self._resync(sender)

    def _recover_if_stalled(self) -> None:
        """Liveness valve: when EVERY live member is parked, no future
        arrival can complete the buffer — emit what is pending (a
        short emission beats a wedged world; counted
        ``async.forced_emits``), or abort loudly when even the buffer
        is empty (every member's current-version result was screened
        out; a deterministic retry cannot fix that)."""
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            if self._round_sync is None:
                return  # pre-kickoff arrivals park until the barrier
            live = self._live_workers()
            if not live or any(r not in self._parked for r in live):
                return
            pending = self._buffer.count
            if not pending:
                self.failure = (
                    f"async world stalled at version {self.round_idx}: "
                    f"every live member ({live}) is parked and the "
                    f"buffer is empty (all current-version results "
                    f"were screened out)"
                )
        if pending:
            telemetry.METRICS.inc("async.forced_emits")
            self._maybe_emit(force=True)
            return
        telemetry.METRICS.inc("round.quorum_lost_aborts")
        telemetry.flight_dump(
            "async_stalled", detail=self.failure, round=self.round_idx,
        )
        self.finish_all()

    def _emit_locked(self) -> None:
        """Drain the buffer into one ``server_update`` step (the same
        body every synchronous path runs, so the server rule cannot
        drift) and advance the version. Caller holds ``self._lock``."""
        mean_delta, mass = self._buffer.emit()
        row = jax.tree.map(
            lambda g, d: (g + d.astype(g.dtype))[None],
            self.state.variables, mean_delta,
        )
        rkey = RND.round_key(self.root_key, self.state.round)
        self.state = server_update(
            self.cfg.fed,
            self.cfg.train,
            self.steps_per_epoch,
            self.batch_size,
            self.state,
            row,
            jnp.asarray([mass]),
            rkey,
            local_reducer(),
        )
        self.round_idx = self._buffer.version
        telemetry.METRICS.inc("async.emits")
        telemetry.RECORDER.record(
            "async_emit", version=self.round_idx, mass=float(mass),
        )
        self._stash_sync_locked(
            jax.tree.map(np.asarray, self.state.variables)
        )

    # -- inherited-protocol adjustments ------------------------------------

    def _maybe_close_round(self, deadline_fired: bool,
                           deadline_round=None, deadline_gen=None
                           ) -> None:
        """There is no round to close — this inherited entry (LEAVE /
        evict / dead-peer) only has to keep the loudness contract: a
        world with NO live member left can never emit again, so abort
        instead of idling forever."""
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            if self._round_sync is None:
                return  # pre-kickoff departure replay
            alive = bool(self._live_workers())
            if not alive:
                self.failure = (
                    f"no live workers left at version {self.round_idx} "
                    f"({len(self._member_workers())} members, dead "
                    f"peers {sorted(self.dead_peers)})"
                )
        if alive:
            # the departed/dead member may have been the only UNPARKED
            # one — re-evaluate the stall valve over the survivors
            self._recover_if_stalled()
            return
        telemetry.METRICS.inc("round.quorum_lost_aborts")
        telemetry.flight_dump(
            "quorum_lost", detail=self.failure, round=self.round_idx,
        )
        self.finish_all()


# ---------------------------------------------------------------------------
# tier actors
# ---------------------------------------------------------------------------


class TierAggregatorActor(FedAvgServerActor):
    """LEAF aggregator: rank 0 of its own leaf deployment world
    (terminating its clients' transports) and a member rank of the
    root world (the ``uplink``). Inherits the WHOLE server-side client
    protocol — readiness barrier, ledger, liveness, straggler rounds,
    receive-edge screens, compressed-round decompression, per-leaf
    reputation/quarantine — and replaces the aggregation tail: a
    closed round becomes one clipped partial ``[sum, n, count]``
    forwarded upstream instead of a local ``server_update``. The model
    it serves its clients is whatever the LAST root sync carried; the
    root alone owns optimizer state and versions."""

    def __init__(self, size: int, transport, uplink: Manager, model,
                 cfg: ExperimentConfig, *, client_base: int = 0,
                 **kwargs):
        kwargs.pop("checkpointer", None)  # the ROOT owns durability
        super().__init__(size, transport, model, cfg,
                         checkpointer=None, **kwargs)
        self._uplink = uplink
        self._client_base = int(client_base)
        self.partials_sent = 0
        self.root_finished = threading.Event()
        # clip near the wire, once per client row (jitted per cohort
        # count — leaf cohorts are small and churn via the quorum
        # machinery, so the cache stays tiny)
        self._partial_fn = jax.jit(self._partial_sum)
        uplink.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self.on_root_sync
        )
        from fedml_tpu.core.message import (
            MSG_TYPE_FINISH,
            MSG_TYPE_S2C_WELCOME,
        )

        uplink.register_message_receive_handler(
            MSG_TYPE_S2C_WELCOME, self.on_root_sync
        )
        uplink.register_message_receive_handler(
            MSG_TYPE_FINISH, self.on_root_finish
        )

    def status(self) -> dict:
        st = super().status()
        st["tier"] = {
            "role": "leaf",
            "client_base": self._client_base,
            "partials_sent": self.partials_sent,
        }
        return st

    def _sample(self) -> np.ndarray:
        """A leaf's clients train a contiguous block of global client
        ids anchored at ``client_base`` — sibling leaves cover
        disjoint shards by construction (core/tier.py)."""
        n = max(1, len(self._member_workers()))
        return (self._client_base + np.arange(n)) % self.num_clients

    # -- root-facing protocol ----------------------------------------------

    def on_root_sync(self, msg: Message) -> None:
        """A root sync (or WELCOME replay) opens leaf round VERSION:
        adopt the model, then run the inherited round machinery over
        this leaf's clients. A duplicate sync for the version already
        in flight only refreshes nothing (clients are mid-update); a
        sync for an already-flushed version RE-RUNS it — the root only
        re-serves a version when its partial was lost with a dead
        incarnation."""
        version = int(msg.get(KEY_ROUND))
        variables = jax.tree.map(jnp.asarray,
                                 msg.get(KEY_MODEL_PARAMS))
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            sync = self._round_sync
            if (sync is not None and sync[0] == version
                    and self.round_idx == version):
                return  # duplicate of the in-flight version
            # adopt the root's model as this leaf's serving state so
            # every inherited consumer — the ``variables`` property,
            # compressed-round decompression, the anomaly scorer's
            # global reference — reads the tier model
            self.state = self.state._replace(variables=variables)
            self.round_idx = version
        self.start_round()

    def on_root_finish(self, msg: Message) -> None:
        self.root_finished.set()
        self.done.set()
        self.finish_all()  # FINISH this leaf's clients, stop downlink
        self._uplink.finish()

    # -- aggregation tail --------------------------------------------------

    @staticmethod
    def _partial_sum(stacked_deltas, weights):
        return jax.tree.map(
            lambda d: jnp.tensordot(
                weights.astype(d.dtype), d, axes=1
            ),
            stacked_deltas,
        )

    def _close_round(self, results, closed_idx, n_live=None,
                     dead=None) -> None:
        """Decompress -> score/exclude (per-LEAF reputation) -> clip
        -> partial-sum -> one frame upstream. No local server_update,
        no checkpoint (the root owns both), no next round (the next
        root sync opens it)."""
        tr = telemetry.TRACER
        if tr is not None:
            tr.log_round_end(closed_idx)
        m = telemetry.METRICS
        stacked_all = None
        if self._cspec.enabled() and results:
            stacked_all = self._decompress_results(results)
        included, stacked = self._score_and_exclude(
            results, closed_idx, stacked_all
        )
        if stacked is None:
            if stacked_all is not None:
                ranks = sorted(results)
                keep = jnp.asarray(
                    [ranks.index(r) for r in included], jnp.int32
                )
                stacked = jax.tree.map(lambda x: x[keep], stacked_all)
            else:
                stacked = T.tree_stack(
                    [results[r][0] for r in included]
                )
        weights = jnp.asarray(
            [results[r][1] for r in included], jnp.float32
        )
        gvars = self.variables
        deltas = jax.tree.map(
            lambda s, g: jnp.asarray(s) - g[None], stacked, gvars
        )
        clipped = self._pipeline.preprocess(deltas)
        psum = self._partial_fn(clipped, weights)
        n_total = float(weights.sum())
        payload = TIER.build_partial(psum, n_total, len(included))
        nbytes = sum(
            a.nbytes for a in jax.tree.leaves(payload[TIER.KEY_TIER_SUM])
        )
        self.partials_sent += 1
        if m.enabled:
            m.inc("tier.partial_sums")
            m.inc("tier.leaf_rounds")
            m.inc("tier.forward_bytes", nbytes)
            m.gauge("round.results", len(results))
        telemetry.RECORDER.record(
            "tier_partial", version=closed_idx, clients=len(included),
            n=n_total,
        )
        try:
            self._uplink.send_message(Message(
                MSG_TYPE_L2R_PARTIAL, self._uplink.rank, 0,
                {
                    **payload,
                    KEY_NUM_SAMPLES: n_total,
                    KEY_ROUND: closed_idx,
                },
            ))
        except Exception:
            # root unreachable: the uplink liveness watchdog owns the
            # verdict; this version's partial is simply lost and the
            # root's straggler machinery absorbs it
            telemetry.METRICS.inc("tier.partial_send_failures")


class _PartialRootMixin:
    """Shared root-side partial handling: receive-edge validation +
    conversion of ``[sum, n, count]`` into the delta the fold/round
    body consumes. Mixed into both root flavors so the sync and async
    trees cannot drift on the wire contract."""

    def _init_partial_plane(self, tier_spec: TIER.TierSpec) -> None:
        self.tier_spec = tier_spec
        # partials ride the leaf->root edge DENSE by design (one frame
        # per flush amortizes the wire); the client->leaf codec is the
        # leaves' business — neutralize the inherited compressed-result
        # plane so the C2S_RESULT screens never misfire at the root
        self._cspec = CMP.CompressionSpec()
        self._payload_template = None
        self._decomp_cache = None
        self.register_message_receive_handler(
            MSG_TYPE_L2R_PARTIAL, self._handle_partial
        )
        # a stray client wired straight at the root is a topology
        # error; its dense result must not silently join the leaves'
        # partials
        self.register_message_receive_handler(
            MSG_TYPE_C2S_RESULT, self._reject_direct_result
        )

    def status(self) -> dict:
        st = super().status()
        st["tier"] = {
            "role": "root",
            "n_leaves": self.tier_spec.n_leaves,
            "partials_folded": telemetry.METRICS.counter(
                "tier.partial_sums"
            ),
            "partials_rejected": telemetry.METRICS.counter(
                "tier.partial_rejected"
            ),
        }
        return st

    def _reject_direct_result(self, msg: Message) -> None:
        telemetry.METRICS.inc("tier.direct_results_rejected")
        telemetry.RECORDER.record(
            "tier_direct_result_rejected", peer=msg.sender,
            round=msg.get(KEY_ROUND),
        )

    def _screen_partial(self, msg: Message):
        """Validate one partial at the receive edge; returns
        ``(mean_delta_tree, n_total)`` or None (counted + dropped)."""
        n_raw = msg.get(KEY_NUM_SAMPLES)
        n_total = float(n_raw) if n_raw is not None else float("nan")
        err = TIER.validate_partial(self.state.variables, msg.payload,
                                    n_total)
        if err is not None:
            telemetry.METRICS.inc("tier.partial_rejected")
            telemetry.RECORDER.record(
                "tier_partial_rejected", peer=msg.sender,
                round=msg.get(KEY_ROUND), detail=err,
            )
            return None
        inv = 1.0 / n_total
        mean_delta = jax.tree.map(
            lambda s: np.asarray(s) * inv, msg.get(TIER.KEY_TIER_SUM)
        )
        telemetry.METRICS.inc("tier.partial_sums")
        return mean_delta, n_total


class TierRootActor(_PartialRootMixin, FedAvgServerActor):
    """Synchronous tier root: the unchanged round machinery
    (quorum/deadline/defense/reputation/checkpoint) where each
    "worker" is a LEAF and each booked result is its partial turned
    into one weighted row ``global + sum/n``. The weighted mean over
    leaf rows reproduces the flat world's weighted mean over all
    clients exactly (core/tier.py); the defense rule and the
    reputation plane operate at leaf granularity — the root's
    per-tier scope."""

    def __init__(self, *args, tier_spec: TIER.TierSpec, **kwargs):
        super().__init__(*args, **kwargs)
        self._init_partial_plane(tier_spec)

    def _handle_partial(self, msg: Message) -> None:
        with self._lock:
            if self._discard_locked(msg):
                return
        screened = self._screen_partial(msg)
        if screened is None:
            return
        mean_delta, n_total = screened
        with self._lock:
            if self._discard_locked(msg):
                return
            sync = self._round_sync
            if sync is None or sync[0] != self.round_idx:
                return
            host_vars = sync[1]
            # one row per leaf against the ROUND's model snapshot: the
            # inherited close recovers exactly sum/n as this leaf's
            # delta
            row = jax.tree.map(
                lambda g, d: g + d.astype(g.dtype), host_vars,
                mean_delta,
            )
            self._results[msg.sender] = (row, n_total)
        self._maybe_close_round(deadline_fired=False)


class AsyncTierRootActor(_PartialRootMixin, AsyncFedAvgServerActor):
    """Asynchronous tier root: leaf partials fold into the staleness
    buffer the moment they land (a partial CARRIES its delta, so even
    a partial older than the history ring stays foldable), the leaf is
    re-synced individually, and the model emits every K partials — the
    fully barrier-free tree of ROADMAP item 1."""

    def __init__(self, *args, tier_spec: TIER.TierSpec, **kwargs):
        super().__init__(*args, **kwargs)
        self._init_partial_plane(tier_spec)

    def _handle_partial(self, msg: Message) -> None:
        sender = msg.sender
        msg_round = msg.get(KEY_ROUND)
        with self._lock:
            if self.done.is_set() or self.failure is not None:
                return
            if sender in self.dead_peers:
                return
            if self._ledger.status(sender) == "evicted":
                return
            if msg_round is None:
                return
            v = int(msg_round)
            if v in self._folded.get(sender, ()):
                telemetry.METRICS.inc("round.duplicate_results")
                return
            version = self.round_idx
        lag = version - v
        if lag < 0:
            telemetry.METRICS.inc("async.too_stale")
            self._resync(sender)
            return
        screened = self._screen_partial(msg)
        if screened is not None:
            mean_delta, n_total = screened
            delta = jax.tree.map(jnp.asarray, mean_delta)
            self._fold(sender, delta, n_total, v, lag)
        self._after_result(sender, v)

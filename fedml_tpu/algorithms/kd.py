"""Knowledge-distillation losses.

Pure-function equivalents of the reference's top-level
``knowledge_distillation`` package: ``SoftTarget`` (Hinton KL with T^2
scaling, ``knowledge_distillation/soft_target.py:5-19``) and ``Logits``
(MSE on raw logits, ``knowledge_distillation/logits.py:10-17``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def soft_target(student_logits, teacher_logits, T: float = 4.0, w=None):
    """T^2-scaled KL(softmax(t/T) || softmax(s/T)), batch-mean.

    Matches ``F.kl_div(log_softmax(s/T), softmax(t/T),
    reduction='batchmean') * T * T`` (``soft_target.py:15-19``):
    batchmean divides by the batch size only, summing over classes.
    ``w`` optionally masks padded rows (the masked mean divides by the
    number of REAL rows, exactly the torch batchmean over the real batch).
    """
    log_p_s = jax.nn.log_softmax(student_logits / T, axis=-1)
    p_t = jax.nn.softmax(teacher_logits / T, axis=-1)
    log_p_t = jax.nn.log_softmax(teacher_logits / T, axis=-1)
    per_row = jnp.sum(p_t * (log_p_t - log_p_s), axis=-1)
    if w is None:
        return jnp.mean(per_row) * T * T
    return jnp.sum(per_row * w) / jnp.maximum(jnp.sum(w), 1.0) * T * T


def logits_mse(student_logits, teacher_logits, w=None):
    """Plain MSE on logits (``logits.py:14-17``)."""
    per_row = jnp.mean(
        jnp.square(student_logits - teacher_logits), axis=-1
    )
    if w is None:
        return jnp.mean(per_row)
    return jnp.sum(per_row * w) / jnp.maximum(jnp.sum(w), 1.0)

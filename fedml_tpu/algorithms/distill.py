"""Distillation-based FL without generators: FedMD, FD (+FAug), FedArjun.

Reference semantics (all compiled here into single-program rounds with the
cohort vmapped):

- **FedMD** (``fedml_api/standalone/fedmd/``): a public dataset assembled
  from client data shares (``FedMD_api.py:31-47``, ``client.py:27-33``);
  per round each client computes logits on the public set, the server
  averages them into a consensus, and each client runs *digest* (CE +
  ``kd_lambda`` * logits-MSE toward the consensus on public data,
  ``model_trainer.py:50-77``) then *revisit* (CE on private data). Clients
  pre-train on public then private data (``model_trainer.py:21-48``).
- **FD + FAug** (``fedml_api/standalone/fd_faug/``): federated distillation
  via per-LABEL average logits. During local training each client
  accumulates label-wise mean logits; the server exchanges leave-one-out
  global label averages (``FD_FAug_api.py:99-138``); the client regularizes
  with ``(1-kd_gamma)*CE + kd_gamma*CE(output, softmax(teacher[label]))``
  (``model_trainer.py:46-68``). (FAug's shared-GAN augmentation is a TODO
  in the reference — ``FD_FAug_api.py:100-101`` — the GAN path here is
  available separately via :mod:`fedml_tpu.algorithms.gan_family`.)
- **FedArjun** (``fedml_api/standalone/federated_arjun/``): each client
  holds a FedAvg-shared *adapter* model + a private local model; per round
  1) KD adapter->local, 2) train local, 3) KD local->adapter
  (``model_trainer.py:38-76``); only adapters are aggregated. KD loss is
  ``(1-kd_lambda)*CE + kd_lambda*SoftTarget(T=4)``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms import kd as KD
from fedml_tpu.algorithms.base import (
    build_evaluator,
    build_local_update,
    make_client_optimizer,
    make_task,
)
from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core import tree as T
from fedml_tpu.data.federated import FederatedArrays, FederatedData, arrays_and_batch
from fedml_tpu.models.base import FedModel

Pytree = Any


from fedml_tpu.algorithms.stack_utils import (
    evaluate_stack as _evaluate_stack,
    stack_gather as _gather,
    stack_scatter as _scatter,
    vmap_init as _vmap_init,
)


def build_public_set(
    data: FederatedData, public_size: int, batch_size: int, seed: int
) -> tuple[np.ndarray, np.ndarray]:
    """Assemble the FedMD public set from equal client shares (reference
    ``share_data``, ``fedmd/client.py:27-33``: each client contributes a
    random ``share_percentage`` of its local data)."""
    rng = np.random.default_rng(seed)
    n_clients = data.num_clients
    public_size = max(
        batch_size, (public_size // batch_size) * batch_size
    )
    per_client = -(-public_size // n_clients)  # ceil
    picks = []
    for i in range(n_clients):
        idx = data.train_idx_map[i]
        take = min(per_client, len(idx))
        picks.append(rng.choice(idx, take, replace=False))
    picked = np.concatenate(picks)
    if len(picked) < public_size:  # top up with yet-unpicked global samples
        pool = np.setdiff1d(np.arange(len(data.x_train)), picked)
        extra = rng.choice(
            pool, min(len(pool), public_size - len(picked)), replace=False
        )
        picked = np.concatenate([picked, extra])
    if len(picked) < public_size:  # degenerate tiny datasets: repeat
        reps = rng.choice(picked, public_size - len(picked), replace=True)
        picked = np.concatenate([picked, reps])
    picked = picked[:public_size]
    return data.x_train[picked], data.y_train[picked]


def _build_supervised_kd_loop(
    model: FedModel, opt, size: int, batch_size: int, mode: str,
    kd_weight: float,
):
    """Scan-based epochs over a fixed (public) set with an optional
    teacher-logits alignment term. ``mode``: "mse" (FedMD digest) or
    "none" (plain CE)."""
    assert size % batch_size == 0
    n_batches = size // batch_size

    def loss_fn(params, static, xb, yb, tb, rng):
        variables = {**static, "params": params}
        logits, new_vars = model.apply_train(variables, xb, rng)
        ce = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        )
        if mode == "mse":
            # digest: CE + kd_lambda * MSE(out, consensus)
            # (fedmd/model_trainer.py:67-74,119-124)
            loss = ce + kd_weight * KD.logits_mse(logits, tb)
        else:
            loss = ce
        return loss, new_vars

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def run(variables, x, y, teacher, rng, epochs: int):
        opt_state = opt.init(variables["params"])

        def epoch_body(carry, ekey):
            variables, opt_state = carry

            def step(carry2, i):
                variables, opt_state = carry2
                sl = lambda a: jax.lax.dynamic_slice_in_dim(
                    a, i * batch_size, batch_size
                )
                params = variables["params"]
                static = {k: v for k, v in variables.items() if k != "params"}
                (_, new_vars), grads = grad_fn(
                    params, static, sl(x), sl(y),
                    sl(teacher) if teacher is not None else None,
                    jax.random.fold_in(ekey, i),
                )
                updates, new_os = opt.update(grads, opt_state, params)
                new_vars = {
                    **new_vars,
                    "params": optax.apply_updates(params, updates),
                }
                return (new_vars, new_os), None

            carry2, _ = jax.lax.scan(
                step, (variables, opt_state), jnp.arange(n_batches)
            )
            return carry2, None

        ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
            jnp.arange(epochs)
        )
        (variables, _), _ = jax.lax.scan(
            epoch_body, (variables, opt_state), ekeys
        )
        return variables

    return run


class FedMDState(NamedTuple):
    model_stack: Pytree  # [N, ...] per-client (stateful) models
    round: jax.Array


class FedMDSim:
    """FedMD: logit-consensus distillation on a shared public dataset."""

    def __init__(
        self, model: FedModel, data: FederatedData, cfg: ExperimentConfig
    ):
        self.model, self.cfg = model, cfg
        self.task = make_task(data.task)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        max_n = self.arrays.max_client_samples

        px, py = build_public_set(
            data, cfg.gan.public_size, self.batch_size, cfg.data.seed
        )
        self.pub_x = jnp.asarray(px, jnp.float32)
        self.pub_y = jnp.asarray(py)
        self.pub_size = self.pub_x.shape[0]
        opt = make_client_optimizer(cfg.train)
        self.digest = _build_supervised_kd_loop(
            model, opt, self.pub_size, self.batch_size, "mse",
            cfg.gan.kd_lambda,
        )
        self.pub_train = _build_supervised_kd_loop(
            model, opt, self.pub_size, self.batch_size, "none", 0.0
        )
        self.local_update = build_local_update(
            model, self.task, cfg.train, self.batch_size, max_n
        )
        # private pretraining honors its own epoch count
        # (fedmd/model_trainer.py:46-48 pretrain_epochs_private)
        import dataclasses as _dc

        self.pretrain_local = build_local_update(
            model, self.task,
            _dc.replace(cfg.train, epochs=max(1, cfg.gan.pretrain_epochs_private)),
            self.batch_size, max_n,
        )
        # revisit = exactly revisit_epochs epochs of private CE with ONE
        # optimizer lifetime (fedmd/model_trainer.py:76-77) — not
        # revisit_epochs repetitions of a train.epochs-epoch run
        self.revisit_update = build_local_update(
            model, self.task,
            _dc.replace(cfg.train, epochs=max(1, cfg.gan.revisit_epochs)),
            self.batch_size, max_n,
        )
        n_b = self.pub_size // self.batch_size

        def extract(variables):
            def body(_, i):
                xb = jax.lax.dynamic_slice_in_dim(
                    self.pub_x, i * self.batch_size, self.batch_size
                )
                return None, model.apply_eval(variables, xb)

            _, out = jax.lax.scan(body, None, jnp.arange(n_b))
            return out.reshape((self.pub_size, -1))

        self.extract = extract
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))
        self._pretrain_fn = jax.jit(self._pretrain)

    # -- phases -------------------------------------------------------------
    def _pretrain(self, stack, arrays: FederatedArrays):
        """Transfer learning: public then private (``model_trainer.py:21-48``)."""
        n = arrays.num_clients
        keys = jax.vmap(
            lambda i: jax.random.fold_in(self.root_key, 0xBEEF + i)
        )(jnp.arange(n))
        g = self.cfg.gan
        stack = jax.vmap(
            lambda v, k: self.pub_train(
                v, self.pub_x, self.pub_y, None, k, g.pretrain_epochs_public
            )
        )(stack, keys)
        stack, _, _ = jax.vmap(
            self.pretrain_local, in_axes=(0, 0, 0, None, None, 0)
        )(stack, arrays.idx, arrays.mask, arrays.x, arrays.y, keys)
        return stack

    def _round(self, state: FedMDState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        g = self.cfg.gan
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        mvars = _gather(state.model_stack, cohort)

        # 1. communicate: logits on public set; 2. aggregate: mean consensus
        #    (FedMD_api.py:82-96)
        logits = jax.vmap(self.extract)(mvars)  # [C, P, K]
        consensus = jnp.mean(logits, axis=0)  # [P, K]

        # 3. digest (toward consensus) + revisit (private CE)
        #    (FedMD_api.py:98-103, model_trainer.py:50-77)
        mvars = jax.vmap(
            lambda v, k: self.digest(
                v, self.pub_x, self.pub_y, consensus,
                jax.random.fold_in(k, 1), g.digest_epochs,
            )
        )(mvars, ckeys)
        mvars, _, msums = jax.vmap(
            self.revisit_update, in_axes=(0, 0, 0, None, None, 0)
        )(
            mvars, arrays.idx[cohort], arrays.mask[cohort],
            arrays.x, arrays.y,
            jax.vmap(lambda k: jax.random.fold_in(k, 2))(ckeys),
        )

        new_stack = _scatter(state.model_stack, cohort, mvars)
        reduced = jax.tree.map(jnp.sum, msums)
        return (
            FedMDState(new_stack, state.round + 1),
            {
                "train_loss": reduced["loss_sum"]
                / jnp.maximum(reduced["w_sum"], 1.0)
            },
        )

    # -- public API ---------------------------------------------------------
    def init(self, pretrain: bool = True) -> FedMDState:
        stack = _vmap_init(
            self.model.init,
            jax.random.fold_in(self.root_key, 0x7FFFFFFF),
            self.arrays.num_clients,
        )
        if pretrain:
            stack = self._pretrain_fn(stack, self.arrays)
        return FedMDState(stack, jnp.asarray(0, jnp.int32))

    def run_round(self, state: FedMDState):
        return self._round_fn(state, self.arrays)

    def evaluate_clients(self, state: FedMDState) -> dict:
        return _evaluate_stack(
            self.evaluator, state.model_stack, self.arrays.test_x,
            self.arrays.test_y, self.arrays.num_clients,
        )


class FDState(NamedTuple):
    model_stack: Pytree  # [N, ...]
    teacher: jax.Array  # [N, K, K] per-client per-label teacher logits
    has_teacher: jax.Array  # [N, K] bool — teacher available PER LABEL
    round: jax.Array


class FDSim:
    """FD (federated distillation via label-averaged logits), the FD half of
    FD+FAug. One round = local training with the soft per-label teacher +
    leave-one-out label-logit exchange."""

    def __init__(
        self, model: FedModel, data: FederatedData, cfg: ExperimentConfig
    ):
        self.model, self.cfg = model, cfg
        self.task = make_task(data.task)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.num_classes = self.arrays.num_classes
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self.local_update = self._build_local_update()
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _build_local_update(self):
        model, cfg_t = self.model, self.cfg.train
        K = self.num_classes
        batch_size, max_n = self.batch_size, self.max_n
        steps_per_epoch = max_n // batch_size
        kd_gamma = self.cfg.gan.kd_gamma
        opt = make_client_optimizer(cfg_t)

        def loss_fn(params, static, xb, yb, wb, teacher, use_t, rng):
            """``use_t`` is a per-LABEL availability mask [K]: a sample only
            gets the KD term if some OTHER client has contributed logits for
            its label — without this, labels unique to this client would be
            distilled toward softmax(zeros) = uniform."""
            variables = {**static, "params": params}
            logits, new_vars = model.apply_train(variables, xb, rng)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            # soft-label co-distillation (fd_faug/model_trainer.py:62-68):
            # CE against softmax of the global per-label average logits
            t_rows = teacher[yb]  # [B, K]
            soft = jax.nn.softmax(t_rows, axis=-1)
            kd_ce = optax.softmax_cross_entropy(logits, soft)
            gamma = kd_gamma * use_t[yb]  # [B] per-sample gate
            per_row = (1 - gamma) * ce + gamma * kd_ce
            loss = jnp.sum(per_row * wb) / jnp.maximum(jnp.sum(wb), 1.0)
            return loss, (new_vars, logits)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def update(variables, idx_row, mask_row, x, y, teacher, use_t, rng):
            opt_state = opt.init(variables["params"])
            lab_sum0 = jnp.zeros((K, K))
            lab_cnt0 = jnp.zeros((K,))

            def epoch_body(carry, ekey):
                variables, opt_state, lab_sum, lab_cnt = carry
                perm = jax.random.permutation(ekey, max_n)
                order = jnp.argsort(1.0 - mask_row[perm], stable=True)
                perm = perm[order]

                def step(carry2, s):
                    variables, opt_state, lab_sum, lab_cnt = carry2
                    take = jax.lax.dynamic_slice_in_dim(
                        perm, s * batch_size, batch_size
                    )
                    b_idx = idx_row[take]
                    wb = mask_row[take]
                    xb = jnp.take(x, b_idx, axis=0)
                    yb = jnp.take(y, b_idx, axis=0)
                    params = variables["params"]
                    static = {
                        k: v for k, v in variables.items() if k != "params"
                    }
                    (_, (new_vars, logits)), grads = grad_fn(
                        params, static, xb, yb, wb, teacher, use_t,
                        jax.random.fold_in(ekey, s),
                    )
                    updates, new_os = opt.update(grads, opt_state, params)
                    new_vars = {
                        **new_vars,
                        "params": optax.apply_updates(params, updates),
                    }
                    valid = jnp.sum(wb) > 0
                    sel = lambda a, b: jax.tree.map(
                        lambda p, q: jnp.where(valid, p, q), a, b
                    )
                    # accumulate per-label logit sums (model_trainer.py:46-47)
                    lab_sum = lab_sum.at[yb].add(logits * wb[:, None])
                    lab_cnt = lab_cnt.at[yb].add(wb)
                    return (
                        sel(new_vars, variables), sel(new_os, opt_state),
                        lab_sum, lab_cnt,
                    ), None

                carry2, _ = jax.lax.scan(
                    step, (variables, opt_state, lab_sum, lab_cnt),
                    jnp.arange(steps_per_epoch),
                )
                return carry2, None

            ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
                jnp.arange(cfg_t.epochs)
            )
            (variables, _, lab_sum, lab_cnt), _ = jax.lax.scan(
                epoch_body, (variables, opt_state, lab_sum0, lab_cnt0), ekeys
            )
            # per-label AVERAGE logits for the exchange
            lab_avg = lab_sum / jnp.maximum(lab_cnt, 1.0)[:, None]
            return variables, lab_avg, lab_cnt, jnp.sum(mask_row)

        return update

    def init(self) -> FDState:
        n = self.arrays.num_clients
        K = self.num_classes
        return FDState(
            model_stack=_vmap_init(
                self.model.init,
                jax.random.fold_in(self.root_key, 0x7FFFFFFF), n,
            ),
            teacher=jnp.zeros((n, K, K)),
            has_teacher=jnp.zeros((n, K), bool),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FDState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        mvars = _gather(state.model_stack, cohort)

        mvars, lab_avg, lab_cnt, _ = jax.vmap(
            self.local_update, in_axes=(0, 0, 0, None, None, 0, 0, 0)
        )(
            mvars, arrays.idx[cohort], arrays.mask[cohort], arrays.x,
            arrays.y, state.teacher[cohort], state.has_teacher[cohort],
            ckeys,
        )

        # leave-one-out global label averages (FD_FAug_api.py:126-138):
        # teacher_i[l] = (sum_j avg_j[l] - avg_i[l]) / (M - 1) over
        # contributors that saw label l
        seen = (lab_cnt > 0).astype(jnp.float32)  # [C, K]
        tot_sum = jnp.sum(lab_avg * seen[..., None], axis=0)  # [K, K]
        tot_m = jnp.sum(seen, axis=0)  # [K]
        m_other = jnp.maximum(tot_m[None] - seen, 1.0)  # [C, K]
        loo = (tot_sum[None] - lab_avg * seen[..., None]) / m_other[..., None]
        have = (tot_m[None] - seen) > 0  # [C, K] some other client saw l

        new_teacher = state.teacher.at[cohort].set(
            jnp.where(have[..., None], loo, state.teacher[cohort])
        )
        new_has = state.has_teacher.at[cohort].set(
            jnp.logical_or(state.has_teacher[cohort], have)
        )
        new_state = FDState(
            _scatter(state.model_stack, cohort, mvars),
            new_teacher, new_has, state.round + 1,
        )
        return new_state, {}

    def run_round(self, state: FDState):
        return self._round_fn(state, self.arrays)

    def evaluate_clients(self, state: FDState) -> dict:
        return _evaluate_stack(
            self.evaluator, state.model_stack, self.arrays.test_x,
            self.arrays.test_y, self.arrays.num_clients,
        )


class FedArjunState(NamedTuple):
    adapter_vars: Pytree  # global FedAvg-shared adapter
    local_stack: Pytree  # [N, ...] private local models
    round: jax.Array


class FedArjunSim:
    """FedArjun: shared adapter + private local model with bidirectional KD
    (``federated_arjun/model_trainer.py:38-76``)."""

    def __init__(
        self,
        adapter: FedModel,
        local: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        self.adapter, self.local, self.cfg = adapter, local, cfg
        self.task = make_task(data.task)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.local_train = build_local_update(
            local, self.task, cfg.train, self.batch_size, self.max_n
        )
        self.evaluator = build_evaluator(local, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self.kd_transfer = self._build_kd_transfer()
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def _build_kd_transfer(self):
        """KD over the client's own (padded) data: student learns from a
        frozen teacher; returns the updated student."""
        cfg_t, g = self.cfg.train, self.cfg.gan
        batch_size, max_n = self.batch_size, self.max_n
        steps = max_n // batch_size
        opt = make_client_optimizer(cfg_t)

        def run(student: FedModel, teacher: FedModel):
            def loss_fn(params, static, t_vars, xb, yb, wb, rng):
                variables = {**static, "params": params}
                s_logits, new_vars = student.apply_train(variables, xb, rng)
                t_logits = jax.lax.stop_gradient(
                    teacher.apply_eval(t_vars, xb)
                )
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    s_logits, yb
                )
                ce = jnp.sum(ce * wb) / jnp.maximum(jnp.sum(wb), 1.0)
                kd_l = KD.soft_target(
                    s_logits, t_logits, g.kd_temperature, w=wb
                )
                loss = (1 - g.kd_lambda) * ce + g.kd_lambda * kd_l
                return loss, new_vars

            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

            def transfer(s_vars, t_vars, idx_row, mask_row, x, y, rng):
                opt_state = opt.init(s_vars["params"])

                def epoch_body(carry, ekey):
                    variables, opt_state = carry
                    perm = jax.random.permutation(ekey, max_n)
                    order = jnp.argsort(1.0 - mask_row[perm], stable=True)
                    perm = perm[order]

                    def step(carry2, s):
                        variables, opt_state = carry2
                        take = jax.lax.dynamic_slice_in_dim(
                            perm, s * batch_size, batch_size
                        )
                        b_idx = idx_row[take]
                        wb = mask_row[take]
                        xb = jnp.take(x, b_idx, axis=0)
                        yb = jnp.take(y, b_idx, axis=0)
                        params = variables["params"]
                        static = {
                            k: v
                            for k, v in variables.items()
                            if k != "params"
                        }
                        (_, new_vars), grads = grad_fn(
                            params, static, t_vars, xb, yb, wb,
                            jax.random.fold_in(ekey, s),
                        )
                        updates, new_os = opt.update(
                            grads, opt_state, params
                        )
                        new_vars = {
                            **new_vars,
                            "params": optax.apply_updates(params, updates),
                        }
                        valid = jnp.sum(wb) > 0
                        sel = lambda a, b: jax.tree.map(
                            lambda p, q: jnp.where(valid, p, q), a, b
                        )
                        return (
                            sel(new_vars, variables),
                            sel(new_os, opt_state),
                        ), None

                    carry2, _ = jax.lax.scan(
                        step, (variables, opt_state), jnp.arange(steps)
                    )
                    return carry2, None

                ekeys = jax.vmap(lambda e: jax.random.fold_in(rng, e))(
                    jnp.arange(max(g.kd_epochs, 1))
                )
                (s_vars, _), _ = jax.lax.scan(
                    epoch_body, (s_vars, opt_state), ekeys
                )
                return s_vars

            return transfer

        return {
            "a2l": run(self.local, self.adapter),
            "l2a": run(self.adapter, self.local),
        }

    def init(self) -> FedArjunState:
        k = jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        ka, kl = jax.random.split(k)
        return FedArjunState(
            adapter_vars=self.adapter.init(ka),
            local_stack=_vmap_init(
                self.local.init, kl, self.arrays.num_clients
            ),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: FedArjunState, arrays: FederatedArrays):
        cfg = self.cfg.fed
        rkey = R.round_key(self.root_key, state.round)
        cohort = R.sample_clients(
            jax.random.fold_in(rkey, 0), arrays.num_clients,
            cfg.clients_per_round,
        )
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(cohort)
        l_vars = _gather(state.local_stack, cohort)
        idx_rows = arrays.idx[cohort]
        mask_rows = arrays.mask[cohort]

        # 1. adapter -> local KD (model_trainer.py:64-66)
        l_vars = jax.vmap(
            self.kd_transfer["a2l"],
            in_axes=(0, None, 0, 0, None, None, 0),
        )(l_vars, state.adapter_vars, idx_rows, mask_rows, arrays.x,
          arrays.y, ckeys)

        # 2. train local on private data (:71)
        l_vars, n_k, _ = jax.vmap(
            self.local_train, in_axes=(0, 0, 0, None, None, 0)
        )(
            l_vars, idx_rows, mask_rows, arrays.x, arrays.y,
            jax.vmap(lambda k: jax.random.fold_in(k, 1))(ckeys),
        )

        # 3. local -> adapter KD, then FedAvg adapters (:74-76)
        a_stack = jax.vmap(
            self.kd_transfer["l2a"],
            in_axes=(None, 0, 0, 0, None, None, 0),
        )(state.adapter_vars, l_vars, idx_rows, mask_rows, arrays.x,
          arrays.y,
          jax.vmap(lambda k: jax.random.fold_in(k, 2))(ckeys))
        new_adapter = T.tree_weighted_mean(a_stack, n_k)

        return (
            FedArjunState(
                new_adapter,
                _scatter(state.local_stack, cohort, l_vars),
                state.round + 1,
            ),
            {},
        )

    def run_round(self, state: FedArjunState):
        return self._round_fn(state, self.arrays)

    def evaluate_clients(self, state: FedArjunState) -> dict:
        return _evaluate_stack(
            self.evaluator, state.local_stack, self.arrays.test_x,
            self.arrays.test_y, self.arrays.num_clients,
        )

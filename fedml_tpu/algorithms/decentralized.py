"""Decentralized (serverless) FL: DSGD and push-sum gossip.

Redesign of the reference's decentralized stack
(``fedml_api/standalone/decentralized/``: ``ClientDSGD``
(``client_dsgd.py:6``), ``ClientPushsum`` (``client_pushsum.py``), driven by
``FedML_decentralized_fl`` (``decentralized_fl_api.py:20``)) and the
decentralized message-passing scaffold
(``fedml_api/distributed/decentralized_framework``).

TPU formulation: every client's params live in one stacked pytree
``[N, ...]``; one gossip round is

1. vmapped local SGD on each client's own data, then
2. mixing: ``theta' = W @ theta`` per leaf — a single [N,N]x[N,P] matmul
   (MXU) instead of N x deg point-to-point sends.

Push-sum additionally carries the scalar weight vector ``w`` mixed by the
same matrix, with estimates ``x = theta / w`` (directed-graph consensus).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core.topology import SymmetricTopologyManager
from fedml_tpu.data.federated import FederatedData, arrays_and_batch
from fedml_tpu.algorithms.base import (
    build_evaluator,
    build_local_update,
    finalize_sums,
    make_task,
)
from fedml_tpu.models.base import FedModel

Pytree = Any


class DecentralizedState(NamedTuple):
    stacked_vars: Pytree  # [N, ...] per-client model variables
    push_weights: jax.Array  # [N] push-sum scalar weights
    round: jax.Array


class DecentralizedSim:
    """DSGD / push-sum over a fixed mixing topology."""

    def __init__(
        self,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
        topology: SymmetricTopologyManager | None = None,
        method: str = "dsgd",  # "dsgd" | "pushsum"
    ):
        assert method in ("dsgd", "pushsum")
        self.model = model
        self.cfg = cfg
        self.method = method
        self.task = make_task(data.task)
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        n = self.arrays.num_clients
        topology = topology or SymmetricTopologyManager(n, neighbor_num=2)
        self.W = jnp.asarray(topology.mixing_matrix(), jnp.float32)
        # Push-sum requires a COLUMN-stochastic mixing matrix (mass each node
        # pushes out sums to 1) so that sum(w) is conserved and the w-vector
        # actually tracks the stationary bias; the row-stochastic W used for
        # DSGD would leave w == ones and degenerate push-sum into DSGD.
        self.P = self.W / jnp.maximum(self.W.sum(axis=0, keepdims=True), 1e-12)
        max_n = self.arrays.max_client_samples
        self.local_update = build_local_update(
            model, self.task, cfg.train, self.batch_size, max_n
        )
        self.evaluator = build_evaluator(model, self.task)
        self.root_key = jax.random.key(cfg.seed)
        self._round_fn = jax.jit(self._round, donate_argnums=(0,))

    def init(self) -> DecentralizedState:
        n = self.arrays.num_clients
        variables = self.model.init(
            jax.random.fold_in(self.root_key, 0x7FFFFFFF)
        )
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), variables
        )
        return DecentralizedState(
            stacked_vars=stacked,
            push_weights=jnp.ones((n,)),
            round=jnp.asarray(0, jnp.int32),
        )

    def _round(self, state: DecentralizedState, arrays):
        n = arrays.num_clients
        rkey = R.round_key(self.root_key, state.round)
        ckeys = jax.vmap(lambda c: R.client_key(rkey, c))(jnp.arange(n))

        def scale(tree_, s):
            return jax.tree.map(
                lambda l: l * s.reshape((n,) + (1,) * (l.ndim - 1)), tree_
            )

        if self.method == "pushsum":
            # SGP (stochastic gradient push): train the de-biased estimate
            # z = x/w, re-bias, then gossip x and w with the same matrix.
            z = scale(state.stacked_vars, 1.0 / state.push_weights.clip(1e-8))
        else:
            z = state.stacked_vars

        new_z, _, msums = jax.vmap(
            self.local_update, in_axes=(0, 0, 0, None, None, 0)
        )(z, arrays.idx, arrays.mask, arrays.x, arrays.y, ckeys)

        if self.method == "pushsum":
            biased = scale(new_z, state.push_weights)
            new_w = self.P @ state.push_weights
            mix_mat = self.P
        else:
            biased = new_z
            new_w = state.push_weights
            mix_mat = self.W

        # gossip mixing: one matmul per leaf over the client axis
        def mix(leaf):
            flat = leaf.reshape(n, -1)
            return (mix_mat @ flat).reshape(leaf.shape)

        mixed = jax.tree.map(mix, biased)

        reduced = jax.tree.map(jnp.sum, msums)
        fin = finalize_sums(reduced)
        return (
            DecentralizedState(mixed, new_w, state.round + 1),
            {"train_loss": fin["loss"], "train_acc": fin["acc"]},
        )

    def run_round(self, state):
        return self._round_fn(state, self.arrays)

    def _debiased(self, state: DecentralizedState) -> Pytree:
        n = self.arrays.num_clients
        w = state.push_weights.clip(1e-8)
        return jax.tree.map(
            lambda l: l / w.reshape((n,) + (1,) * (l.ndim - 1)),
            state.stacked_vars,
        )

    def evaluate_consensus(self, state: DecentralizedState) -> dict:
        """Evaluate the client-average (de-biased) model on the test set."""
        est = self._debiased(state)
        avg = jax.tree.map(lambda l: jnp.mean(l, axis=0), est)
        m = self.evaluator(avg, self.arrays.test_x, self.arrays.test_y)
        return {k: float(v) for k, v in m.items()}

    def consensus_distance(self, state: DecentralizedState) -> float:
        """Mean squared distance of clients from the mean model — the
        convergence diagnostic for gossip methods."""
        est = self._debiased(state)
        avg = jax.tree.map(lambda l: jnp.mean(l, axis=0), est)
        sq = jax.tree.map(lambda l, a: jnp.sum((l - a[None]) ** 2), est, avg)
        return float(jax.tree.reduce(jnp.add, sq) / state.push_weights.shape[0])


# ---------------------------------------------------------------------------
# Decentralized ONLINE learning (streaming, regret metric)
# ---------------------------------------------------------------------------


class OnlineDecentralizedSim:
    """Decentralized online learning on a sample stream with cumulative
    regret — the reference's actual DOL setting
    (``decentralized_fl_api.py:12-17``: SUSY / room-occupancy streams,
    ``cal_regret`` = sum of per-iteration losses / (N*(t+1));
    ``ClientDSGD.train`` (``client_dsgd.py:54-73``): grad at the current
    estimate z_t on sample t, x = z - lr*g, gossip-mix x, z = x;
    ``ClientPushsum`` additionally mixes the omega mass with a
    column-stochastic matrix and de-biases z = x/omega, with optional
    time-varying topology re-drawn each iteration).

    TPU formulation: the WHOLE T-iteration protocol is one ``lax.scan``;
    each iteration is a vmapped per-client grad on that client's t-th
    sample + one [N,N]x[N,P] mixing matmul. Binary logistic model (the
    reference's ``LogisticRegression`` + BCELoss), params stacked [N, d].
    """

    def __init__(
        self,
        stream_x,  # [N, T, d]
        stream_y,  # [N, T] in {0, 1}
        method: str = "dsgd",  # "dsgd" | "pushsum"
        topology: SymmetricTopologyManager | None = None,
        lr: float = 0.1,
        weight_decay: float = 0.0,
        time_varying: bool = False,
        seed: int = 0,
    ):
        assert method in ("dsgd", "pushsum")
        self.method = method
        self.lr = lr
        self.wd = weight_decay
        self.x = jnp.asarray(stream_x, jnp.float32)
        self.y = jnp.asarray(stream_y, jnp.float32)
        n, t = self.y.shape
        self.n, self.t = n, t
        if time_varying:
            # reference re-generates the topology each iteration with
            # seed=t (client_pushsum.py:63-72); matrices are tiny, so we
            # precompute the [T, N, N] stack host-side and scan over it
            mats = []
            for it in range(t):
                # extra random links make the draw actually depend on the
                # seed (a plain ring is seed-independent); the reference's
                # Watts-Strogatz topology re-draw has random rewiring too
                topo = SymmetricTopologyManager(
                    n, neighbor_num=2, extra_links=max(2, n // 4),
                    seed=seed + it,
                )
                mats.append(topo.mixing_matrix())
            W = jnp.asarray(np.stack(mats), jnp.float32)
        else:
            topo = topology or SymmetricTopologyManager(
                n, neighbor_num=2, seed=seed
            )
            W = jnp.broadcast_to(
                jnp.asarray(topo.mixing_matrix(), jnp.float32)[None],
                (t, n, n),
            )
        if method == "pushsum":
            # column-stochastic per matrix (mass each node pushes out sums
            # to 1) so omega tracks the stationary bias — same reasoning as
            # DecentralizedSim.P. NB: W is stacked [T, N, N]; mixing is
            # x'_i = sum_j W[t,i,j] x_j, so the COLUMN sum of matrix t is
            # the reduction over axis=1 (the output index), not axis=0
            # (which is the time axis here).
            W = W / jnp.maximum(W.sum(axis=1, keepdims=True), 1e-12)
        self.W = W

    # sink-logging cadence; the harness sets this from cfg.fed.eval_every
    log_every: int = 10

    def run(self, metrics_sink=None, log_every: int | None = None):
        """Run the full stream; returns a dict with the per-iteration loss
        matrix [T, N], the running average regret curve [T]
        (reference ``cal_regret``), and the final stacked params. When a
        ``metrics_sink`` is given, the regret curve is logged every
        ``log_every`` iterations plus one final record (exactly one record
        per logged round)."""
        n, t = self.n, self.t
        d = self.x.shape[-1]
        lr, wd = self.lr, self.wd

        def bce_loss(params, xi, yi):
            w, b = params
            logit = xi @ w + b
            # BCE on sigmoid output, matching torch BCELoss numerics via
            # the stable logit form
            return (
                jnp.maximum(logit, 0) - logit * yi
                + jnp.log1p(jnp.exp(-jnp.abs(logit)))
            )

        grad_fn = jax.vmap(jax.value_and_grad(bce_loss), in_axes=(0, 0, 0))

        def step(carry, inputs):
            z_w, z_b, omega = carry
            xi, yi, Wt = inputs  # [N,d], [N], [N,N]
            losses, (g_w, g_b) = grad_fn((z_w, z_b), xi, yi)
            if wd > 0:
                g_w = g_w + wd * z_w
            # x_{t+1/2} = z_t - lr * grad (client_dsgd.py:68-71)
            x_w = (z_w if self.method == "dsgd" else z_w * omega[:, None]) \
                - lr * g_w * (1.0 if self.method == "dsgd"
                              else omega[:, None])
            x_b = (z_b if self.method == "dsgd" else z_b * omega) - lr * g_b \
                * (1.0 if self.method == "dsgd" else omega)
            # gossip mixing: one matmul per leaf
            x_w = Wt @ x_w
            x_b = Wt @ x_b
            if self.method == "pushsum":
                omega = Wt @ omega
                z_w = x_w / omega[:, None].clip(1e-8)
                z_b = x_b / omega.clip(1e-8)
            else:
                z_w, z_b = x_w, x_b
            return (z_w, z_b, omega), losses

        init = (
            jnp.zeros((n, d)),
            jnp.zeros((n,)),
            jnp.ones((n,)),
        )
        xs = (
            jnp.swapaxes(self.x, 0, 1),  # [T, N, d]
            jnp.swapaxes(self.y, 0, 1),  # [T, N]
            self.W,  # [T, N, N]
        )
        (z_w, z_b, omega), losses = jax.jit(
            lambda init, xs: jax.lax.scan(step, init, xs)
        )(init, xs)
        # regret(t) = sum_{s<=t} sum_i loss_{s,i} / (N * (t+1))
        per_iter = losses.sum(axis=1)  # [T]
        regret = jnp.cumsum(per_iter) / (n * jnp.arange(1, t + 1))
        out = {
            "losses": losses,
            "regret": regret,
            "params": (z_w, z_b),
            "final_regret": float(regret[-1]),
        }
        if metrics_sink is not None:
            r_host = np.asarray(regret)
            step = max(
                1, int(self.log_every if log_every is None else log_every)
            )
            for it in range(step - 1, t - 1, step):
                metrics_sink.log(
                    {"round": it, "regret": float(r_host[it])}
                )
            metrics_sink.log(
                {"round": t - 1, "regret": float(r_host[-1]),
                 "final_regret": float(r_host[-1])}
            )
        return out

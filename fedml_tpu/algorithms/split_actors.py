"""Split-compute FL across a REAL transport boundary.

The compiled sims in :mod:`fedml_tpu.algorithms.split` run both halves of
each split algorithm inside one XLA program (joint autodiff across the
cut). These actors run the same math as two (or more) processes
exchanging :class:`~fedml_tpu.core.message.Message`s over any
``BaseTransport`` backend — the trust/process boundary the reference
deploys:

- **SplitNN** (``fedml_api/distributed/split_nn/client.py:24-34``,
  ``server.py:40-57``): every batch ships activations+labels up and the
  cut gradient back; clients take turns around the ring while the server
  weights persist.
- **FedGKT** (``fedml_api/distributed/fedgkt/GKTClientTrainer.py:50``):
  clients ship extracted feature maps + logits + labels; the server
  trains the upper trunk on the received banks and returns per-sample
  teacher logits.
- **Vertical FL**
  (``fedml_api/standalone/classical_vertical_fl/guest_trainer.py:10``,
  ``party_models.py``): hosts ship per-batch logit components; the guest
  (label owner) returns the common gradient d loss / d component.

Equality contract: every actor derives batch order, rng keys, optimizer
state, and update gating exactly as its compiled sim does, so a
loopback/gRPC run matches the sim to float round-off (the backward pass
across the cut is the same chain rule the joint autodiff executes) —
pinned per algorithm in ``tests/test_split_actors.py``.

All handlers are event-driven state machines (the transport drain is
single-threaded; a handler that blocked waiting for the reply would
deadlock the inbox).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import random as R
from fedml_tpu.core.manager import ClientManager, Manager, ServerManager
from fedml_tpu.core.message import MSG_TYPE_NAMES, Message
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.data.federated import FederatedData, arrays_and_batch
from fedml_tpu.algorithms.base import make_client_optimizer
from fedml_tpu.algorithms.split import kl_temperature

Pytree = Any

# message types (module-local space, like the reference's per-algorithm
# message_define.py files)
MSG_SNN_TURN = 100
MSG_SNN_ACTS = 101
MSG_SNN_GRADS = 102
MSG_SNN_EPOCH_DONE = 103

MSG_GKT_START = 110
MSG_GKT_FEATURES = 111

MSG_VFL_STEP = 120
MSG_VFL_COMPONENT = 121
MSG_VFL_GRAD = 122

# Register symbolic names so the per-type wire-byte counters
# (`transport.bytes_by_type.<name>`, docs/OBSERVABILITY.md) attribute
# split-compute traffic readably — without these rows the counters fall
# back to bare integers (`transport.bytes_by_type.101`), which is
# exactly what the fedlint message-edge rule flags: a wire-cost claim
# about activations vs gradients must be able to name them.
MSG_TYPE_NAMES.update({
    MSG_SNN_TURN: "snn_turn",
    MSG_SNN_ACTS: "snn_acts",
    MSG_SNN_GRADS: "snn_grads",
    MSG_SNN_EPOCH_DONE: "snn_epoch_done",
    MSG_GKT_START: "gkt_start",
    MSG_GKT_FEATURES: "gkt_features",
    MSG_VFL_STEP: "vfl_step",
    MSG_VFL_COMPONENT: "vfl_component",
    MSG_VFL_GRAD: "vfl_grad",
})


# ---------------------------------------------------------------------------
# SplitNN
# ---------------------------------------------------------------------------


class SplitNNServerActor(ServerManager):
    """Upper-trunk owner (reference ``split_nn/server.py``): receives
    activations+labels, answers with the cut gradient, steps its own
    optimizer, coordinates the ring."""

    def __init__(
        self,
        size: int,
        transport: BaseTransport,
        server_model,
        server_vars: Pytree,
        cfg: ExperimentConfig,
    ):
        super().__init__(0, size, transport)
        self.cfg = cfg
        self.server_model = server_model
        self.server_vars = server_vars
        self.s_opt = make_client_optimizer(cfg.train)
        self.server_opt_state = self.s_opt.init(server_vars["params"])
        self.round_idx = 0
        self._turn = 1  # rank whose epoch is running
        self.loss_sum = 0.0
        self.correct_sum = 0.0
        self.n_sum = 0.0
        self.metrics_history: list[dict] = []
        self.done = threading.Event()
        self.register_message_receive_handler(MSG_SNN_ACTS, self._on_acts)
        self.register_message_receive_handler(
            MSG_SNN_EPOCH_DONE, self._on_epoch_done
        )

        def server_step(s_vars, s_os, acts, yb, wb):
            """Identical math to SplitNNSim._round's server half: loss and
            grads w.r.t. (acts, server params), valid-gated update."""
            sp = s_vars["params"]
            s_static = {k: v for k, v in s_vars.items() if k != "params"}

            def f(acts, sp):
                logits = self.server_model.apply(
                    {**s_static, "params": sp}, acts, train=True
                )
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb
                )
                loss = jnp.sum(ce * wb) / jnp.maximum(jnp.sum(wb), 1.0)
                correct = jnp.sum(
                    (jnp.argmax(logits, -1) == yb).astype(jnp.float32) * wb
                )
                return loss, correct

            (loss, correct), (d_acts, sg) = jax.value_and_grad(
                f, argnums=(0, 1), has_aux=True
            )(acts, sp)
            su, new_os = self.s_opt.update(sg, s_os, sp)
            new_vars = {
                **s_vars, "params": optax.apply_updates(sp, su)
            }
            valid = jnp.sum(wb) > 0
            sel = lambda a, b: jax.tree.map(
                lambda p, q: jnp.where(valid, p, q), a, b
            )
            return (
                sel(new_vars, s_vars), sel(new_os, s_os), d_acts,
                jnp.where(valid, loss, 0.0), correct, jnp.sum(wb),
            )

        self._server_step = jax.jit(server_step)

    def start_round(self) -> None:
        self._turn = 1
        self.send_message(
            Message(MSG_SNN_TURN, 0, 1, {"round": self.round_idx})
        )

    def _on_acts(self, msg: Message) -> None:
        acts = jnp.asarray(msg.get("acts"))
        yb = jnp.asarray(msg.get("y"))
        wb = jnp.asarray(msg.get("w"))
        (self.server_vars, self.server_opt_state, d_acts, loss, correct,
         wsum) = self._server_step(
            self.server_vars, self.server_opt_state, acts, yb, wb
        )
        self.loss_sum += float(loss)
        self.correct_sum += float(correct)
        self.n_sum += float(wsum)
        self.send_message(
            Message(
                MSG_SNN_GRADS, 0, msg.sender,
                {"d_acts": np.asarray(d_acts)},
            )
        )

    def _on_epoch_done(self, msg: Message) -> None:
        if self._turn < self.size - 1:
            self._turn += 1
            self.send_message(
                Message(
                    MSG_SNN_TURN, 0, self._turn,
                    {"round": self.round_idx},
                )
            )
            return
        # ring complete: book metrics exactly like the sim
        n = self.size - 1
        steps = msg.get("steps")
        self.metrics_history.append(
            {
                "train_loss": self.loss_sum / (n * steps),
                "train_acc": self.correct_sum / max(self.n_sum, 1.0),
            }
        )
        self.loss_sum = self.correct_sum = self.n_sum = 0.0
        self.round_idx += 1
        if self.round_idx >= self.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
        else:
            self.start_round()


class SplitNNClientActor(ClientManager):
    """Lower-stack owner (reference ``split_nn/client.py``): forwards its
    batch through the local stack, ships activations, applies the
    returned cut gradient via the local vjp."""

    def __init__(
        self,
        rank: int,
        size: int,
        transport: BaseTransport,
        client_model,
        client_vars: Pytree,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        super().__init__(rank, size, transport)
        self.cfg = cfg
        self.client_model = client_model
        self.c_vars = client_vars
        self.arrays, self.batch_size = arrays_and_batch(data, cfg.data)
        self.max_n = self.arrays.max_client_samples
        self.steps = self.max_n // self.batch_size
        self.c_opt = make_client_optimizer(cfg.train)
        self.root_key = jax.random.key(cfg.seed)
        self.client_index = rank - 1
        self._step = 0
        self._opt_state = None
        self._xb = None
        self._wb = None
        self.register_message_receive_handler(MSG_SNN_TURN, self._on_turn)
        self.register_message_receive_handler(MSG_SNN_GRADS, self._on_grads)

        def batch_and_acts(c_vars, ckey, step):
            """The sim's exact batch order (perm from the round/client key,
            real-first stable sort), then the lower-stack forward."""
            idx_row = self.arrays.idx[self.client_index]
            mask_row = self.arrays.mask[self.client_index]
            perm = jax.random.permutation(ckey, self.max_n)
            order = jnp.argsort(1.0 - mask_row[perm], stable=True)
            take = jax.lax.dynamic_slice_in_dim(
                perm[order], step * self.batch_size, self.batch_size
            )
            b_idx = idx_row[take]
            wb = mask_row[take]
            xb = jnp.take(self.arrays.x, b_idx, axis=0)
            yb = jnp.take(self.arrays.y, b_idx, axis=0)
            acts = self.client_model.apply(c_vars, xb, train=True)
            return xb, yb, wb, acts

        def apply_cut_grads(c_vars, c_os, xb, wb, d_acts):
            """Client-side backward through the cut: vjp at the same
            point the forward used (chain rule == the sim's joint grad),
            valid-gated update like the sim."""
            cp = c_vars["params"]
            c_static = {k: v for k, v in c_vars.items() if k != "params"}
            _, vjp_fn = jax.vjp(
                lambda p: self.client_model.apply(
                    {**c_static, "params": p}, xb, train=True
                ),
                cp,
            )
            (cg,) = vjp_fn(d_acts)
            cu, new_os = self.c_opt.update(cg, c_os, cp)
            new_vars = {**c_vars, "params": optax.apply_updates(cp, cu)}
            valid = jnp.sum(wb) > 0
            sel = lambda a, b: jax.tree.map(
                lambda p, q: jnp.where(valid, p, q), a, b
            )
            return sel(new_vars, c_vars), sel(new_os, c_os)

        self._batch_and_acts = jax.jit(batch_and_acts)
        self._apply_cut_grads = jax.jit(apply_cut_grads)

    def _on_turn(self, msg: Message) -> None:
        rkey = R.round_key(self.root_key, jnp.asarray(msg.get("round")))
        self._ckey = R.client_key(rkey, self.client_index)
        self._opt_state = self.c_opt.init(self.c_vars["params"])
        self._step = 0
        self._send_acts()

    def _send_acts(self) -> None:
        xb, yb, wb, acts = self._batch_and_acts(
            self.c_vars, self._ckey, self._step
        )
        self._xb, self._wb = xb, wb
        self.send_message(
            Message(
                MSG_SNN_ACTS, self.rank, 0,
                {
                    "acts": np.asarray(acts),
                    "y": np.asarray(yb),
                    "w": np.asarray(wb),
                },
            )
        )

    def _on_grads(self, msg: Message) -> None:
        d_acts = jnp.asarray(msg.get("d_acts"))
        self.c_vars, self._opt_state = self._apply_cut_grads(
            self.c_vars, self._opt_state, self._xb, self._wb, d_acts
        )
        self._step += 1
        if self._step < self.steps:
            self._send_acts()
        else:
            self.send_message(
                Message(
                    MSG_SNN_EPOCH_DONE, self.rank, 0,
                    {"steps": self.steps},
                )
            )


# ---------------------------------------------------------------------------
# FedGKT
# ---------------------------------------------------------------------------


class GKTClientActor(ClientManager):
    """Edge trainer (reference ``GKTClientTrainer``): local CE(+KD) epochs
    on the lower stack, then ships extracted feature maps + local logits
    + labels for its samples (``GKTClientTrainer.py:50``)."""

    def __init__(
        self,
        rank: int,
        size: int,
        transport: BaseTransport,
        sim,  # FedGKTSim — the source of truth for the client-phase math
        client_vars: Pytree,
    ):
        super().__init__(rank, size, transport)
        self.sim = sim
        self.c_vars = client_vars
        self.client_index = rank - 1
        self.register_message_receive_handler(
            MSG_GKT_START, self._on_start
        )
        self._client_phase = jax.jit(sim._client_phase)

        def extract(c_vars):
            """Per-slot features/logits for this client's padded index
            row, batched exactly like the server pass batches (row-wise
            values are batch-invariant: eval-mode forward)."""
            arrays = self.sim.arrays
            idx_row = arrays.idx[self.client_index]
            bs = self.sim.batch_size

            def body(_, s):
                take = jax.lax.dynamic_slice_in_dim(idx_row, s * bs, bs)
                xb = jnp.take(arrays.x, take, axis=0)
                yb = jnp.take(arrays.y, take, axis=0)
                fb, lb = self.sim._client_apply_eval(c_vars, xb)
                return None, (fb, lb, yb)

            _, (f, l, y) = jax.lax.scan(
                body, None, jnp.arange(self.sim.max_n // bs)
            )
            flat = lambda a: a.reshape((-1,) + a.shape[2:])
            return flat(f), flat(l), flat(y)

        self._extract = jax.jit(extract)

    def _on_start(self, msg: Message) -> None:
        arrays = self.sim.arrays
        c = self.client_index
        rkey = R.round_key(self.sim.root_key, jnp.asarray(msg.get("round")))
        ckey = R.client_key(rkey, c)
        s_logits = jnp.asarray(msg.get("server_logits"))
        use_kd = jnp.asarray(msg.get("use_kd"))
        self.c_vars = self._client_phase(
            self.c_vars, arrays.idx[c], arrays.mask[c], arrays.x,
            arrays.y, s_logits, use_kd, ckey,
        )
        f, l, y = self._extract(self.c_vars)
        self.send_message(
            Message(
                MSG_GKT_FEATURES, self.rank, 0,
                {
                    "features": np.asarray(f),
                    "logits": np.asarray(l),
                    "labels": np.asarray(y),
                    "mask": np.asarray(arrays.mask[c]),
                },
            )
        )


class GKTServerActor(ServerManager):
    """Server trainer (reference ``GKTServerTrainer``): trains the upper
    trunk on the received feature banks (KD to client logits + CE), then
    returns per-sample teacher logits. Batch order matches the sim's
    server pass (same skey-derived perms), so numerics match the compiled
    FedGKTSim even though features arrive over the wire instead of being
    recomputed in-program."""

    def __init__(
        self,
        size: int,
        transport: BaseTransport,
        sim,  # FedGKTSim
        server_vars: Pytree,
    ):
        super().__init__(0, size, transport)
        self.sim = sim
        self.server_vars = server_vars
        self.server_opt_state = sim.s_opt.init(server_vars["params"])
        self.round_idx = 0
        self.done = threading.Event()
        self._banks: dict[int, dict] = {}
        # test/diagnostic hook: called with (round_idx, f_banks, l_banks,
        # y_banks) once per round, right before the server phase consumes
        # the assembled banks — lets equality tests pin the ACTOR-produced
        # banks against sim-produced banks per phase
        self.on_banks = None
        self.server_logits = jnp.zeros(
            (sim.n_total, sim.num_classes)
        )
        self.register_message_receive_handler(
            MSG_GKT_FEATURES, self._on_features
        )

        def server_phase(s_vars, s_os, f_banks, l_banks, y_banks, masks,
                         round_idx):
            """The sim's server training re-expressed over received banks:
            same loss, same per-epoch/client perms (skey), same gating.
            f_banks: [n, max_n, ...] per-slot features in idx-row order.
            ``round_idx`` is a traced argument so ONE jit serves every
            round (no per-round recompiles)."""
            cfg = self.sim.cfg
            bs = self.sim.batch_size
            steps = self.sim.max_n // bs
            rkey = R.round_key(self.sim.root_key, round_idx)
            skey = jax.random.fold_in(rkey, 0x5EAF)

            def s_loss_fn(params, static, fb, yb, tb, wb):
                variables = {**static, "params": params}
                out, new_vars = self.sim._server_apply_train(variables, fb)
                kd = kl_temperature(out, tb, self.sim.T, wb)
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    out, yb
                )
                ce = jnp.sum(ce * wb) / jnp.maximum(jnp.sum(wb), 1.0)
                return kd + self.sim.alpha * ce, new_vars

            s_grad = jax.value_and_grad(s_loss_fn, has_aux=True)

            def client_pass(carry, inputs):
                variables, opt_state = carry
                fbank, lbank, ybank, mask_row, ckey = inputs
                perm = jax.random.permutation(ckey, self.sim.max_n)
                order = jnp.argsort(1.0 - mask_row[perm], stable=True)
                perm = perm[order]

                def step(carry2, s):
                    variables, opt_state = carry2
                    take = jax.lax.dynamic_slice_in_dim(perm, s * bs, bs)
                    fb = jnp.take(fbank, take, axis=0)
                    tb = jnp.take(lbank, take, axis=0)
                    yb = jnp.take(ybank, take, axis=0)
                    wb = mask_row[take]
                    params = variables["params"]
                    static = {
                        k: v for k, v in variables.items()
                        if k != "params"
                    }
                    (_, new_vars), grads = s_grad(
                        params, static, fb, yb, tb, wb
                    )
                    updates, new_os = self.sim.s_opt.update(
                        grads, opt_state, params
                    )
                    new_vars = {
                        **new_vars,
                        "params": optax.apply_updates(params, updates),
                    }
                    valid = jnp.sum(wb) > 0
                    sel = lambda a, b: jax.tree.map(
                        lambda p, q: jnp.where(valid, p, q), a, b
                    )
                    return (
                        sel(new_vars, variables), sel(new_os, opt_state)
                    ), None

                carry2, _ = jax.lax.scan(
                    step, (variables, opt_state), jnp.arange(steps)
                )
                return carry2, None

            n = f_banks.shape[0]

            def s_epoch(carry, ekey):
                ckeys_e = jax.vmap(lambda c: jax.random.fold_in(ekey, c))(
                    jnp.arange(n)
                )
                carry, _ = jax.lax.scan(
                    client_pass, carry,
                    (f_banks, l_banks, y_banks, masks, ckeys_e),
                )
                return carry, None

            ekeys = jax.vmap(lambda e: jax.random.fold_in(skey, e))(
                jnp.arange(cfg.train.epochs)
            )
            (s_vars, s_os), _ = jax.lax.scan(
                s_epoch, (s_vars, s_os), ekeys
            )

            # teacher logits bank from the received features (sim step 4)
            def logits_client(bank, inputs):
                fbank, mask_row, idx_row = inputs

                def body(bank, s):
                    take = jax.lax.dynamic_slice_in_dim(
                        idx_row, s * bs, bs
                    )
                    fslot = jax.lax.dynamic_slice_in_dim(
                        fbank, s * bs, bs
                    )
                    wb = jax.lax.dynamic_slice_in_dim(
                        mask_row, s * bs, bs
                    )
                    out = self.sim._server_apply_eval(s_vars, fslot)
                    safe = jnp.where(
                        wb > 0, take, self.sim.n_total
                    ).astype(jnp.int32)
                    return bank.at[safe].set(out), None

                bank, _ = jax.lax.scan(
                    body, bank, jnp.arange(steps)
                )
                return bank, None

            bank0 = jnp.zeros(
                (self.sim.n_total + 1, self.sim.num_classes)
            )
            bank, _ = jax.lax.scan(
                logits_client, bank0,
                (f_banks, masks, self.sim.arrays.idx),
            )
            return s_vars, s_os, bank[: self.sim.n_total]

        self._server_phase = jax.jit(server_phase)

    def start_round(self) -> None:
        host_logits = np.asarray(self.server_logits)
        self.broadcast(
            MSG_GKT_START,
            lambda r: {
                "round": self.round_idx,
                "server_logits": host_logits,
                "use_kd": self.round_idx > 0,
            },
        )

    def _on_features(self, msg: Message) -> None:
        self._banks[msg.sender] = msg.payload
        if len(self._banks) < self.size - 1:
            return
        banks = [self._banks[r] for r in range(1, self.size)]
        self._banks = {}
        stack = lambda key: jnp.stack(
            [jnp.asarray(b[key]) for b in banks]
        )
        f_banks, l_banks, y_banks, masks = (
            stack("features"), stack("logits"), stack("labels"),
            stack("mask"),
        )
        if self.on_banks is not None:
            self.on_banks(self.round_idx, f_banks, l_banks, y_banks)
        (self.server_vars, self.server_opt_state,
         self.server_logits) = self._server_phase(
            self.server_vars, self.server_opt_state,
            f_banks, l_banks, y_banks, masks,
            jnp.asarray(self.round_idx, jnp.int32),
        )
        self.round_idx += 1
        if self.round_idx >= self.sim.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
        else:
            self.start_round()


# ---------------------------------------------------------------------------
# Vertical FL
# ---------------------------------------------------------------------------


class VFLGuestActor(ServerManager):
    """Label owner (reference ``guest_trainer.py``): sums the parties'
    logit components, computes the common gradient
    d BCE / d component (identical for every party), trains its own
    slice, returns the gradient to the hosts."""

    def __init__(
        self,
        size: int,
        transport: BaseTransport,
        sim,  # VFLSim — source of truth for batching and party math
        party_vars: Pytree,
        opt_states,
        epochs: int,
    ):
        super().__init__(0, size, transport)
        self.sim = sim
        self.party_vars = party_vars  # party 0 (guest) variables
        self.opt_states = opt_states
        self.epochs = epochs
        self.epoch = 0
        self.step_idx = 0  # global step counter (sim's state.step)
        self.losses: list[float] = []
        self.epoch_losses: list[float] = []
        self._components: dict[int, np.ndarray] = {}
        self._perm = None
        self._pos = 0
        self.done = threading.Event()
        self.register_message_receive_handler(
            MSG_VFL_COMPONENT, self._on_component
        )

        def guest_step(pv, os_, xb, yb, host_sum):
            """Guest's half of the sim's joint step: its component is
            differentiated jointly with the BCE of (its component +
            received host components); the cotangent of the host sum IS
            the common gradient the hosts need (sim: autodiff through
            the sum gives every party that same dL/dtotal)."""
            lv, dv = pv
            lo, do = os_

            def loss_fn(lp, dp, host_sum):
                c = self.sim._party_logit(
                    ({**lv, "params": lp}, {**dv, "params": dp}), 0, xb,
                    True,
                )
                bce = optax.sigmoid_binary_cross_entropy(
                    c + host_sum, yb
                )
                return jnp.mean(bce)

            loss, (lg, dg, d_host) = jax.value_and_grad(
                loss_fn, argnums=(0, 1, 2)
            )(lv["params"], dv["params"], host_sum)
            lu, new_lo = self.sim.opt.update(lg, lo, lv["params"])
            du, new_do = self.sim.opt.update(dg, do, dv["params"])
            new_pv = (
                {**lv, "params": optax.apply_updates(lv["params"], lu)},
                {**dv, "params": optax.apply_updates(dv["params"], du)},
            )
            return new_pv, (new_lo, new_do), d_host, loss

        self._guest_step = jax.jit(guest_step)

    def start_epoch(self) -> None:
        n = self.sim.x_train.shape[0]
        rng = np.random.default_rng(int(self.step_idx))
        self._perm = rng.permutation(n)
        self._pos = 0
        self.epoch_losses = []
        if n // self.sim.batch_size == 0:
            # mirror VFLSim.run_epoch exactly: zero full batches means
            # zero steps and loss 0.0 — no ragged-batch update
            self._finish_epoch()
            return
        self._request_step()

    def _request_step(self) -> None:
        bs = self.sim.batch_size
        take = self._perm[self._pos * bs:(self._pos + 1) * bs]
        self._take = take
        self.broadcast(
            MSG_VFL_STEP, lambda r: {"idx": np.asarray(take)}
        )

    def _on_component(self, msg: Message) -> None:
        self._components[msg.sender] = msg.get("component")
        if len(self._components) < self.size - 1:
            return
        host_sum = jnp.sum(
            jnp.stack(
                [
                    jnp.asarray(self._components[r])
                    for r in range(1, self.size)
                ]
            ),
            axis=0,
        )
        self._components = {}
        xb = self.sim._slice(
            self.sim.x_train[self._take], 0
        )
        yb = self.sim.y_train[self._take]
        (self.party_vars, self.opt_states, d_host,
         loss) = self._guest_step(
            self.party_vars, self.opt_states, xb, yb, host_sum
        )
        self.epoch_losses.append(float(loss))
        self.broadcast(
            MSG_VFL_GRAD, lambda r: {"grad": np.asarray(d_host)}
        )
        self.step_idx += 1
        self._pos += 1
        if self._pos < len(self._perm) // self.sim.batch_size:
            self._request_step()
            return
        self._finish_epoch()

    def _finish_epoch(self) -> None:
        self.losses.append(
            float(np.mean(self.epoch_losses)) if self.epoch_losses
            else 0.0
        )
        self.epoch += 1
        if self.epoch >= self.epochs:
            self.done.set()
            self.finish_all()
        else:
            self.start_epoch()


class VFLHostActor(ClientManager):
    """Feature-slice owner without labels (reference
    ``party_models.py``): answers batch requests with its logit
    component, applies the guest's common gradient via local vjp."""

    def __init__(
        self,
        rank: int,
        size: int,
        transport: BaseTransport,
        sim,  # VFLSim
        party_vars: Pytree,
        opt_states,
    ):
        super().__init__(rank, size, transport)
        self.sim = sim
        self.party = rank  # sim party index (guest is 0)
        self.party_vars = party_vars
        self.opt_states = opt_states
        self._xb = None
        self.register_message_receive_handler(
            MSG_VFL_STEP, self._on_step
        )
        self.register_message_receive_handler(
            MSG_VFL_GRAD, self._on_grad
        )

        def component(pv, xb):
            return self.sim._party_logit(pv, self.party, xb, True)

        def apply_grad(pv, os_, xb, d_comp):
            lv, dv = pv
            lo, do = os_
            _, vjp_fn = jax.vjp(
                lambda lp, dp: self.sim._party_logit(
                    ({**lv, "params": lp}, {**dv, "params": dp}),
                    self.party, xb, True,
                ),
                lv["params"], dv["params"],
            )
            lg, dg = vjp_fn(d_comp)
            lu, new_lo = self.sim.opt.update(lg, lo, lv["params"])
            du, new_do = self.sim.opt.update(dg, do, dv["params"])
            new_pv = (
                {**lv, "params": optax.apply_updates(lv["params"], lu)},
                {**dv, "params": optax.apply_updates(dv["params"], du)},
            )
            return new_pv, (new_lo, new_do)

        self._component = jax.jit(component)
        self._apply_grad = jax.jit(apply_grad)

    def _on_step(self, msg: Message) -> None:
        take = np.asarray(msg.get("idx"))
        self._xb = self.sim._slice(self.sim.x_train[take], self.party)
        comp = self._component(self.party_vars, self._xb)
        self.send_message(
            Message(
                MSG_VFL_COMPONENT, self.rank, 0,
                {"component": np.asarray(comp)},
            )
        )

    def _on_grad(self, msg: Message) -> None:
        d_comp = jnp.asarray(msg.get("grad"))
        self.party_vars, self.opt_states = self._apply_grad(
            self.party_vars, self.opt_states, self._xb, d_comp
        )


# ---------------------------------------------------------------------------
# Launchers: wire an actor set over a backend and run to completion
# ---------------------------------------------------------------------------


def _run_actors(server: Manager, clients: Sequence[Manager],
                kickoff: Callable[[], None], timeout: float = 600.0):
    threads = [
        threading.Thread(target=c.run, daemon=True) for c in clients
    ]
    for t in threads:
        t.start()
    server.transport.start()
    kickoff()
    server.run()
    for t in threads:
        t.join(timeout=timeout)


def run_splitnn_distributed(
    client_model, server_model, data: FederatedData,
    cfg: ExperimentConfig, transports: Sequence[BaseTransport],
    init_state,
):
    """Run SplitNN actors (1 server + N clients) over started-or-startable
    ``transports`` (rank order), starting from a ``SplitNNSim`` init
    state; returns (server actor, final client vars list)."""
    size = len(transports)
    server = SplitNNServerActor(
        size, transports[0], server_model,
        init_state.server_vars, cfg,
    )
    clients = [
        SplitNNClientActor(
            r, size, transports[r], client_model,
            jax.tree.map(lambda s: s[r - 1], init_state.client_stack),
            data, cfg,
        )
        for r in range(1, size)
    ]
    for t in transports[1:]:
        t.start()
    _run_actors(server, clients, server.start_round)
    return server, [c.c_vars for c in clients]


def run_gkt_distributed(
    sim, transports: Sequence[BaseTransport], init_state, on_banks=None
):
    """Run FedGKT actors from a ``FedGKTSim`` (used for its jitted phase
    math and config) and its init state; returns the server actor.
    ``on_banks`` (optional) is installed as the server's per-round bank
    capture hook."""
    size = len(transports)
    server = GKTServerActor(
        size, transports[0], sim, init_state.server_vars
    )
    server.on_banks = on_banks
    clients = [
        GKTClientActor(
            r, size, transports[r], sim,
            jax.tree.map(lambda s: s[r - 1], init_state.client_stack),
        )
        for r in range(1, size)
    ]
    for t in transports[1:]:
        t.start()
    _run_actors(server, clients, server.start_round)
    return server, [c.c_vars for c in clients]


def run_vfl_distributed(
    sim, transports: Sequence[BaseTransport], init_state, epochs: int
):
    """Run vertical-FL actors from a ``VFLSim`` init state: guest =
    party 0 (rank 0), hosts = parties 1.. (ranks 1..). Returns
    (guest actor, host actors)."""
    size = len(transports)
    guest = VFLGuestActor(
        size, transports[0], sim,
        init_state.party_vars[0], init_state.opt_states[0], epochs,
    )
    hosts = [
        VFLHostActor(
            r, size, transports[r], sim,
            init_state.party_vars[r], init_state.opt_states[r],
        )
        for r in range(1, size)
    ]
    for t in transports[1:]:
        t.start()
    _run_actors(guest, hosts, guest.start_epoch)
    return guest, hosts

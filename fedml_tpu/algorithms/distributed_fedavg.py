"""Actor-based distributed FedAvg over the message-passing runtime.

Redesign of ``fedml_api/distributed/fedavg`` (5-file pattern:
``FedAvgAPI.py`` init + rank split, ``FedAVGAggregator``, ``FedAVGTrainer``,
``FedAvgServerManager``/``FedAvgClientManager``, ``message_define.py``).
The actor shell is for TRUE cross-process deployments (multi-host DCN);
compute inside each actor is the same jitted local update as the compiled
simulator, so the math is identical to :class:`FedAvgSim` by construction.

Topology (reference ``FedAvgAPI.py:36-66``): rank 0 = server, rank i>=1
trains the partition of client ``cohort[i-1]`` each round.
"""

from __future__ import annotations

import threading
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import tree as T
from fedml_tpu.core.manager import ClientManager, ServerManager
from fedml_tpu.core.message import (
    KEY_CLIENT_INDEX,
    KEY_MODEL_PARAMS,
    KEY_NUM_SAMPLES,
    KEY_ROUND,
    MSG_TYPE_C2S_RESULT,
    MSG_TYPE_S2C_SYNC_MODEL,
    Message,
)
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.data.federated import FederatedData, arrays_and_batch
from fedml_tpu.algorithms.base import build_local_update, make_task
from fedml_tpu.models.base import FedModel


class FedAvgServerActor(ServerManager):
    """Rank-0 aggregator (reference ``FedAVGServerManager`` +
    ``FedAVGAggregator``)."""

    def __init__(
        self,
        size: int,
        transport: BaseTransport,
        model: FedModel,
        cfg: ExperimentConfig,
        num_clients: int,
        on_round_done: Callable[[int, dict], None] | None = None,
    ):
        super().__init__(0, size, transport)
        self.cfg = cfg
        self.num_clients = num_clients
        self.model = model
        self.variables = model.init(jax.random.key(cfg.seed))
        self.round_idx = 0
        self._results: dict[int, tuple[dict, float]] = {}
        self._lock = threading.Lock()
        self.on_round_done = on_round_done
        self.done = threading.Event()
        self.register_message_receive_handler(
            MSG_TYPE_C2S_RESULT, self._handle_result
        )

    def _sample(self) -> np.ndarray:
        """Seeded cohort sampling (reference ``client_sampling``,
        ``FedAVGAggregator.py:90-98``). In the distributed path the cohort
        size is the worker count, as in the reference (one MPI rank per
        sampled client, ``FedAvgAPI.py:36-66``); if there are more workers
        than clients the assignment wraps so every worker gets a client."""
        n_workers = self.size - 1
        if n_workers >= self.num_clients:
            return np.arange(self.num_clients)
        rng = np.random.default_rng(self.round_idx)
        return rng.choice(self.num_clients, n_workers, replace=False)

    def start_round(self) -> None:
        cohort = self._sample()
        host_vars = jax.tree.map(np.asarray, self.variables)
        self.broadcast(
            MSG_TYPE_S2C_SYNC_MODEL,
            lambda r: {
                KEY_MODEL_PARAMS: host_vars,
                KEY_CLIENT_INDEX: int(cohort[(r - 1) % len(cohort)]),
                KEY_ROUND: self.round_idx,
            },
        )

    def _handle_result(self, msg: Message) -> None:
        with self._lock:
            self._results[msg.sender] = (
                msg.get(KEY_MODEL_PARAMS),
                float(msg.get(KEY_NUM_SAMPLES)),
            )
            if len(self._results) < self.size - 1:
                return
            results = self._results
            self._results = {}
        # all received: aggregate (reference
        # handle_message_receive_model_from_client, FedAvgServerManager.py:45-82)
        stacked = T.tree_stack([v for v, _ in results.values()])
        weights = jnp.asarray([n for _, n in results.values()])
        self.variables = T.tree_weighted_mean(stacked, weights)
        self.round_idx += 1
        if self.on_round_done is not None:
            self.on_round_done(self.round_idx, {"num_results": len(results)})
        if self.round_idx >= self.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
        else:
            self.start_round()


class FedAvgClientActor(ClientManager):
    """Rank>=1 worker (reference ``FedAVGClientManager`` +
    ``FedAVGTrainer``)."""

    def __init__(
        self,
        rank: int,
        size: int,
        transport: BaseTransport,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        super().__init__(rank, size, transport)
        self.cfg = cfg
        self.model = model
        self.arrays, batch = arrays_and_batch(data, cfg.data)
        max_n = self.arrays.max_client_samples
        task = make_task(data.task)
        self._local_update = jax.jit(
            build_local_update(model, task, cfg.train, batch, max_n)
        )
        self.root_key = jax.random.key(cfg.seed)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self._handle_sync
        )

    def _handle_sync(self, msg: Message) -> None:
        client_idx = int(msg.get(KEY_CLIENT_INDEX))
        round_idx = int(msg.get(KEY_ROUND))
        variables = jax.tree.map(jnp.asarray, msg.get(KEY_MODEL_PARAMS))
        rng = jax.random.fold_in(
            jax.random.fold_in(self.root_key, round_idx), client_idx
        )
        new_vars, n_k, _ = self._local_update(
            variables,
            self.arrays.idx[client_idx],
            self.arrays.mask[client_idx],
            self.arrays.x,
            self.arrays.y,
            rng,
        )
        self.send_message(
            Message(
                MSG_TYPE_C2S_RESULT,
                self.rank,
                0,
                {
                    KEY_MODEL_PARAMS: jax.tree.map(np.asarray, new_vars),
                    KEY_NUM_SAMPLES: float(n_k),
                },
            )
        )

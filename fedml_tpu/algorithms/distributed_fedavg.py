"""Actor-based distributed FedAvg over the message-passing runtime.

Redesign of ``fedml_api/distributed/fedavg`` (5-file pattern:
``FedAvgAPI.py`` init + rank split, ``FedAVGAggregator``, ``FedAVGTrainer``,
``FedAvgServerManager``/``FedAvgClientManager``, ``message_define.py``).
The actor shell is for TRUE cross-process deployments (multi-host DCN);
compute inside each actor is the same jitted local update as the compiled
simulator, so the math is identical to :class:`FedAvgSim` by construction.

Topology (reference ``FedAvgAPI.py:36-66``): rank 0 = server, rank i>=1
trains the partition of client ``cohort[i-1]`` each round.
"""

from __future__ import annotations

import threading
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import ExperimentConfig
from fedml_tpu.core import tree as T
from fedml_tpu.core.manager import ClientManager, ServerManager
from fedml_tpu.core.message import (
    KEY_CLIENT_INDEX,
    KEY_MODEL_PARAMS,
    KEY_NUM_SAMPLES,
    KEY_ROUND,
    MSG_TYPE_C2S_RESULT,
    MSG_TYPE_S2C_SYNC_MODEL,
    Message,
)
from fedml_tpu.core.transport.base import BaseTransport
from fedml_tpu.data.federated import FederatedData, arrays_and_batch
from fedml_tpu.algorithms.base import build_local_update, make_task
from fedml_tpu.algorithms.fedavg import (
    ServerState,
    local_reducer,
    make_server_optimizer,
    server_update,
)
from fedml_tpu.core import random as RND
from fedml_tpu.models.base import FedModel


class FedAvgServerActor(ServerManager):
    """Rank-0 aggregator (reference ``FedAVGServerManager`` +
    ``FedAVGAggregator``)."""

    def __init__(
        self,
        size: int,
        transport: BaseTransport,
        model: FedModel,
        cfg: ExperimentConfig,
        num_clients: int,
        on_round_done: Callable[[int, dict], None] | None = None,
        initial_variables=None,
        steps_per_epoch: int | None = None,
        batch_size: int | None = None,
        data: FederatedData | None = None,
    ):
        super().__init__(0, size, transport)
        self.cfg = cfg
        self.num_clients = num_clients
        self.model = model
        variables = (
            initial_variables
            if initial_variables is not None
            else model.init(jax.random.key(cfg.seed))
        )
        opt = make_server_optimizer(
            cfg.fed.server_optimizer, cfg.fed.server_lr,
            cfg.fed.server_momentum,
        )
        # full ServerState so EVERY server rule the compiled sim supports
        # (FedOpt adam/adagrad/yogi pseudo-gradients, FedNova
        # tau-normalization + gmf momentum, robust clip/noise/median/
        # trimmed-mean) runs over the actor runtime too — the transport
        # zoo's second consumer (ref fedopt/FedOptAggregator.py)
        self.state = ServerState(
            variables=variables,
            opt_state=opt.init(variables["params"]),
            momentum=jax.tree.map(jnp.zeros_like, variables["params"]),
            round=jnp.asarray(0, jnp.int32),
        )
        # FedNova's tau normalization needs the RESOLVED batch size and
        # steps_per_epoch (arrays_and_batch handles full-batch mode and
        # batch > max_n clamping) — pass `data` or the explicit values;
        # raw cfg.data.batch_size would silently skew tau.
        if data is not None and (steps_per_epoch is None
                                 or batch_size is None):
            arrays, rbatch = arrays_and_batch(data, cfg.data)
            batch_size = rbatch if batch_size is None else batch_size
            if steps_per_epoch is None:
                steps_per_epoch = arrays.max_client_samples // rbatch
        if cfg.fed.algorithm == "fednova" and (
            steps_per_epoch is None or batch_size is None
        ):
            raise ValueError(
                "fednova server rule needs BOTH steps_per_epoch and "
                "batch_size (the RESOLVED values — full-batch mode and "
                "batch > max_n clamping change them): pass data= to "
                "resolve automatically, or both values explicitly"
            )
        # explicit 0 is a caller bug (would silently skew FedNova tau if
        # coerced to 1) — reject rather than repair
        if steps_per_epoch is not None and steps_per_epoch < 1:
            raise ValueError(
                f"steps_per_epoch must be >= 1, got {steps_per_epoch}"
            )
        if batch_size is not None and batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.steps_per_epoch = 1 if steps_per_epoch is None else steps_per_epoch
        self.batch_size = cfg.data.batch_size if batch_size is None else batch_size
        self.root_key = jax.random.key(cfg.seed)
        self.round_idx = 0
        self._results: dict[int, tuple[dict, float]] = {}
        self._lock = threading.Lock()
        self.on_round_done = on_round_done
        self.done = threading.Event()
        self.register_message_receive_handler(
            MSG_TYPE_C2S_RESULT, self._handle_result
        )

    @property
    def variables(self):
        return self.state.variables

    def _sample(self) -> np.ndarray:
        """Seeded cohort sampling (reference ``client_sampling``,
        ``FedAVGAggregator.py:90-98``). In the distributed path the cohort
        size is the worker count, as in the reference (one MPI rank per
        sampled client, ``FedAvgAPI.py:36-66``); if there are more workers
        than clients the assignment wraps so every worker gets a client."""
        n_workers = self.size - 1
        if n_workers >= self.num_clients:
            return np.arange(self.num_clients)
        rng = np.random.default_rng(self.round_idx)
        return rng.choice(self.num_clients, n_workers, replace=False)

    def start_round(self) -> None:
        cohort = self._sample()
        host_vars = jax.tree.map(np.asarray, self.variables)
        self.broadcast(
            MSG_TYPE_S2C_SYNC_MODEL,
            lambda r: {
                KEY_MODEL_PARAMS: host_vars,
                KEY_CLIENT_INDEX: int(cohort[(r - 1) % len(cohort)]),
                KEY_ROUND: self.round_idx,
            },
        )

    def _handle_result(self, msg: Message) -> None:
        with self._lock:
            self._results[msg.sender] = (
                msg.get(KEY_MODEL_PARAMS),
                float(msg.get(KEY_NUM_SAMPLES)),
            )
            if len(self._results) < self.size - 1:
                return
            results = self._results
            self._results = {}
        # all received: aggregate through the SAME server_update as the
        # compiled sim (reference handle_message_receive_model_from_client,
        # FedAvgServerManager.py:45-82 + fedopt/FedOptAggregator.py) — the
        # two paths cannot drift
        stacked = T.tree_stack(
            [results[r][0] for r in sorted(results)]
        )
        weights = jnp.asarray([results[r][1] for r in sorted(results)])
        rkey = RND.round_key(self.root_key, self.state.round)
        self.state = server_update(
            self.cfg.fed,
            self.cfg.train,
            self.steps_per_epoch,
            self.batch_size,
            self.state,
            jax.tree.map(jnp.asarray, stacked),
            weights,
            rkey,
            local_reducer(),
        )
        self.round_idx += 1
        if self.on_round_done is not None:
            self.on_round_done(self.round_idx, {"num_results": len(results)})
        if self.round_idx >= self.cfg.fed.num_rounds:
            self.done.set()
            self.finish_all()
        else:
            self.start_round()


class FedAvgClientActor(ClientManager):
    """Rank>=1 worker (reference ``FedAVGClientManager`` +
    ``FedAVGTrainer``)."""

    def __init__(
        self,
        rank: int,
        size: int,
        transport: BaseTransport,
        model: FedModel,
        data: FederatedData,
        cfg: ExperimentConfig,
    ):
        super().__init__(rank, size, transport)
        self.cfg = cfg
        self.model = model
        self.arrays, batch = arrays_and_batch(data, cfg.data)
        max_n = self.arrays.max_client_samples
        task = make_task(data.task)
        self._local_update = jax.jit(
            build_local_update(model, task, cfg.train, batch, max_n)
        )
        self.root_key = jax.random.key(cfg.seed)
        self.register_message_receive_handler(
            MSG_TYPE_S2C_SYNC_MODEL, self._handle_sync
        )

    def _handle_sync(self, msg: Message) -> None:
        client_idx = int(msg.get(KEY_CLIENT_INDEX))
        round_idx = int(msg.get(KEY_ROUND))
        variables = jax.tree.map(jnp.asarray, msg.get(KEY_MODEL_PARAMS))
        rng = jax.random.fold_in(
            jax.random.fold_in(self.root_key, round_idx), client_idx
        )
        new_vars, n_k, _ = self._local_update(
            variables,
            self.arrays.idx[client_idx],
            self.arrays.mask[client_idx],
            self.arrays.x,
            self.arrays.y,
            rng,
        )
        self.send_message(
            Message(
                MSG_TYPE_C2S_RESULT,
                self.rank,
                0,
                {
                    KEY_MODEL_PARAMS: jax.tree.map(np.asarray, new_vars),
                    KEY_NUM_SAMPLES: float(n_k),
                },
            )
        )
